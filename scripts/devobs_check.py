"""devobs-check — the device-observatory gate (fast CI shape, ~60 s).

Certifies the in-scan telemetry contract on a small fused population so CI
catches a broken aux stream before the expensive ``bench.py --devobs``
acceptance run does:

1. a 64-node :class:`~p2pfl_tpu.population.PopulationEngine` run with
   devobs ON feeds the host ``SKETCHES`` streams (``update_norm``,
   ``train_loss``) and the ``p2pfl_mesh_*`` Prometheus family, and the
   sketch totals are **chunking-invariant** (rounds_per_call 2 vs 4 fold
   the same counts — the aux stream is a property of the schedule, not of
   how the scan is sliced);
2. telemetry is **free where it matters**: the node-0 params hash with
   devobs ON is bit-identical to the hash with devobs OFF (aux rides only
   the scan ys side — the params math never sees it);
3. the NaN tripwire fires within one chunk of a seeded injection, in BOTH
   actions: ``park`` returns a partial result carrying ``.tripped`` and a
   flight-recorder dump path, ``abort`` raises with the engine state still
   parked and readable;
4. doc-shape parity: the fused snapshot exposes every key family a real
   wire ``Observatory.snapshot()`` does (``snapshot_shape_diff`` empty) —
   one document shape for 8 sockets or 100k virtual nodes.

Exit 0 on pass, 1 on failure. ``make devobs-check`` wires it next to the
other plane gates.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _hash0(eng) -> str:
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    return canonical_params_hash(eng.gather_params(0))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.population import PopulationEngine
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.telemetry.export import render_prometheus
    from p2pfl_tpu.telemetry.sketches import SKETCHES

    n, rounds, fraction, seed = 64, 8, 0.125, 1234
    eng_kw = dict(
        cohort_fraction=fraction, seed=seed, samples_per_node=8, hidden=(8,),
    )
    t0 = time.monotonic()
    print(
        f"devobs-check: n={n} rounds={rounds} cohort={fraction:g} "
        f"seed={seed} — telemetry arm...",
        file=sys.stderr,
    )

    # --- arm 1: aux stream feeds sketches + Prometheus, chunking-invariant ---
    counts = {}
    for rpc in (2, 4):
        SKETCHES.reset()
        with Settings.overridden(DEVOBS_ENABLED=True):
            with PopulationEngine(n, **eng_kw) as eng:
                eng.run(rounds, warmup=True, rounds_per_call=rpc)
                hash_on = _hash0(eng)
        un = SKETCHES.get("update_norm", "mesh-sim")
        tl = SKETCHES.get("train_loss", "mesh-sim")
        assert un is not None and un.count > 0, "update_norm sketch empty"
        assert tl is not None and tl.count > 0, "train_loss sketch empty"
        counts[rpc] = (un.count, tl.count)
    assert counts[2] == counts[4], (
        f"aux stream not chunking-invariant: rpc=2 folded {counts[2]}, "
        f"rpc=4 folded {counts[4]}"
    )
    expected = rounds * max(1, int(n * fraction))
    assert counts[2][0] == expected, (
        f"update_norm count {counts[2][0]} != rounds*cohort {expected}"
    )
    prom = render_prometheus(REGISTRY)
    for metric in ("p2pfl_mesh_round", "p2pfl_mesh_train_loss",
                   "p2pfl_mesh_weight_mass", "p2pfl_mesh_chunk_seconds"):
        assert metric in prom, f"{metric} missing from Prometheus exposition"
    print(
        f"devobs-check: sketches chunk-invariant ({counts[2][0]} norms, "
        f"{counts[2][1]} losses), p2pfl_mesh_* exported — off arm...",
        file=sys.stderr,
    )

    # --- arm 2: devobs OFF is bit-identical on the params path ---------------
    SKETCHES.reset()
    with Settings.overridden(DEVOBS_ENABLED=False):
        with PopulationEngine(n, **eng_kw) as eng:
            eng.run(rounds, warmup=True, rounds_per_call=4)
            hash_off = _hash0(eng)
    assert hash_on == hash_off, (
        f"devobs perturbed the params math: on={hash_on} off={hash_off}"
    )
    un_off = SKETCHES.get("update_norm", "mesh-sim")
    assert un_off is None or un_off.count == 0, "devobs OFF still folded sketches"
    print(
        f"devobs-check: on/off hash identical ({hash_on[:18]}...) — "
        "tripwire arms...",
        file=sys.stderr,
    )

    # --- arm 3: NaN tripwire, park then abort --------------------------------
    inject_at, trip_rpc = 3, 2
    SKETCHES.reset()
    with Settings.overridden(
        DEVOBS_ENABLED=True,
        DEVOBS_NAN_INJECT_ROUND=inject_at,
        DEVOBS_TRIP_ACTION="park",
    ):
        with PopulationEngine(n, **eng_kw) as eng:
            res = eng.run(rounds, warmup=True, rounds_per_call=trip_rpc)
    trip = res.tripped
    assert trip is not None and trip["kind"] == "nonfinite", f"no trip: {trip}"
    assert trip["round"] == inject_at, f"trip round {trip['round']} != {inject_at}"
    stop = (inject_at // trip_rpc + 1) * trip_rpc
    assert res.rounds == stop, (
        f"park ran {res.rounds} rounds, expected chunk-boundary stop at {stop}"
    )
    assert trip.get("flightrec") and os.path.exists(trip["flightrec"]), (
        f"flight-recorder dump missing: {trip.get('flightrec')}"
    )

    SKETCHES.reset()
    with Settings.overridden(
        DEVOBS_ENABLED=True,
        DEVOBS_NAN_INJECT_ROUND=inject_at,
        DEVOBS_TRIP_ACTION="abort",
    ):
        with PopulationEngine(n, **eng_kw) as eng:
            try:
                eng.run(rounds, warmup=True, rounds_per_call=trip_rpc)
            except RuntimeError as exc:
                assert "devobs tripwire" in str(exc), f"wrong abort: {exc}"
            else:
                raise AssertionError("abort action did not raise")
            # The abort parks state before raising — it must stay readable.
            assert eng.sim.params_stack is not None, "abort dropped the state"
            _hash0(eng)
    print(
        "devobs-check: NaN trip in-chunk (park stopped at "
        f"round {stop}, abort raised with state parked) — parity arm...",
        file=sys.stderr,
    )

    # --- arm 4: fused snapshot shape == wire observatory shape ---------------
    from p2pfl_tpu.telemetry import digest as digest_mod
    from p2pfl_tpu.telemetry.observatory import Observatory, snapshot_shape_diff
    from p2pfl_tpu.telemetry.sketches import DistinctEstimator, QuantileSketch

    SKETCHES.reset()
    with Settings.overridden(DEVOBS_ENABLED=True):
        with PopulationEngine(n, **eng_kw) as eng:
            res = eng.run(rounds, warmup=True, rounds_per_call=4)
            fused = eng.snapshot(res, top_n=8)
    sk = QuantileSketch(rel_err=0.02)
    for lag in (0, 0, 1, 2):
        sk.add(float(lag))
    est = DistinctEstimator()
    est.add("mem://a")
    wire_obs = Observatory("mem://devobs-check")
    wire_obs.ingest(
        digest_mod.HealthDigest(
            node="mem://peer", ts=time.time(), round=3, stage="RoundStage",
            mode="sync", steps_per_s=25.0,
            sketches={"staleness": sk.to_wire(), "__distinct__": est.to_wire()},
        )
    )
    missing = snapshot_shape_diff(fused, wire_obs.snapshot())
    assert not missing, f"fused snapshot missing wire key families: {missing}"
    assert fused.get("devobs", {}).get("train_loss") is not None, (
        "fused snapshot devobs block lost the in-scan loss"
    )

    print(
        f"devobs-check: PASS in {time.monotonic() - t0:.1f}s — sketches "
        "chunk-invariant, on/off hash identical, tripwires fire in-chunk "
        "(park + abort), fused/wire doc shapes at parity",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
