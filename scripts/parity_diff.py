"""parity_diff — align two trajectory ledgers; localize the first divergence.

The sim↔real parity gate's comparator: given two
``ledger_<node>.jsonl`` dumps (telemetry/ledger.py — one from the real wire
federation, one from the fused mesh, or any two runs of one backend),

    python scripts/parity_diff.py A.jsonl B.jsonl [--out artifacts/parity_diff.json]

aligns their canonical event streams by ``(round, event kind, sender)`` and
compares field-wise, reporting the FIRST divergent event with surrounding
context. Exit codes: ``0`` parity OK, ``1`` DIVERGED, ``2`` usage/unreadable.

What is compared (per kind; unknown fields are ignored so schema growth
stays forward-compatible):

* ``round_open`` / ``window_open`` — the member set,
* ``contribution_folded`` — sender, lag, num_samples,
* ``aggregate_committed`` — contributors, num_samples, and the content
  ``hash`` bit-for-bit WHEN BOTH SIDES CARRY ONE (a missing hash — e.g. a
  fused chunk's intermediate round — is reported as a note, not a diff),
* ``round_close`` / ``window_close`` — presence.

Environment/defense kinds (``chaos_fault``, ``admission_rejected``,
``membership``) legitimately differ between backends — the fused mesh has
no wire to drop frames from — and are compared only under ``--all-kinds``.

Hostile-input tolerance (exercised by tests/test_ledger.py): truncated
files stop at the torn line with a note, events of unknown schema versions
are skipped with a note, non-JSON lines and missing fields never raise.
Stdlib-only, like ``fed_top`` — runs anywhere the repo does.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

#: schema versions this differ knows how to compare.
KNOWN_VERSIONS = (1,)

#: canonical within-round kind order — mirror of telemetry/ledger.KIND_RANK
#: (kept in sync by tests; duplicated so this script stays stdlib-only and
#: importable without the package).
KIND_RANK = {
    "round_open": 0,
    "window_open": 0,
    "chaos_fault": 1,
    "membership": 2,
    "admission_rejected": 3,
    "privacy_masked": 3,
    "contribution_folded": 4,
    "aggregate_committed": 5,
    "window_close": 6,
    "round_close": 6,
}

TRAJECTORY_KINDS = (
    "round_open",
    "window_open",
    "contribution_folded",
    "aggregate_committed",
    "window_close",
    "round_close",
)

#: fields compared per kind (hash is special-cased: both sides must carry it).
COMPARED_FIELDS = {
    "round_open": ("members",),
    "window_open": ("members",),
    "contribution_folded": ("sender", "lag", "num_samples"),
    "aggregate_committed": ("contributors", "num_samples"),
    "round_close": (),
    "window_close": (),
    "membership": ("event", "peer"),
    "chaos_fault": ("fault", "peer"),
    "admission_rejected": ("sender", "reason"),
}


def read_ledger(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]], List[str]]:
    """Tolerant JSONL reader: returns ``(header, events, notes)``. A torn /
    non-JSON line ends the read with a note (crash-truncated dumps are a
    first-class input); unknown event versions are skipped with a note."""
    notes: List[str] = []
    header: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    with open(path, "r", errors="replace") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                notes.append(
                    f"{os.path.basename(path)}: truncated/corrupt at line "
                    f"{lineno} — stopping there"
                )
                break
            if not isinstance(doc, dict):
                notes.append(
                    f"{os.path.basename(path)}: line {lineno} is not an "
                    "event object — skipped"
                )
                continue
            if lineno == 1 and doc.get("ledger") == "trajectory":
                header = doc
                continue
            v = doc.get("v")
            if v not in KNOWN_VERSIONS:
                notes.append(
                    f"{os.path.basename(path)}: line {lineno} has unknown "
                    f"event version {v!r} — skipped"
                )
                continue
            if not isinstance(doc.get("kind"), str):
                notes.append(
                    f"{os.path.basename(path)}: line {lineno} has no kind — "
                    "skipped"
                )
                continue
            events.append(doc)
    return header, events, notes


def _align_key(ev: Dict[str, Any]) -> Tuple:
    rnd = ev.get("round")
    return (
        rnd if isinstance(rnd, (int, float)) else -1,
        KIND_RANK.get(ev.get("kind"), 9),
        str(ev.get("kind", "")),
        str(ev.get("sender", ev.get("peer", ""))),
    )


def _event_brief(ev: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if ev is None:
        return None
    keep = ("kind", "round", "sender", "peer", "members", "contributors",
            "num_samples", "lag", "hash", "event", "reason", "fault")
    return {k: ev[k] for k in keep if k in ev}


def compare_ledgers(
    events_a: List[Dict[str, Any]],
    events_b: List[Dict[str, Any]],
    kinds: Tuple[str, ...] = TRAJECTORY_KINDS,
    context: int = 3,
) -> Dict[str, Any]:
    """Pure comparison (importable by tests / bench): align by
    ``(round, kind, sender)`` and report the first divergence."""
    a = sorted((e for e in events_a if e.get("kind") in kinds), key=_align_key)
    b = sorted((e for e in events_b if e.get("kind") in kinds), key=_align_key)
    notes: List[str] = []
    first: Optional[Dict[str, Any]] = None
    compared = 0
    hashes_compared = 0

    for i in range(max(len(a), len(b))):
        ea = a[i] if i < len(a) else None
        eb = b[i] if i < len(b) else None
        problem: Optional[str] = None
        if ea is None or eb is None:
            missing = "A" if ea is None else "B"
            problem = f"event present in one ledger only (missing in {missing})"
        elif _align_key(ea) != _align_key(eb):
            problem = "alignment mismatch (round/kind/sender differ)"
        else:
            kind = ea["kind"]
            for field in COMPARED_FIELDS.get(kind, ()):
                if ea.get(field) != eb.get(field):
                    problem = (
                        f"field {field!r} differs: "
                        f"{ea.get(field)!r} != {eb.get(field)!r}"
                    )
                    break
            if problem is None and kind == "aggregate_committed":
                ha, hb = ea.get("hash"), eb.get("hash")
                if ha is not None and hb is not None:
                    hashes_compared += 1
                    if ha != hb:
                        problem = f"aggregate hash differs: {ha} != {hb}"
                elif ha is None and hb is None:
                    notes.append(
                        f"round {ea.get('round')}: neither commit carries a "
                        "hash — values not certified"
                    )
                else:
                    notes.append(
                        f"round {ea.get('round')}: hash present on one side "
                        "only — not compared"
                    )
        if problem is not None:
            lo = max(0, i - context)
            first = {
                "index": i,
                "problem": problem,
                "a": _event_brief(ea),
                "b": _event_brief(eb),
                "context_a": [_event_brief(e) for e in a[lo: i + context + 1]],
                "context_b": [_event_brief(e) for e in b[lo: i + context + 1]],
            }
            break
        compared += 1

    return {
        "status": "OK" if first is None else "DIVERGED",
        "compared_events": compared,
        "hashes_compared": hashes_compared,
        "events_a": len(a),
        "events_b": len(b),
        "kinds": list(kinds),
        "first_divergence": first,
        "notes": notes,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="align two trajectory ledgers; localize the first divergence"
    )
    ap.add_argument("ledger_a")
    ap.add_argument("ledger_b")
    ap.add_argument(
        "--all-kinds", action="store_true",
        help="also compare environment/defense events (chaos_fault, "
        "admission_rejected, membership)",
    )
    ap.add_argument(
        "--context", type=int, default=3,
        help="events of context around the first divergence (default 3)",
    )
    ap.add_argument(
        "--out", default=None,
        help="write the JSON report here (e.g. artifacts/parity_diff.json)",
    )
    args = ap.parse_args(argv)

    try:
        ha, ea, na = read_ledger(args.ledger_a)
        hb, eb, nb = read_ledger(args.ledger_b)
    except OSError as e:
        print(f"parity_diff: cannot read inputs: {e}", file=sys.stderr)
        return 2

    kinds = TRAJECTORY_KINDS
    if args.all_kinds:
        kinds = tuple(KIND_RANK)
    report = compare_ledgers(ea, eb, kinds=kinds, context=args.context)
    report["ledger_a"] = {"path": args.ledger_a, "header": ha}
    report["ledger_b"] = {"path": args.ledger_b, "header": hb}
    report["notes"] = na + nb + report["notes"]
    if ha.get("run_id") and hb.get("run_id") and ha["run_id"] != hb["run_id"]:
        report["notes"].append(
            f"run ids differ: {ha['run_id']!r} vs {hb['run_id']!r} — "
            "comparing different scenarios?"
        )

    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = f"{args.out}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        os.replace(tmp, args.out)

    fd = report["first_divergence"]
    if fd is None:
        print(
            f"parity OK: {report['compared_events']} events aligned, "
            f"{report['hashes_compared']} aggregate hashes bit-exact",
            file=sys.stderr,
        )
    else:
        print(
            f"parity DIVERGED at event {fd['index']}: {fd['problem']}\n"
            f"  a: {json.dumps(fd['a'])}\n  b: {json.dumps(fd['b'])}",
            file=sys.stderr,
        )
    for note in report["notes"]:
        print(f"  note: {note}", file=sys.stderr)
    print(json.dumps({k: report[k] for k in (
        "status", "compared_events", "hashes_compared", "notes"
    )}))
    return 0 if fd is None else 1


if __name__ == "__main__":
    sys.exit(main())
