"""CI gate: 3-node in-memory federation with write-ahead journals — one node
is killed mid-round, then RESUMED from its journal as the same address; the
resumed identity must re-enter the stage machine, run real training rounds,
and the whole federation (resumed node included) must finish within the wall
budget. Fast, CPU-only, tier-1-safe — invoked by ``make recovery-check``.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

ROUNDS = 4
#: Wall budget for the whole learning run including the kill + resume.
#: Generous for a loaded 1-core CI box, far below what timeout-burning
#: (rounds x vote/aggregation deadlines) would need.
WALL_BUDGET_S = 120.0


def main() -> int:
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.management.checkpoint import NodeJournal, attach_node_journal
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3  # full committee: the victim is always a trainer
    REGISTRY.reset()

    n = 3
    data = synthetic_mnist(n_train=128 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
    tmp = tempfile.mkdtemp(prefix="recovery-check-")
    journals = [NodeJournal(os.path.join(tmp, f"j{i}")) for i in range(n)]
    for nd, journal in zip(nodes, journals):
        attach_node_journal(nd, journal)
        nd.start()
    try:
        for i in range(1, n):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, n - 1, wait=15)

        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=ROUNDS, epochs=1)

        victim = nodes[2]
        victim_addr = victim.addr
        # Kill only after the victim's first snapshot is durable.
        deadline = time.time() + 45
        while time.time() < deadline and not journals[2].all_steps():
            time.sleep(0.05)
        if not journals[2].all_steps():
            print("FAIL: victim never journaled a round", file=sys.stderr)
            return 1
        victim.crash()
        journals[2].wait()
        print(f"killed {victim_addr} mid-round", file=sys.stderr)

        resumed = Node.resume(mlp_model(seed=99), parts[2], journals[2], batch_size=32)
        if resumed.addr != victim_addr:
            print(
                f"FAIL: resumed as {resumed.addr}, journal identity was "
                f"{victim_addr}", file=sys.stderr,
            )
            return 1
        resumed.start()
        resumed.resume_learning()
        nodes[2] = resumed
        print(
            f"resumed {resumed.addr} from its journal at round "
            f"{resumed.state.round}", file=sys.stderr,
        )

        finish_deadline = t0 + WALL_BUDGET_S
        while time.monotonic() < finish_deadline:
            if all(
                not nd.learning_in_progress() and nd.learning_workflow is not None
                for nd in nodes
            ):
                break
            time.sleep(0.2)
        else:
            print(
                f"FAIL: federation did not finish within {WALL_BUDGET_S:.0f}s "
                f"(stages: {({nd.addr: nd.state.current_stage for nd in nodes})})",
                file=sys.stderr,
            )
            return 1
        elapsed = time.monotonic() - t0

        history = resumed.learning_workflow.history
        if history[:1] != ["ResumeStage"]:
            print(f"FAIL: resumed node did not enter via ResumeStage: {history[:3]}",
                  file=sys.stderr)
            return 1
        if history.count("RoundFinishedStage") < 1 or history.count("TrainStage") < 1:
            print(
                f"FAIL: resumed node never trained/finished a round: {history}",
                file=sys.stderr,
            )
            return 1
        for nd in nodes[:2]:
            if nd.learning_workflow.history.count("RoundFinishedStage") != ROUNDS:
                print(
                    f"FAIL: {nd.addr} finished "
                    f"{nd.learning_workflow.history.count('RoundFinishedStage')}"
                    f"/{ROUNDS} rounds", file=sys.stderr,
                )
                return 1
        resumes = REGISTRY.get("p2pfl_recovery_resumes_total")
        n_resumes = sum(c.value for _, c in resumes.samples()) if resumes else 0
        if n_resumes < 1:
            print("FAIL: p2pfl_recovery_resumes_total not incremented", file=sys.stderr)
            return 1
    finally:
        for nd in nodes:
            nd.stop()
        for journal in journals:
            try:
                journal.close()
            except Exception:  # noqa: BLE001
                pass
        InMemoryRegistry.reset()

    print(
        f"recovery-check OK: {victim_addr} crashed mid-round, resumed from its "
        f"journal as itself, trained "
        f"{history.count('TrainStage')} round(s) post-resume; federation "
        f"finished {ROUNDS} rounds in {elapsed:.1f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
