"""asyncpop-check — the async-window population gate (fast CI shape, ~60 s).

Certifies the FedBuff window contract on a small fused population so CI
catches a broken scheduler or fold before the expensive
``bench.py --asyncpop`` acceptance run does:

1. a 32-node :class:`~p2pfl_tpu.population.AsyncPopulationEngine` with a
   seeded slow tier ``(1,1,2,5)`` closes every window by FILL (the
   stall-patience backpressure keeps the stream flowing), fold lag stays
   within ``ASYNCPOP_MAX_LAG``, and the window stream is replay-identical
   when driven in chunks (3 + 5 windows vs one 8-window call — same global
   params hash);
2. the 10x flash-crowd arrival trace sustains throughput: contributions
   keep folding through the spike-and-trough cycle, no unbounded pending
   queue, staleness bounded by construction;
3. wire-vs-fused async parity at n=4: the REAL
   :class:`~p2pfl_tpu.learning.aggregators.async_buffer.AsyncBufferedAggregator`
   replaying the same compiled window stream produces a ledger that aligns
   event-for-event with the fused engine's — every aggregate hash
   bit-exact (``scripts/parity_diff.py`` is the comparator).

Exit 0 on pass, 1 on failure. ``make asyncpop-check`` wires it next to
``population-check``.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.population import AsyncPopulationEngine, wire_window_replay
    from p2pfl_tpu.telemetry.ledger import LEDGERS, canonical_params_hash

    t0 = time.monotonic()
    n, windows, seed = 32, 8, 1234
    tiers = (1.0, 1.0, 2.0, 5.0)
    eng_kw = dict(
        cohort_fraction=0.5, seed=seed, samples_per_node=8, feature_dim=8,
        num_classes=4, hidden=(8,), batch_size=4, speed_tiers=tiers,
    )
    print(
        f"asyncpop-check: n={n} windows={windows} tiers={tiers} seed={seed} "
        "— slow-tier window arm...",
        file=sys.stderr,
    )
    with AsyncPopulationEngine(n, **eng_kw) as eng:
        res = eng.run(windows, eval_every=windows)
        hash_single = canonical_params_hash(eng.global_params())
        sched = res.schedule
    max_lag = int(sched.lag[sched.present].max()) if sched.present.any() else 0
    if not (res.close_codes == 0).all():
        print(
            f"FAIL: windows closed by {res.close_codes.tolist()} under the "
            "slow tier — expected every close by FILL (code 0)",
            file=sys.stderr,
        )
        return 1
    if max_lag > int(Settings.ASYNCPOP_MAX_LAG):
        print(
            f"FAIL: fold lag {max_lag} > ASYNCPOP_MAX_LAG "
            f"{Settings.ASYNCPOP_MAX_LAG}",
            file=sys.stderr,
        )
        return 1
    with AsyncPopulationEngine(n, **eng_kw) as eng2:
        eng2.run(3, eval_every=10)
        eng2.run(5, eval_every=10)
        hash_chunked = canonical_params_hash(eng2.global_params())
    if hash_chunked != hash_single:
        print(
            f"FAIL: chunked window stream hash {hash_chunked[:16]}… != "
            f"single-call {hash_single[:16]}…",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: {windows} slow-tier windows all closed by fill, max lag "
        f"{max_lag} <= {Settings.ASYNCPOP_MAX_LAG}, chunked replay "
        "bit-identical",
        file=sys.stderr,
    )

    # Flash crowd: the 10x spike must not stall the stream or grow the
    # pending queue past the stall-patience backpressure bound.
    period, fc_windows = 6, 18
    with AsyncPopulationEngine(
        128, cohort_fraction=0.25, seed=seed + 1, samples_per_node=8,
        feature_dim=8, num_classes=4, hidden=(8,), batch_size=4,
        speed_tiers=tiers, trace="flash", trace_period=period,
    ) as fc:
        fc_res = fc.run(fc_windows, eval_every=fc_windows)
        fc_sched = fc_res.schedule
        patience = fc.plan.resolved()[2]
        fc_k = fc.cohort_k
    contribs = int(fc_res.fills.sum())
    stalls = int((fc_res.close_codes == 2).sum())
    max_queue = int(fc_sched.queue_depth.max())
    bound = (patience + 1) * fc_k
    if contribs == 0 or stalls > fc_windows // 2:
        print(
            f"FAIL: flash crowd did not sustain throughput "
            f"({contribs} contribs, {stalls}/{fc_windows} stalls)",
            file=sys.stderr,
        )
        return 1
    if max_queue > bound:
        print(
            f"FAIL: flash-crowd pending queue {max_queue} > backpressure "
            f"bound {bound}",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: flash crowd sustained {contribs} contributions over "
        f"{fc_windows} windows ({stalls} stalls, max queue {max_queue} <= "
        f"{bound})",
        file=sys.stderr,
    )

    # Wire-vs-fused parity at n=4: same stream, real async buffer, every
    # aggregate hash bit-exact.
    spec = importlib.util.spec_from_file_location(
        "parity_diff", os.path.join(REPO, "scripts", "parity_diff.py")
    )
    parity_diff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(parity_diff)
    par_kw = dict(
        cohort_fraction=1.0, seed=seed + 2, samples_per_node=8,
        feature_dim=8, num_classes=4, hidden=(8,), batch_size=4,
        speed_tiers=(1.0, 1.0, 2.0, 3.0),
    )
    par_windows = 5
    LEDGERS.reset()
    with AsyncPopulationEngine(4, **par_kw) as fused:
        led = fused.attach_ledger("fused-async")
        fused.run(par_windows, eval_every=100, windows_per_call=1)
        fused_ev = led.canonical_events()
    wire_window_replay(
        AsyncPopulationEngine(4, **par_kw), par_windows, node="wire-async"
    )
    wire_ev = LEDGERS.get("wire-async").canonical_events()
    report = parity_diff.compare_ledgers(wire_ev, fused_ev)
    if report["status"] != "OK":
        print(
            f"FAIL: wire-vs-fused async parity diverged: "
            f"{report['first_divergence']}",
            file=sys.stderr,
        )
        return 1
    if report["hashes_compared"] < 1:
        print("FAIL: parity compared zero aggregate hashes", file=sys.stderr)
        return 1
    print(
        f"PASS: wire-vs-fused async parity OK ({report['compared_events']} "
        f"events aligned, {report['hashes_compared']} hashes bit-exact)",
        file=sys.stderr,
    )
    print(
        f"asyncpop-check PASSED in {time.monotonic() - t0:.1f}s",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
