"""parity-check — the sim↔real parity gate (fast CI shape, ~25 s).

Runs one seeded :class:`~p2pfl_tpu.parity.ParityScenario` at
``Settings.PARITY_NODES`` / ``Settings.PARITY_ROUNDS`` (default 3 nodes, 2
rounds — no chaos, no adversary: the quick gate certifies the clean
trajectory; the adversarial shape is ``bench.py --parity``) on BOTH
execution backends:

1. the real wire — in-memory transport, full Node / gossip / admission /
   aggregator stack, the shared parity-learner kernel,
2. the fused mesh — ``MeshSimulation(canonical_committee=True)``.

and asserts, via ``scripts/parity_diff.py`` over the emitted trajectory
ledgers:

* every wire node's per-round aggregate hashes agree,
* the wire trajectory aligns event-for-event with the mesh trajectory,
* every round's aggregate content hash is BIT-EXACT across backends,
* a deliberately perturbed event is localized (negative control — the
  differ must prove it can fail).

Exit 0 on pass, 1 on failure. ``make parity-check`` wires it next to the
other plane gates.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_parity_diff():
    spec = importlib.util.spec_from_file_location(
        "parity_diff", os.path.join(REPO, "scripts", "parity_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.parity import ParityScenario, run_fused, run_wire

    parity_diff = _load_parity_diff()
    scn = ParityScenario(
        seed=Settings.PARITY_SEED,
        n_nodes=Settings.PARITY_NODES,
        rounds=Settings.PARITY_ROUNDS,
        samples_per_node=32,
        batch_size=16,
        hidden=(16,),
    )
    tmp = tempfile.mkdtemp(prefix="parity_check_")
    t0 = time.monotonic()
    print(
        f"parity-check: scenario seed={scn.seed} n={scn.n_nodes} "
        f"rounds={scn.rounds} — wire arm...",
        file=sys.stderr,
    )
    wire = run_wire(scn, ledger_dir=tmp, timeout_s=180.0)
    print(
        f"parity-check: wire done ({time.monotonic() - t0:.1f}s) — fused arm...",
        file=sys.stderr,
    )
    fused = run_fused(scn, ledger_dir=tmp)

    names = scn.node_names
    ref = wire["hashes"][names[0]]
    if len(ref) != scn.rounds:
        print(
            f"FAIL: wire node0 committed rounds {sorted(ref)} "
            f"(wanted {scn.rounds})",
            file=sys.stderr,
        )
        return 1
    for n in names:
        if wire["hashes"][n] != ref:
            print(
                f"FAIL: wire nodes disagree — {n}: {wire['hashes'][n]} vs "
                f"{names[0]}: {ref}",
                file=sys.stderr,
            )
            return 1
    print("PASS: all wire nodes committed identical per-round hashes", file=sys.stderr)

    report = parity_diff.compare_ledgers(
        wire["events"][names[0]], fused["events"]
    )
    if report["status"] != "OK":
        print(
            "FAIL: wire vs fused DIVERGED: "
            f"{json.dumps(report['first_divergence'])}",
            file=sys.stderr,
        )
        return 1
    if report["hashes_compared"] != scn.rounds:
        print(
            f"FAIL: only {report['hashes_compared']}/{scn.rounds} aggregate "
            "hashes bit-compared",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: {report['compared_events']} events aligned, "
        f"{report['hashes_compared']} aggregate hashes bit-exact across "
        "backends",
        file=sys.stderr,
    )

    # Negative control: the differ must be able to FAIL.
    perturbed = [dict(e) for e in fused["events"]]
    for e in perturbed:
        if e["kind"] == "aggregate_committed" and e.get("hash"):
            e["hash"] = "sha256:" + "0" * 64
            break
    neg = parity_diff.compare_ledgers(wire["events"][names[0]], perturbed)
    if neg["status"] != "DIVERGED" or "hash differs" not in (
        (neg["first_divergence"] or {}).get("problem", "")
    ):
        print(
            f"FAIL: negative control not localized: {json.dumps(neg['first_divergence'])}",
            file=sys.stderr,
        )
        return 1
    print("PASS: perturbed event localized (negative control)", file=sys.stderr)
    print(
        f"parity-check PASSED in {time.monotonic() - t0:.1f}s "
        f"(ledgers under {tmp})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
