"""Per-node shared state and synchronization primitives.

Capability parity with reference p2pfl/node_state.py:26-136: the state object
is shared between the stage machine, the command handlers (which run on
transport threads) and the public Node API, so every cross-thread handoff is
an explicit ``threading.Event`` here.

Design departure from the reference: the reference coordinates with raw
``threading.Lock`` objects acquired at init and "released" to signal
(node_state.py:74-80), a pattern that throws if a lock is released twice.
Events are idempotent and state their intent; the aggregation handoff is an
Event in the reference too (``aggregated_model_event``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from p2pfl_tpu.experiment import Experiment


class NodeState:
    """Mutable state of one federated node during an experiment.

    Attributes:
        addr: This node's address string.
        status: Human-readable lifecycle tag ("Idle" / "Learning").
        experiment: Active :class:`Experiment` or ``None``.
        simulation: Whether the learner is being simulated on the mesh backend.
        models_aggregated: addr -> list of contributors that peer has merged
            (tracks partial-aggregation progress; reference node_state.py:60).
        nei_status: addr -> last round that neighbor reported finishing
            (-1 right after the peer announced an initialized model).
        train_set: Committee (trainset) elected for the current round.
        train_set_votes: addr -> {candidate: weight} votes received.
        learner: The node's learner (set by Node).
        wire: Sparse-delta wire codec (round anchor + error-feedback
            residuals, :class:`~p2pfl_tpu.comm.delta.DeltaWireCodec`).
            Anchors are snapshotted by the stage machine at every round
            boundary; active only under ``Settings.WIRE_COMPRESSION="topk"``.
        admission: Wire admission controller (structural/NaN/norm screening
            of inbound model frames,
            :class:`~p2pfl_tpu.comm.admission.AdmissionController`).
    """

    def __init__(self, addr: str) -> None:
        from p2pfl_tpu.comm.admission import AdmissionController
        from p2pfl_tpu.comm.delta import DeltaWireCodec
        from p2pfl_tpu.privacy.secagg import PrivacyPlane

        self.addr = addr
        self.status = "Idle"
        self.experiment: Optional[Experiment] = None
        self.simulation = False
        self.wire = DeltaWireCodec(addr)
        # Byzantine defense: inbound model-plane frames are screened here
        # (structure/dtype/NaN/norm-bound, comm/admission.py) between
        # decode_frame and aggregator.add_model / apply_frame.
        self.admission = AdmissionController(addr)
        # Privacy plane (p2pfl_tpu/privacy/): session DH keypair, pairwise
        # mask state, EF residual of the masked lattice codec, repair
        # shares. Active only under Settings.PRIVACY_SECAGG, but the key
        # material exists unconditionally so handshakes from masked peers
        # always have something to answer with.
        self.privacy = PrivacyPlane(addr)
        # Federation-wide trace id of the running experiment: minted by the
        # initiator, adopted by peers from the start_learning frame's span
        # context (telemetry/tracing.py). None -> the workflow opens a
        # fresh local trace.
        self.trace_id: Optional[str] = None
        # Stage the workflow is currently executing ("" outside a session) —
        # gossiped to the fleet in the node's health digest so peers can see
        # WHERE a stalled node is stuck, not just that it lags.
        self.current_stage: str = ""
        # Scheduler of the running experiment: "sync" (barrier rounds) or
        # "async" (elastic windows, stages/async_node.py). Set by
        # Node.start_learning_thread; meaningful only while an experiment is
        # in progress.
        self.fed_mode: str = "sync"
        # Epochs per round/window — kept so a mid-experiment joiner can be
        # welcomed with the session's parameters (AsyncJoinCommand).
        self.epochs: int = 1
        # Async peers that announced they finished their windows
        # (async_done): the window fill target stops counting them — a
        # finished peer produces no more contributions, and waiting on one
        # would burn the window timeout (the last-node-standing case).
        self.async_done_peers: set = set()

        # --- durable recovery plane (stages/recovery.py) --------------------
        # True while the node is PARKED in quorum-aware degraded mode: below
        # the live-peer quorum it makes no vote/window progress (heartbeats
        # continue, state is journaled) instead of burning timeout rounds.
        self.parked: bool = False
        # Every address (self included) seen live during this experiment —
        # the quorum denominator. Grows monotonically per session; reset by
        # set_experiment.
        self.session_members: set = set()
        # Partition-heal reconciliation: a dense catch-up model offered by
        # the ahead side of a healed split, adopted ATOMICALLY at the next
        # round boundary (applying it mid-stage would race the stage's own
        # model writes). {"round", "params", "contributors", "source"}.
        self._reconcile_lock = threading.Lock()
        self._pending_reconcile: Optional[Dict[str, Any]] = None

        # Learning info (populated by commands / stages).
        self.models_aggregated: Dict[str, List[str]] = {}
        # Previous-round partial-aggregation coverage: under train<->diffuse
        # overlap (Settings.OVERLAP_TRAIN_DIFFUSE) the round-r partial-model
        # drain keeps serving laggards after increase_round() replaced the
        # live coverage table — their progress announcements (round r, our
        # round r+1) land here so the drain's candidate set still shrinks to
        # empty instead of stalling out.
        self.models_aggregated_prev: Dict[str, List[str]] = {}
        self.prev_coverage_round: int = -1
        # Background diffusion drains (stages/base_node.py): the partial- and
        # full-model gossip loops the overlap path runs off the stage thread.
        # Threads deregister themselves implicitly (join_drains prunes dead
        # ones); joined bounded at experiment finish and node stop.
        self._drains_lock = threading.Lock()
        self._drains: List[threading.Thread] = []
        # Pre-dispatched training segment (train<->diffuse overlap): when the
        # committee election is deterministic (TRAIN_SET_SIZE covers every
        # candidate), VoteTrainSetStage dispatches the round's fit during the
        # vote RTT — overlapped with the previous round's diffusion drains —
        # and TrainStage joins it before touching the aggregator.
        self.prefit: Optional[tuple] = None  # (round, threading.Thread)
        self.nei_status: Dict[str, int] = {}
        self.train_set: List[str] = []
        self.train_set_votes: Dict[str, Dict[str, int]] = {}
        self.learner: Any = None

        # Synchronization.
        self.train_set_votes_lock = threading.Lock()
        self.start_thread_lock = threading.Lock()
        # Guards the last_full_model_round monotonic update: the stage
        # machine (workflow thread) and the full_model / async_catchup
        # handlers (transport threads) all advance it with a read-modify-
        # write max(); unguarded, two concurrent writers can regress the
        # high-water mark and reopen the first-wins adoption window that
        # PR 4 closed. Found by the C3 static checker (make analyze).
        self.full_model_round_lock = threading.Lock()
        # Set when all expected votes have (possibly) arrived — consumers
        # re-check the vote table and clear it again while polling.
        self.votes_ready_event = threading.Event()
        # Set once the model has been initialized (own weights or received
        # via an init-model gossip). Reference models this as a lock acquired
        # at __init__ (node_state.py:77-79).
        self.model_initialized_event = threading.Event()
        # Set when an aggregated (full) model for this round has been adopted.
        self.aggregated_model_event = threading.Event()
        # Highest round for which a full aggregated model was adopted — lets
        # WaitAggregatedModelsStage skip its wait if the model raced ahead of
        # the stage transition (clear-then-wait race).
        self.last_full_model_round = -1

    def note_full_model_round(self, round: int) -> None:
        """Advance the highest round whose full aggregated model we hold.

        Monotonic and locked: callers race from the workflow thread
        (TrainStage / AsyncWindowStage marking their own aggregate) and from
        transport threads (full_model / async_catchup adoption), and an
        interleaved ``max()`` read-modify-write could regress the mark —
        letting a later (possibly Byzantine) full-model frame re-win a round
        that first-wins already closed."""
        with self.full_model_round_lock:
            if round > self.last_full_model_round:
                self.last_full_model_round = round

    # --- partition-heal reconciliation (stages/recovery.py) -----------------

    def offer_reconcile(
        self, round: int, params: Any, contributors: List[str], source: str
    ) -> bool:
        """Store a reconcile catch-up (transport thread). Kept only when it
        is ahead of both the current round and any already-pending offer —
        the freshest generation wins, stale offers are dropped."""
        with self._reconcile_lock:
            current = self.round
            if current is None or round <= current:
                return False
            if (
                self._pending_reconcile is not None
                and round <= self._pending_reconcile["round"]
            ):
                return False
            self._pending_reconcile = {
                "round": int(round),
                "params": params,
                "contributors": list(contributors),
                "source": source,
            }
            return True

    def reconcile_ahead(self) -> bool:
        """True when a pending catch-up targets a round ahead of us — the
        signal sliced stage waits use to wind the current round down fast."""
        with self._reconcile_lock:
            return (
                self._pending_reconcile is not None
                and self.round is not None
                and self._pending_reconcile["round"] > self.round
            )

    def take_reconcile(self) -> Optional[Dict[str, Any]]:
        """Pop the pending catch-up iff still ahead of the current round
        (stale offers — we caught up naturally — are discarded)."""
        with self._reconcile_lock:
            p, self._pending_reconcile = self._pending_reconcile, None
            if p is None or self.round is None or p["round"] <= self.round:
                return None
            return p

    # --- round bookkeeping (proxied off Experiment; reference :84-97) -------

    @property
    def round(self) -> Optional[int]:
        return self.experiment.round if self.experiment is not None else None

    @property
    def total_rounds(self) -> Optional[int]:
        return self.experiment.total_rounds if self.experiment is not None else None

    def set_experiment(self, exp_name: str, total_rounds: int) -> None:
        """Start (or restart) an experiment and flip status to Learning."""
        self.status = "Learning"
        self.async_done_peers = set()
        self.parked = False
        self.session_members = {self.addr}
        with self._reconcile_lock:
            self._pending_reconcile = None
        self.experiment = Experiment(exp_name=exp_name, total_rounds=total_rounds)

    def increase_round(self) -> None:
        if self.experiment is None:
            raise ValueError("no experiment in progress")
        finished = self.round
        self.experiment.increase_round()
        # Retire (don't discard) the finished round's coverage table: the
        # overlap drain for that round reads it until its candidates empty.
        self.models_aggregated_prev = self.models_aggregated
        self.prev_coverage_round = -1 if finished is None else int(finished)
        self.models_aggregated = {}

    def coverage(self, round: int) -> Dict[str, List[str]]:
        """Partial-aggregation coverage table for ``round``: the live table
        for the current round, the retired one for the round just finished
        (the overlap drain's view), empty otherwise."""
        if self.round is not None and round == self.round:
            return self.models_aggregated
        if round == self.prev_coverage_round:
            return self.models_aggregated_prev
        return {}

    def take_prefit(self, round: int) -> Optional[threading.Thread]:
        """Pop the pre-dispatched fit thread iff it belongs to ``round``.
        A STALE one (reconcile fast-forward, abandoned round) is aborted and
        joined here — its thread mutates the learner model, and letting it
        run unowned would race whatever adoption superseded the round."""
        p, self.prefit = self.prefit, None
        if p is None:
            return None
        if p[0] != round:
            try:
                if self.learner is not None:
                    self.learner.interrupt_fit()
            except Exception:  # noqa: BLE001 — cleanup must not break the stage
                pass
            p[1].join(timeout=30.0)
            return None
        return p[1]

    # --- diffusion drains (train<->diffuse overlap) --------------------------

    def add_drain(self, thread: threading.Thread) -> None:
        with self._drains_lock:
            self._drains = [t for t in self._drains if t.is_alive()]
            self._drains.append(thread)

    def join_drains(self, timeout: Optional[float] = None) -> None:
        """Bounded join of outstanding diffusion drains (each terminates on
        its own via empty candidates / stall exit / early stop — the join
        only bounds how long a finish or stop waits for that)."""
        with self._drains_lock:
            drains, self._drains = self._drains, []
        for t in drains:
            if t.is_alive():
                t.join(timeout)
        alive = [t for t in drains if t.is_alive()]
        if alive:
            with self._drains_lock:
                self._drains.extend(alive)

    def clear(self) -> None:
        """Reset to the post-construction state (reference :125-127)."""
        self.__init__(self.addr)  # type: ignore[misc]

    def __str__(self) -> str:
        exp = str(self.experiment) if self.experiment else "None"
        return f"NodeState(addr={self.addr}, status={self.status}, {exp})"
