"""Federation observatory: assemble gossiped health digests into a fleet view.

Every node runs one :class:`Observatory` (owned by its communication
protocol). Peers' :class:`~p2pfl_tpu.telemetry.digest.HealthDigest` frames
arrive on the heartbeat path (``CommunicationProtocol.handle_envelope``
feeds :meth:`Observatory.ingest`); the observatory keeps the latest digest
per peer plus enough history to derive federation-level health nobody
reports directly:

* **straggler score** — how far behind the fleet a peer is running, three
  components summed: round lag behind the fleet-max round; the positive
  z-score of the peer's ROUND-ENTRY LATENESS (seconds between the fleet
  leader entering the current round and this peer entering it — persistent
  for the whole round, unlike raw round lag, which the vote barrier erases
  within seconds when a straggler catches up); and the positive z-score of
  its step time against the fleet's step-time distribution (a peer in the
  current round whose steps crawl scores high too). APPFL's server does
  this centrally (arxiv 2409.11585); here every node derives it from
  gossip.
* **suspect score** — Byzantine suspicion: admission rejections the fleet
  attributes to this peer (PR 4's ``p2pfl_updates_rejected_total`` gained a
  ``source`` label exactly so digests can carry per-sender attribution),
  summed across every reporting observer.
* **link score** — local link quality to the peer: missed heartbeats and
  clock skew, read from the heartbeater's own gauges (these are facts about
  OUR link, so they come from the local registry, not from digests).

Population scale (PR 8): the observatory is bounded in fleet size. Peers
whose digests stop arriving for ``Settings.OBS_PEER_TTL`` are EVICTED —
dropped from the per-peer table AND every scoring statistic (a crashed
peer must not skew straggler z-scores forever), counted
``p2pfl_fed_evicted_total``. Beyond ``Settings.OBS_MAX_TRACKED`` live
peers, new peers' digests fold into MERGED fleet sketches plus a bounded
worst-straggler candidate table instead of growing the per-peer dict — the
fleet quantile view (:meth:`fleet_quantiles`, built from the v2 digests'
mergeable sketches) stays exact-within-sketch-error while per-node memory
grows ~O(log n). Prometheus refreshes are rate-limited by
``Settings.OBS_REFRESH_MIN_S`` (each refresh is O(live peers)).

Exports: the ``p2pfl_fed_*`` Prometheus section, :meth:`snapshot` (the
JSON federation view ``scripts/fed_top.py`` renders live — now with a
``fleet`` quantile section), :meth:`top` (argmax helpers the benches
assert on), and :func:`write_snapshot_doc` (the atomic writer the fused-
mesh simulation reuses for its virtual-fleet snapshots).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from collections import deque

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry.digest import HealthDigest
from p2pfl_tpu.telemetry.metrics import REGISTRY
from p2pfl_tpu.telemetry.sketches import DistinctEstimator, QuantileSketch

#: Membership churn tail kept (and snapshotted) per observatory.
MEMBERSHIP_EVENTS = 64

#: Top-N rows a population snapshot keeps per metric (and the unit of the
#: bounded overflow straggler-candidate table, which holds 4x this).
_TOP_CANDIDATES = 16

_PEER_ROUND = REGISTRY.gauge(
    "p2pfl_fed_peer_round",
    "Latest round a peer reported via its gossiped health digest",
    labels=("node", "peer"),
)
_STRAGGLER = REGISTRY.gauge(
    "p2pfl_fed_straggler_score",
    "Derived straggler score per peer (round lag + positive step-time "
    "z-score vs the fleet); higher = further behind",
    labels=("node", "peer"),
)
_SUSPECT = REGISTRY.gauge(
    "p2pfl_fed_suspect_score",
    "Derived Byzantine-suspect score per peer (admission rejections the "
    "fleet attributes to frames this peer sent)",
    labels=("node", "peer"),
)
_LINK = REGISTRY.gauge(
    "p2pfl_fed_link_score",
    "Local link-quality score per peer (missed heartbeats + |clock skew|); "
    "higher = worse link",
    labels=("node", "peer"),
)
_PEERS_KNOWN = REGISTRY.gauge(
    "p2pfl_fed_peers_known",
    "Peers (self included) with a live health digest in the observatory",
    labels=("node",),
)
_DIGESTS_RX = REGISTRY.counter(
    "p2pfl_fed_digests_rx_total",
    "Health digests ingested, by reporting peer",
    labels=("node", "peer"),
)
_EVICTED = REGISTRY.counter(
    "p2pfl_fed_evicted_total",
    "Peers evicted from the observatory after OBS_PEER_TTL with no digest "
    "(dead peers leave the scoring statistics instead of skewing them)",
    labels=("node",),
)
_OVERFLOW = REGISTRY.gauge(
    "p2pfl_fed_overflow_peers",
    "Peers folded into merged fleet sketches instead of per-peer tracking "
    "(population beyond OBS_MAX_TRACKED)",
    labels=("node",),
)

# --- device observatory (fused population engines) --------------------------
# The p2pfl_mesh_* family mirrors what the in-scan aux stream reports per
# chunk: the fused backends' headline vitals, scrapeable next to the wire's
# p2pfl_fed_* section. "node" is the engine label (mesh-sim /
# population-engine / asyncpop-engine).
_MESH_ROUND = REGISTRY.gauge(
    "p2pfl_mesh_round",
    "Absolute round/window cursor of a fused population engine",
    labels=("node",),
)
_MESH_LOSS = REGISTRY.gauge(
    "p2pfl_mesh_train_loss",
    "Cohort mean training loss of the last fused round/window, measured "
    "inside the compiled scan",
    labels=("node",),
)
_MESH_WEIGHT_MASS = REGISTRY.gauge(
    "p2pfl_mesh_weight_mass",
    "Fold-weight mass (sample-count x staleness discount) aggregated in "
    "the last fused round/window",
    labels=("node",),
)
_MESH_PARTICIPANTS = REGISTRY.counter(
    "p2pfl_mesh_participants_total",
    "Cumulative cohort members whose contributions folded into a fused "
    "aggregate",
    labels=("node",),
)
_MESH_TRIPS = REGISTRY.counter(
    "p2pfl_mesh_trips_total",
    "Health-tripwire trips inside the compiled scan, by kind "
    "(nonfinite | loss_diverge)",
    labels=("node", "kind"),
)
_MESH_PEAK_BYTES = REGISTRY.gauge(
    "p2pfl_mesh_device_peak_bytes",
    "Device memory watermark (peak bytes) observed around the last timed "
    "chunk of a fused run",
    labels=("node",),
)
_MESH_CHUNK_SECONDS = REGISTRY.gauge(
    "p2pfl_mesh_chunk_seconds",
    "Wall seconds of the last timed fused chunk (one _run_jit call)",
    labels=("node",),
)


def mesh_chunk_telemetry(
    node: str,
    *,
    round_cursor: Optional[int] = None,
    train_loss: Optional[float] = None,
    weight_mass: Optional[float] = None,
    participants: Optional[float] = None,
    chunk_seconds: Optional[float] = None,
    peak_bytes: Optional[float] = None,
) -> None:
    """Mirror one fused chunk's aux-stream summary into the p2pfl_mesh_*
    registry section. Never raises — a broken export must not break the
    chunk it was observing."""
    try:
        if round_cursor is not None:
            _MESH_ROUND.labels(node).set(float(round_cursor))
        if train_loss is not None:
            _MESH_LOSS.labels(node).set(float(train_loss))
        if weight_mass is not None:
            _MESH_WEIGHT_MASS.labels(node).set(float(weight_mass))
        if participants is not None and participants > 0:
            _MESH_PARTICIPANTS.labels(node).inc(float(participants))
        if chunk_seconds is not None:
            _MESH_CHUNK_SECONDS.labels(node).set(float(chunk_seconds))
        if peak_bytes is not None:
            _MESH_PEAK_BYTES.labels(node).set(float(peak_bytes))
    except Exception:  # noqa: BLE001
        pass


def mesh_trip(node: str, kind: str) -> None:
    """Count one tripwire trip (kind: nonfinite | loss_diverge)."""
    try:
        _MESH_TRIPS.labels(node, kind).inc()
    except Exception:  # noqa: BLE001
        pass

#: A digest older than this many seconds is stale: its peer stops counting
#: toward fleet statistics (it is probably dead and the heartbeater will
#: sweep it; keeping its frozen round would poison the round-lag baseline).
STALE_AFTER_S = 60.0

#: Round-entry lateness below this (seconds) never contributes to the
#: straggler score: every healthy fleet has a statistically-latest member,
#: and sub-second entry skew is gossip jitter, not straggling.
LATENESS_FLOOR_S = 1.0


class Observatory:
    """Per-node fleet view assembled from gossiped health digests.

    Thread-safe: ingest runs on transport threads, snapshots on whatever
    thread asks (bench pollers, ``fed_top`` writers, tests).
    """

    def __init__(self, addr: str, recorder: Optional[Any] = None) -> None:
        self._addr = addr
        self._lock = threading.Lock()
        #: peer -> (digest, local-monotonic arrival time)
        self._peers: Dict[str, Tuple[HealthDigest, float]] = {}
        #: peer -> (round, local-monotonic time the peer's digests FIRST
        #: reported that round) — the round-entry lateness base.
        self._entries: Dict[str, Tuple[int, float]] = {}
        #: membership churn tail: the last MEMBERSHIP_EVENTS join/rejoin/
        #: leave transitions this observatory witnessed (first digest from an
        #: unknown peer = join; after a forget = rejoin; forget = leave) —
        #: surfaced in the snapshot so ``fed_top`` shows churn live.
        self._membership: deque = deque(maxlen=MEMBERSHIP_EVENTS)
        self._ever_seen: set = set()
        #: peers that left via forget (suspected death) or TTL eviction —
        #: their NEXT appearance is a "recover" heal, not a plain rejoin,
        #: and their scoring state starts fresh.
        self._forgotten: set = set()
        #: peers whose "recover" event was already emitted (explicit
        #: peer_recovered from the heal detector) — the digest that follows
        #: must not emit a second membership event.
        self._returned: set = set()
        #: peer -> missed-beat counter value at its last recovery: the link
        #: score reads misses ABOVE this baseline, so a healed peer does not
        #: inherit every beat the partition ate.
        self._link_baseline: Dict[str, float] = {}
        #: optional flight recorder — membership transitions are postmortem-
        #: worthy events (Node/protocol wire the per-node recorder in).
        self.recorder = recorder
        self._peers_known = _PEERS_KNOWN.labels(addr)
        self._evicted = _EVICTED.labels(addr)
        self._overflow_gauge = _OVERFLOW.labels(addr)
        # Population-overflow state: beyond Settings.OBS_MAX_TRACKED live
        # peers, new peers' digests fold here instead of into _peers —
        # merged fleet sketches (mergeable by construction) + a bounded
        # worst-round-lag candidate table so the top-straggler question
        # still has an answer among untracked peers.
        self._overflow_sketches: Dict[str, QuantileSketch] = {}
        self._overflow_distinct: Optional[DistinctEstimator] = None
        self._overflow_seen: set = set()  # addresses folded at least once
        self._overflow_top: Dict[str, Tuple[float, int]] = {}  # peer -> (lag, round)
        self._last_evict = 0.0  # monotonic; eviction sweep throttle
        self._last_refresh = 0.0  # monotonic; Prometheus refresh throttle

    def _membership_event(self, event: str, peer: str) -> None:
        # caller holds the lock
        self._membership.append(
            {"event": event, "peer": peer, "ts": round(time.time(), 3)}
        )
        rec = self.recorder
        if rec is not None:
            try:
                rec.record("membership", event=event, peer=peer)
            except Exception:  # noqa: BLE001 — observability must not raise
                pass
        # Trajectory ledger: this method is THE membership choke point —
        # join/rejoin/leave/evict/recover all pass through here, so the
        # ledger's membership stream needs exactly one emission site.
        from p2pfl_tpu.telemetry.ledger import LEDGERS

        LEDGERS.emit(self._addr, "membership", event=event, peer=peer)

    # --- ingest --------------------------------------------------------------

    def ingest(self, dig: HealthDigest) -> bool:
        """Record a peer's digest (or our own — the self view rides the same
        path). Returns True when the peer's round or stage CHANGED — the
        signal the flight recorder logs as a digest-delta event.

        Memory bounds: an unknown peer arriving while the per-peer table is
        at ``OBS_MAX_TRACKED`` folds into the overflow fleet sketches (and,
        when its round lag is among the worst, the bounded straggler-
        candidate table) instead of growing the table; peers silent past
        ``OBS_PEER_TTL`` are evicted by the sweep this call triggers.
        """
        now = time.monotonic()
        self._evict_expired(now)
        with self._lock:
            prev = self._peers.get(dig.node)
            # Out-of-order delivery (gossip re-forwarding): keep the newest
            # by sender timestamp when both carry one.
            if prev is not None and dig.ts and prev[0].ts and dig.ts < prev[0].ts:
                return False
            if prev is None and dig.node != self._addr:
                if len(self._peers) >= max(8, int(Settings.OBS_MAX_TRACKED)):
                    self._fold_overflow(dig)
                    return False
                if dig.node in self._returned:
                    # The heal detector already announced this recovery and
                    # reset the peer's stats — no second membership event.
                    self._returned.discard(dig.node)
                elif dig.node in self._forgotten:
                    # Reappearance after suspected death / TTL eviction: a
                    # heal. Scoring state starts fresh — stale pre-partition
                    # z-stats must not outlive the partition.
                    self._recover_locked(dig.node)
                else:
                    self._membership_event(
                        "rejoin" if dig.node in self._ever_seen else "join",
                        dig.node,
                    )
            self._ever_seen.add(dig.node)
            self._peers[dig.node] = (dig, now)
            entry = self._entries.get(dig.node)
            if entry is None or entry[0] != dig.round:
                self._entries[dig.node] = (dig.round, now)
        if dig.node != self._addr:
            _DIGESTS_RX.labels(self._addr, dig.node).inc()
        self._refresh()
        return prev is None or prev[0].round != dig.round or prev[0].stage != dig.stage

    def _fold_overflow(self, dig: HealthDigest) -> None:
        """Population-overflow path (caller holds the lock): merge the
        digest's sketches into the fleet aggregate and keep the peer only
        if it belongs in the bounded worst-straggler candidate table."""
        self._overflow_seen.add(dig.node)
        self._overflow_gauge.set(len(self._overflow_seen))
        for name in dig.sketches:
            if name == "__distinct__":
                est = dig.distinct()
                if est is not None:
                    if self._overflow_distinct is None:
                        self._overflow_distinct = est
                    else:
                        self._overflow_distinct.merge_in(est)
                continue
            sk = dig.sketch(name)
            if sk is None:
                continue
            mine = self._overflow_sketches.get(name)
            if mine is None:
                self._overflow_sketches[name] = sk
            else:
                mine.merge_in(sk)
        # Worst-straggler candidates among the untracked mass: keyed by raw
        # round index (the fleet-max baseline is applied at read time).
        cap = 4 * _TOP_CANDIDATES
        if dig.round >= 0:
            self._overflow_top[dig.node] = (float(dig.round), dig.round)
            if len(self._overflow_top) > cap:
                # Drop the LEAST-behind candidate (highest round).
                drop = max(self._overflow_top, key=lambda p: self._overflow_top[p][0])
                self._overflow_top.pop(drop, None)

    def _evict_expired(self, now: float) -> None:
        """Drop peers whose last digest is older than OBS_PEER_TTL — they
        leave the scoring statistics entirely (STALE_AFTER_S only hides a
        peer from the live set; eviction frees its memory and its round-
        entry record, which would otherwise skew lateness baselines
        forever). Throttled to ~1/s: the sweep is O(peers)."""
        ttl = float(Settings.OBS_PEER_TTL)
        if ttl <= 0.0 or now - self._last_evict < 1.0:
            return
        self._last_evict = now
        evicted: List[str] = []
        with self._lock:
            for peer, (_, seen) in list(self._peers.items()):
                if peer != self._addr and now - seen > ttl:
                    self._peers.pop(peer, None)
                    self._entries.pop(peer, None)
                    self._forgotten.add(peer)  # a return after TTL is a heal
                    evicted.append(peer)
                    self._membership_event("evict", peer)
        for _ in evicted:
            self._evicted.inc()

    def forget(self, peer: str) -> None:
        """Drop a peer's entry (heartbeat sweep declared it dead)."""
        with self._lock:
            known = self._peers.pop(peer, None) is not None
            self._entries.pop(peer, None)
            if known:
                self._membership_event("leave", peer)
                self._forgotten.add(peer)
        self._refresh()

    def _recover_locked(self, peer: str) -> None:
        """Heal bookkeeping (caller holds the lock): emit the "recover"
        membership event (mirrored to the flight recorder like every other
        membership transition) and reset the peer's scoring state — its
        round-entry clock restarts, and the link score's missed-beat
        baseline moves to NOW so partition-era misses stop counting."""
        self._forgotten.discard(peer)
        self._entries.pop(peer, None)
        self._link_baseline[peer] = self._missed_beats(peer)
        self._membership_event("recover", peer)

    def peer_recovered(self, peer: str) -> None:
        """Explicit heal notification (the protocol's heal detector saw a
        failure-departed peer come back): announce the recovery and reset
        the peer's scoring state. The digest that follows re-populates the
        table without a duplicate membership event."""
        with self._lock:
            self._recover_locked(peer)
            self._returned.add(peer)
        self._refresh()

    # --- derived health ------------------------------------------------------

    def _live(self) -> List[Tuple[HealthDigest, float]]:
        now = time.monotonic()
        with self._lock:
            return [
                (d, seen) for d, seen in self._peers.values()
                if now - seen <= STALE_AFTER_S
            ]

    def scores(self) -> Dict[str, Dict[str, float]]:
        """{peer: {straggler, suspect, link, round, age_s}} over live
        digests. Scores are comparable within one observatory; the bench
        contract is about the ARGMAX (top straggler / top suspect), not
        absolute values."""
        live = self._live()
        now = time.monotonic()
        if not live:
            return {}
        # Fleet baselines. Round lag is measured against the fleet-max
        # round among live digests; step times against the fleet mean/std.
        max_round = max(d.round for d, _ in live)
        step_times = [1.0 / d.steps_per_s for d, _ in live if d.steps_per_s > 0]
        mean_st = sum(step_times) / len(step_times) if step_times else 0.0
        var_st = (
            sum((t - mean_st) ** 2 for t in step_times) / len(step_times)
            if step_times
            else 0.0
        )
        std_st = math.sqrt(var_st)
        # Round-entry lateness: seconds behind the FIRST peer to enter the
        # fleet-max round. A straggler that catches up at the next vote
        # barrier erases its round-index lag within seconds, but its late
        # entry stays on the books for the whole round — this is what keeps
        # the straggler score up between the transient lag windows.
        with self._lock:
            entries = dict(self._entries)
        lead_entry: Optional[float] = None
        if max_round >= 0:
            at_max = [
                t for r, t in entries.values() if r == max_round
            ]
            if at_max:
                lead_entry = min(at_max)
        lateness: Dict[str, float] = {}
        for d, _ in live:
            if d.round < 0 or lead_entry is None:
                lateness[d.node] = 0.0
            elif d.round == max_round:
                lateness[d.node] = max(
                    0.0, entries.get(d.node, (max_round, now))[1] - lead_entry
                )
            else:  # still hasn't entered the fleet round — clock keeps running
                lateness[d.node] = max(0.0, now - lead_entry)
        mean_lt = sum(lateness.values()) / len(lateness) if lateness else 0.0
        var_lt = (
            sum((t - mean_lt) ** 2 for t in lateness.values()) / len(lateness)
            if lateness
            else 0.0
        )
        std_lt = math.sqrt(var_lt)
        # Suspect attribution: sum every observer's rejected_by_source.
        attributed: Dict[str, float] = {}
        for d, _ in live:
            for src, n in d.rejected_by_source.items():
                attributed[src] = attributed.get(src, 0.0) + float(n)
        out: Dict[str, Dict[str, float]] = {}
        for d, seen in live:
            lag = float(max(0, max_round - d.round)) if d.round >= 0 else 0.0
            z = 0.0
            if d.steps_per_s > 0 and std_st > 1e-9:
                z = max(0.0, ((1.0 / d.steps_per_s) - mean_st) / std_st)
            lz = 0.0
            lt = lateness.get(d.node, 0.0)
            if std_lt > 1e-9 and lt >= LATENESS_FLOOR_S:
                lz = max(0.0, (lt - mean_lt) / std_lt)
            straggler = lag + lz + z
            suspect = attributed.get(d.node, 0.0)
            link = 0.0
            if d.node != self._addr:
                link = self._link_score(d.node)
            out[d.node] = {
                "straggler": round(straggler, 4),
                "suspect": round(suspect, 4),
                "link": round(link, 4),
                "round": float(d.round),
                "age_s": round(now - seen, 3),
            }
        return out

    def _missed_beats(self, peer: str) -> float:
        missed = REGISTRY.get("p2pfl_heartbeat_missed_total")
        if missed is None:
            return 0.0
        return sum(
            child.value
            for labels, child in missed.samples()
            if labels.get("node") == self._addr and labels.get("peer") == peer
        )

    def _link_score(self, peer: str) -> float:
        """Missed beats + |clock skew| for OUR link to ``peer`` (heartbeater
        gauges — already computed locally, not gossiped). Misses below the
        peer's recovery baseline don't count: a healed partition survivor
        starts its link score fresh instead of inheriting every beat the
        partition ate."""
        score = max(
            0.0, self._missed_beats(peer) - self._link_baseline.get(peer, 0.0)
        )
        skew = REGISTRY.get("p2pfl_heartbeat_clock_skew_seconds")
        if skew is not None:
            for labels, child in skew.samples():
                if labels.get("node") == self._addr and labels.get("peer") == peer:
                    score += abs(child.value)
        return score

    def suspect_score(self, peer: str) -> float:
        """Fleet-attributed Byzantine suspicion for ``peer``: the sum of
        admission rejections every live digest attributes to frames it sent.
        Unlike :meth:`scores`, this answers for ANY address — an adversary
        that poisons the model plane while never reporting digests of its
        own must still be gateable (async participation control)."""
        total = 0.0
        for d, _ in self._live():
            total += float(d.rejected_by_source.get(peer, 0.0))
        return total

    def fleet_quantiles(self) -> Dict[str, Any]:
        """Fleet-level distribution view, merged from the v2 digests'
        sketches (live tracked peers + the population overflow aggregate):
        ``{metric: {p50, p90, p99, count, mean}}`` plus the HyperLogLog
        ``distinct_contributors`` estimate. Metrics nobody reported are
        absent; v1 peers simply contribute nothing here."""
        distinct: Optional[DistinctEstimator] = None
        now = time.monotonic()
        with self._lock:
            live = [
                d for d, seen in self._peers.values()
                if now - seen <= STALE_AFTER_S
            ]
            merged = {k: v.copy() for k, v in self._overflow_sketches.items()}
            if self._overflow_distinct is not None:
                distinct = DistinctEstimator(self._overflow_distinct.m)
                distinct._registers = bytearray(self._overflow_distinct._registers)
        for d in live:
            for name in d.sketches:
                if name == "__distinct__":
                    est = d.distinct()
                    if est is not None:
                        if distinct is None:
                            distinct = est
                        else:
                            distinct.merge_in(est)
                    continue
                sk = d.sketch(name)
                if sk is None:
                    continue
                mine = merged.get(name)
                if mine is None:
                    merged[name] = sk
                else:
                    mine.merge_in(sk)
        out: Dict[str, Any] = {}
        for name, sk in sorted(merged.items()):
            if sk.count <= 0:
                continue
            q = sk.quantiles()
            out[name] = {
                "p50": round(q["p50"], 6),
                "p90": round(q["p90"], 6),
                "p99": round(q["p99"], 6),
                "count": sk.count,
                "mean": round(sk.mean, 6),
            }
        if distinct is not None:
            out["distinct_contributors"] = round(distinct.estimate(), 1)
        return out

    def estimated_memory_bytes(self) -> int:
        """Rough per-node observatory footprint: encoded size of every
        tracked digest plus the overflow aggregate's wire size. The bench
        plots this against fleet size — it must plateau (tracked peers cap
        at OBS_MAX_TRACKED, overflow state is O(sketch bins))."""
        total = 0
        with self._lock:
            for d, _ in self._peers.values():
                try:
                    total += len(d.encode())
                except Exception:  # noqa: BLE001
                    total += 512
            for sk in self._overflow_sketches.values():
                total += len(json.dumps(sk.to_wire()))
            total += 64 * len(self._entries)
            total += 80 * len(self._overflow_top)
            if self._overflow_distinct is not None:
                total += self._overflow_distinct.m
        return total

    def top(self, metric: str) -> Optional[str]:
        """Peer (never self) with the highest nonzero ``metric`` score —
        ``"straggler"`` | ``"suspect"`` | ``"link"``. None when no peer
        scores above zero (a healthy fleet has no top straggler)."""
        best, best_score = None, 0.0
        for peer, s in self.scores().items():
            if peer == self._addr:
                continue
            if s.get(metric, 0.0) > best_score:
                best, best_score = peer, s[metric]
        return best

    # --- export --------------------------------------------------------------

    def _refresh(self) -> None:
        """Mirror the derived view into the p2pfl_fed_* registry section.

        Rate-limited by ``Settings.OBS_REFRESH_MIN_S``: the derivation is
        O(live peers), and at population scale a per-beat refresh would make
        ingest quadratic. 0 (default) refreshes on every ingest."""
        now = time.monotonic()
        min_s = float(Settings.OBS_REFRESH_MIN_S)
        if min_s > 0.0 and now - self._last_refresh < min_s:
            return
        self._last_refresh = now
        scores = self.scores()
        for peer, s in scores.items():
            _PEER_ROUND.labels(self._addr, peer).set(s["round"])
            _STRAGGLER.labels(self._addr, peer).set(s["straggler"])
            _SUSPECT.labels(self._addr, peer).set(s["suspect"])
            if peer != self._addr:
                _LINK.labels(self._addr, peer).set(s["link"])
        self._peers_known.set(len(scores))

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able federation view: every live peer's latest digest plus
        the derived scores — what ``scripts/fed_top.py`` renders."""
        live = self._live()
        scores = self.scores()
        peers: Dict[str, Any] = {}
        for d, _ in live:
            stale_sk = d.sketch("staleness")
            entry = {
                "ts": d.ts,
                "version": d.version,
                "staleness_p90": (
                    round(stale_sk.quantile(0.9), 4)
                    if stale_sk is not None and stale_sk.count > 0
                    else None
                ),
                "round": d.round,
                "total_rounds": d.total_rounds,
                "stage": d.stage,
                "mode": d.mode,
                "staleness": d.staleness,
                "steps_per_s": d.steps_per_s,
                "jit_compile_s": d.jit_compile_s,
                "tx_bytes": d.tx_bytes,
                "tx_by_codec": dict(d.tx_by_codec),
                "rx_bytes": d.rx_bytes,
                "queue_depth": d.queue_depth,
                "agg_waits": d.agg_waits,
                "agg_wait_s": d.agg_wait_s,
                "contributors": d.contributors,
                "rejections": dict(d.rejections),
                "rejected_by_source": dict(d.rejected_by_source),
                "faults_seen": d.faults_seen,
                "dp_epsilon": d.dp_epsilon,
                # Supervisor vitals: None for unsupervised/older peers —
                # fed_top renders "-" (cross-version tolerance is the
                # digest decoder's absent-field default).
                "restarts": getattr(d, "restarts", None),
                "degrade": getattr(d, "degrade", None),
                "mem_bytes": d.mem_bytes,
                "scores": scores.get(d.node, {}),
            }
            peers[d.node] = entry
        with self._lock:
            membership = list(self._membership)
            overflow_peers = len(self._overflow_seen)
            # The most-behind untracked peers (lowest reported round): the
            # top-straggler question keeps an answer beyond the tracking cap.
            overflow_worst = [
                {"peer": p, "round": rnd}
                for p, (key, rnd) in sorted(
                    self._overflow_top.items(), key=lambda kv: kv[1][0]
                )[:_TOP_CANDIDATES]
            ]
        doc = {
            "observer": self._addr,
            "written_at": time.time(),
            "peers": peers,
            "fleet": {
                "tracked_peers": len(peers),
                "overflow_peers": overflow_peers,
                "size": len(peers) + overflow_peers,
                "overflow_stragglers": overflow_worst,
                "quantiles": self.fleet_quantiles(),
            },
            "membership_events": membership,
            "top_straggler": self.top("straggler"),
            "top_suspect": self.top("suspect"),
        }
        # Trajectory-ledger tail: the observer's last few canonical events
        # ride the snapshot so fed_top's PARITY panel shows what the
        # federation just DID (rounds opened, contributions folded,
        # aggregates committed) next to how it is doing.
        from p2pfl_tpu.telemetry.ledger import LEDGERS

        led = LEDGERS.peek(self._addr)
        tail_n = int(Settings.LEDGER_SNAPSHOT_TAIL)
        if led is not None and tail_n > 0:
            doc["ledger"] = {
                "run_id": led.run_id,
                "events": led.tail(tail_n),
            }
        return doc

    def write_snapshot(self, path: str) -> str:
        """Atomically write :meth:`snapshot` as JSON to ``path`` (the file
        ``fed_top.py`` polls). Returns the path."""
        return write_snapshot_doc(path, self.snapshot())

    def reset(self) -> None:
        with self._lock:
            self._peers.clear()
            self._entries.clear()
            self._membership.clear()
            self._ever_seen.clear()
            self._forgotten.clear()
            self._returned.clear()
            self._link_baseline.clear()
            self._overflow_sketches.clear()
            self._overflow_top.clear()
            self._overflow_seen.clear()
            self._overflow_distinct = None
        self._peers_known.set(0)
        self._overflow_gauge.set(0)


#: snapshot-doc schema: v2 added the common versioned "header" block
#: (run_id / schema_version / node / clock era); old readers that only
#: know "observer"/"peers"/"fleet" keep working.
SNAPSHOT_SCHEMA_VERSION = 2


def write_snapshot_doc(path: str, doc: Dict[str, Any]) -> str:
    """Atomically write a federation-snapshot document (tmp + rename, the
    contract ``fed_top.py`` polls against). Shared by the real-wire
    observatory and the fused-mesh virtual-fleet snapshot — which makes it
    the single choke point stamping the run-correlated artifact header."""
    from p2pfl_tpu.telemetry.bundle import artifact_header

    doc.setdefault(
        "header",
        artifact_header(
            node=str(doc.get("observer", "")),
            kind="snapshot",
            schema_version=SNAPSHOT_SCHEMA_VERSION,
        ),
    )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # pid alone collides when two node threads write the same doc path
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def population_snapshot(
    observer: str,
    node_names: List[str],
    metrics: Dict[str, Any],
    top_n: int = _TOP_CANDIDATES,
    rel_err: Optional[float] = None,
    extras: Optional[Dict[str, Any]] = None,
    extra_sketches: Optional[Dict[str, QuantileSketch]] = None,
) -> Dict[str, Any]:
    """Build a fed_top-renderable snapshot from PER-NODE metric arrays —
    through the REAL :class:`Observatory` ingestion path.

    The fused-mesh simulation's observability path: the jitted round
    program computes per-virtual-node health arrays (round lag, step time,
    participation, rejections), and this helper routes them through a real
    observatory exactly like the wire does — the worst ``top_n`` stragglers
    become synthesized :class:`HealthDigest` frames fed to
    :meth:`Observatory.ingest` (membership events, scoring, Prometheus
    refresh and all), while the remaining population mass takes the same
    overflow fold a beyond-``OBS_MAX_TRACKED`` wire fleet takes (merged
    fleet sketches + the bounded worst-straggler candidate table). The
    returned document therefore IS an ``Observatory.snapshot()`` — same
    producer, same shape — so a 100k-vnode mesh run renders in the same
    ``fed_top`` view as an 8-node real-wire federation, and
    :func:`snapshot_shape_diff` can assert the parity.

    ``metrics`` maps metric name -> array-like of length ``len(node_names)``.
    Straggler SELECTION (which vnodes get tracked) uses the full-population
    ordering ``round_lag + positive step-time z``; the per-peer scores in
    the document then come from the observatory's own scorer over the
    tracked set. Quantile mass is folded ONCE: the full arrays go into the
    overflow sketches via one vectorized ``add_many`` per metric, and the
    synthesized digests deliberately carry no sketches of their own.

    ``extras`` (optional) is the device-observatory side channel — cohort
    train loss, update-norm summary, device memory watermark, tripwire
    state — stamped onto every tracked vnode row (``loss`` / ``gnorm`` /
    ``trip`` / ``mem_bytes``) and echoed as ``doc["devobs"]`` for the
    bench. ``extra_sketches`` merges in-scan device sketches (e.g. the
    ``update_norm`` buckets folded through ``SKETCHES``) into the fleet
    quantile view.
    """
    import numpy as np

    if rel_err is None:
        rel_err = Settings.SKETCH_REL_ERR
    n = len(node_names)
    arrays = {
        k: np.asarray(v, np.float64).ravel() for k, v in metrics.items()
    }
    for k, a in arrays.items():
        if a.shape != (n,):
            raise ValueError(
                f"metric {k!r} has shape {a.shape}, expected ({n},)"
            )
    lag = arrays.get("round_lag", np.zeros(n))
    step = arrays.get("step_time", np.zeros(n))
    rej = arrays.get("rejections", np.zeros(n))
    rounds_arr = arrays.get("round")
    part = arrays.get("participation")
    stale = arrays.get("staleness")
    # Straggler SELECTION over the full population mirrors the real
    # observatory's score shape: round lag plus positive step-time z.
    std = float(step.std())
    z = np.maximum(0.0, (step - float(step.mean())) / std) if std > 1e-12 else np.zeros(n)
    straggler = lag + z
    full_order = np.argsort(-straggler, kind="stable")
    order = full_order[: max(1, int(top_n))].tolist()
    # Track the worst SUSPECTS too (nonzero fleet-attributed rejections): a
    # Byzantine vnode is postmortem-worthy even when it isn't a straggler,
    # and the wire's top_suspect question needs it in the per-peer table to
    # have an answer.
    for i in np.argsort(-rej, kind="stable")[: max(1, int(top_n))].tolist():
        if rej[i] > 0 and i not in order:
            order.append(i)
    tracked = {node_names[i] for i in order}

    obs = Observatory(observer)
    now = time.time()
    max_round = int(rounds_arr.max()) if rounds_arr is not None and n else -1
    # The observer's self view rides the same path as on the wire — and
    # carries the fleet's per-sender rejection attribution, which is how
    # the real scorer derives suspect scores.
    obs.ingest(
        HealthDigest(
            node=observer,
            ts=now,
            round=max_round,
            stage="observer",
            mode="fused",
            rejected_by_source={
                node_names[i]: float(rej[i]) for i in order if rej[i] > 0
            },
        )
    )
    for i in order:
        obs.ingest(
            HealthDigest(
                node=node_names[i],
                ts=now,
                round=int(rounds_arr[i]) if rounds_arr is not None else -1,
                stage="virtual",
                mode="",
                staleness=float(stale[i]) if stale is not None else 0.0,
                steps_per_s=(1.0 / float(step[i])) if step[i] > 0 else 0.0,
                contributors=float(part[i]) if part is not None else 0.0,
            )
        )
    # Everyone else takes the population-overflow path: ALL quantile mass
    # (tracked rows included — their digests carry no sketches, so nothing
    # is counted twice) folds into the merged fleet sketches in one
    # vectorized pass per metric, and the worst untracked stragglers fill
    # the bounded candidate table the snapshot's overflow section reads.
    with obs._lock:
        for k, a in sorted(arrays.items()):
            sk = QuantileSketch(
                rel_err=rel_err, max_bins=Settings.SKETCH_MAX_BINS
            )
            sk.add_many(a)
            obs._overflow_sketches[k] = sk
        if extra_sketches:
            for k, sk in sorted(extra_sketches.items()):
                if sk is None or sk.count <= 0:
                    continue
                mine = obs._overflow_sketches.get(k)
                if mine is None:
                    obs._overflow_sketches[k] = sk.copy()
                else:
                    mine.merge_in(sk.copy())
        obs._overflow_seen.update(
            nm for nm in node_names if nm not in tracked
        )
        cap = 4 * _TOP_CANDIDATES
        for i in full_order.tolist():
            if len(obs._overflow_top) >= cap:
                break
            if node_names[i] in tracked:
                continue
            rnd = int(rounds_arr[i]) if rounds_arr is not None else -1
            obs._overflow_top[node_names[i]] = (float(rnd), rnd)
    obs._overflow_gauge.set(len(obs._overflow_seen))

    doc = obs.snapshot()
    doc["virtual"] = True
    fill = arrays.get("cohort_fill")
    win = arrays.get("window")
    wfill = arrays.get("window_fill")
    for i in order:
        entry = doc["peers"].get(node_names[i])
        if entry is None:
            continue
        # Realized solicitation fraction under cohort sampling (the
        # population engine's fairness metric); None when the run carried
        # no cohort_fill array — fed_top prints "-" then. window /
        # window_fill likewise are async-population facts: the last window
        # this vnode folded into (-1: never) and its realized fold
        # fraction; None on sync runs.
        entry["cohort_fill"] = (
            round(float(fill[i]), 4) if fill is not None else None
        )
        entry["window"] = int(win[i]) if win is not None else None
        entry["window_fill"] = (
            round(float(wfill[i]), 4) if wfill is not None else None
        )
        if extras:
            entry["loss"] = extras.get("train_loss")
            entry["gnorm"] = extras.get("update_norm_p90")
            entry["trip"] = extras.get("tripped")
            if extras.get("mem_bytes"):
                entry["mem_bytes"] = float(extras["mem_bytes"])
    if extras:
        doc["devobs"] = dict(extras)
    return doc


def snapshot_shape_diff(
    fused: Dict[str, Any], wire: Dict[str, Any]
) -> List[str]:
    """Shape-parity check between a fused population snapshot and a wire
    ``Observatory.snapshot()``: every key family the wire document exposes
    must exist in the fused one (the fused doc may carry extras — cohort
    fill, devobs columns — but never less). Returns the missing keys,
    prefixed ``top-level:`` / ``peer:`` / ``fleet:``; empty means parity."""

    def peer_keys(doc: Dict[str, Any]) -> set:
        ks: set = set()
        for p in (doc.get("peers") or {}).values():
            if isinstance(p, dict):
                ks |= set(p)
        return ks

    out = [f"top-level:{k}" for k in sorted(set(wire) - set(fused))]
    out += [f"peer:{k}" for k in sorted(peer_keys(wire) - peer_keys(fused))]
    out += [
        f"fleet:{k}"
        for k in sorted(
            set(wire.get("fleet") or {}) - set(fused.get("fleet") or {})
        )
    ]
    return out


__all__ = [
    "Observatory",
    "SNAPSHOT_SCHEMA_VERSION",
    "STALE_AFTER_S",
    "mesh_chunk_telemetry",
    "mesh_trip",
    "population_snapshot",
    "snapshot_shape_diff",
    "write_snapshot_doc",
]
