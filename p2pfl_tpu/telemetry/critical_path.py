"""Round critical-path analysis over the federation span DAG.

Every remaining ROADMAP frontier (async aggregation, comm/compute overlap,
population scale) is a wall-clock problem, and the per-stage spans from the
telemetry plane already record where each node's time went — but attribution
was manual: "which span on WHICH node gated this round?" had to be answered
by eyeballing a Perfetto timeline. This module answers it mechanically.

The model: a federated round is a DAG of spans. Within a node, stage spans
are sequential (the workflow runs them one after another). Across nodes, a
*wait* span (``aggregation_wait``, ``full_model_wait``, ``vote_rtt``, the
``diffuse:*`` gossip loops) ends because a frame ARRIVED — and the receiving
``recv:*``/``apply:*`` span is parented onto the sender's span through the
wire trace context, so the edge back to the gating sender is already in the
span table. The critical path is a backward walk from the round's
last-finishing span: a wait span is resolved through the recv span that
ended it (jumping to the sender's then-active span); a compute span is
resolved to its same-node predecessor. Each hop attributes the walked
wall-clock interval to the span that actually occupied it, so a node that
merely *waited* contributes ~nothing while the straggler whose ``fit`` held
everyone up carries the time — the gating node falls out as an argmax.

Clock domains: spans recorded by ONE tracer share one monotonic clock and
need no correction. Traces exported by DIFFERENT processes (a real gRPC
deployment) are merged via each export's wall-clock epoch anchor
(``Tracer.wall_epoch``), with residual NTP skew corrected from the
heartbeater's per-peer clock-skew measurements — either passed explicitly
(``skew_s``) or read from the ``peer_clock_skew_s`` annotation that
``CommunicationProtocol.export_trace`` stamps onto each dump.

Outputs (``CriticalPathAnalyzer.report()``):

* per-round critical paths: the gating node + the span chain with per-hop
  attributed seconds,
* per-round and aggregate stage wall-clock shares (where does a round's
  node-time actually go),
* a train<->diffuse overlap report: how much model diffusion time overlaps
  local training on the same node (today: ~0 — the measured headroom
  ROADMAP item 4 claims by overlapping them).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from p2pfl_tpu.telemetry.metrics import REGISTRY, MetricsRegistry
from p2pfl_tpu.telemetry.tracing import TRACER, Span, Tracer

#: Fine-grained stage-work spans that carry a round and form path segments.
#: Async-scheduler spans ride the same machinery — a WINDOW is a round to
#: the walk (the ``round`` span arg carries the window index).
FINE_SPANS = (
    "vote_rtt",
    "fit",
    "aggregation_wait",
    "full_model_wait",
    "diffuse:init_model",
    "diffuse:partial_model",
    "diffuse:full_model",
    "diffuse:async_model",
    "async_window_wait",
)

#: Zero-duration diagnosis markers the async scheduler drops per window
#: (close reason, mean folded lag, fill) — consumed by the window report,
#: never path segments.
WINDOW_MARKER = "window_close"

#: Spans that end because a remote frame arrived, and the recv/apply span
#: names that can resolve them. Order matters: earlier names are preferred
#: (``recv:*`` before ``apply:*`` — the recv span's parent IS the sender's
#: span, while an apply span parents onto the local recv around it).
WAIT_RESOLVERS: Dict[str, Tuple[str, ...]] = {
    "aggregation_wait": ("recv:partial_model", "apply:partial_model"),
    "full_model_wait": ("recv:full_model", "apply:full_model"),
    "vote_rtt": ("recv:vote_train_set",),
    "diffuse:init_model": ("recv:model_initialized",),
    # Partial-model gossip relays CONTENT: what a node can send at time t
    # is bounded by the partials that reached it by t, so content arrivals
    # are preferred over coverage acks — the walk then chases a relayed
    # contribution back through intermediate nodes to its slow origin.
    "diffuse:partial_model": (
        "recv:partial_model",
        "apply:partial_model",
        "recv:models_aggregated",
        "recv:models_ready",
    ),
    "diffuse:full_model": ("recv:models_ready",),
    # An async window's fill wait ends because a contribution arrived; the
    # recv span's parent link crosses the wire to the (possibly slow)
    # contributor whose frame closed the window.
    "async_window_wait": ("recv:async_model", "apply:async_model"),
}

#: Container spans (whole-stage / whole-experiment) — never path segments.
_CONTAINER_SUFFIXES = ("Stage",)
_CONTAINER_NAMES = ("experiment", "set_start_learning")


def _is_recv(name: str) -> bool:
    return name.startswith("recv:") or name.startswith("apply:")


def _is_container(name: str) -> bool:
    return name in _CONTAINER_NAMES or name.endswith(_CONTAINER_SUFFIXES)


@dataclass
class Seg:
    """One normalized span on the merged timeline (start/end in shared s)."""

    name: str
    node: str
    start_s: float
    end_s: float
    span_id: str
    parent_id: str
    trace_id: str
    round: Optional[int]
    #: raw span args (close reason, mean lag, ... — window markers carry
    #: their diagnosis here; empty for most spans).
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def dur_s(self) -> float:
        return self.end_s - self.start_s


@dataclass
class PathHop:
    """One hop of a round's critical path, earliest first.

    ``attributed_s`` is the slice of round wall-clock this hop is
    responsible for ON the path (a wait span resolved by a remote arrival
    is attributed only its post-arrival tail, not the whole wait).
    """

    node: str
    name: str
    start_s: float
    end_s: float
    attributed_s: float
    kind: str  # "compute" | "wait" | "recv"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "node": self.node,
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "end_s": round(self.end_s, 6),
            "attributed_s": round(self.attributed_s, 6),
            "kind": self.kind,
        }


@dataclass
class RoundPath:
    round: int
    gating_node: Optional[str]
    hops: List[PathHop] = field(default_factory=list)
    wall_s: float = 0.0
    attributed_by_node: Dict[str, float] = field(default_factory=dict)
    coverage: float = 0.0  # attributed path time / round wall-clock

    def to_dict(self) -> Dict[str, Any]:
        return {
            "round": self.round,
            "gating_node": self.gating_node,
            "wall_s": round(self.wall_s, 6),
            "coverage": round(self.coverage, 4),
            "attributed_by_node": {
                n: round(v, 6) for n, v in self.attributed_by_node.items()
            },
            "path": [h.to_dict() for h in self.hops],
        }


def skew_from_registry(
    reference_node: str, registry: MetricsRegistry = REGISTRY
) -> Dict[str, float]:
    """Per-node skew corrections from the heartbeat clock-skew gauge.

    The gauge records ``receiver wall - sender-stamped beat timestamp``; for
    ``reference_node`` as receiver that is (up to one-way latency) how far
    each peer's wall clock lags the reference's. Adding the returned value
    to a peer's wall-clock timestamps maps them into the reference's clock
    domain — the convention :class:`CriticalPathAnalyzer` expects.
    """
    out: Dict[str, float] = {}
    fam = registry.get("p2pfl_heartbeat_clock_skew_seconds")
    if fam is None:
        return out
    for labels, child in fam.samples():
        if labels.get("node") == reference_node and labels.get("peer"):
            out[labels["peer"]] = float(child.value)
    return out


class CriticalPathAnalyzer:
    """Assemble the per-round span DAG and walk its critical paths.

    Args:
        segs: normalized spans on ONE shared timeline (see the
            ``from_tracer`` / ``from_chrome_traces`` constructors).
        slack_s: causal tolerance when matching arrivals to waits and
            predecessors to successors — covers the 0.5 s event-wait slices
            in the stage machine plus gossip tick jitter.
    """

    def __init__(self, segs: Sequence[Seg], slack_s: float = 1.0) -> None:
        self.slack_s = float(slack_s)
        self._fine = sorted(
            (s for s in segs if s.name in FINE_SPANS), key=lambda s: s.start_s
        )
        self._recv = sorted(
            (s for s in segs if _is_recv(s.name)), key=lambda s: s.end_s
        )
        self._markers = [s for s in segs if s.name == WINDOW_MARKER]
        self._by_id = {s.span_id: s for s in segs if s.span_id}
        self._fine_by_node: Dict[str, List[Seg]] = {}
        for s in self._fine:
            self._fine_by_node.setdefault(s.node, []).append(s)

    # --- constructors --------------------------------------------------------

    @classmethod
    def from_tracer(
        cls,
        tracer: Tracer = TRACER,
        skew_s: Optional[Dict[str, float]] = None,
        slack_s: float = 1.0,
    ) -> "CriticalPathAnalyzer":
        """All spans share the tracer's clock; ``skew_s`` is for tests."""
        skew = skew_s or {}
        segs = [
            Seg(
                name=s.name,
                node=s.node,
                start_s=s.start_s + skew.get(s.node, 0.0),
                end_s=s.start_s + s.dur_s + skew.get(s.node, 0.0),
                span_id=s.span_id,
                parent_id=s.parent_id,
                trace_id=s.trace_id,
                round=_round_of(s.args),
                extra=dict(s.args),
            )
            for s in tracer.spans()
        ]
        return cls(segs, slack_s=slack_s)

    @classmethod
    def from_chrome_traces(
        cls,
        docs: Iterable[Dict[str, Any]],
        skew_s: Optional[Dict[str, float]] = None,
        auto_skew: bool = True,
        slack_s: float = 1.0,
    ) -> "CriticalPathAnalyzer":
        """Merge per-process ``export_chrome_trace`` documents.

        Each document's µs timestamps are mapped onto the wall clock through
        its ``metadata.wall_epoch_s`` anchor. The FIRST document is the
        reference clock domain; with ``auto_skew`` (default), other
        documents whose ``metadata.node`` appears in the reference's
        ``peer_clock_skew_s`` annotation (written by
        ``CommunicationProtocol.export_trace``) are shifted by that measured
        skew. Explicit ``skew_s`` entries (node -> seconds to add) win over
        the automatic ones.
        """
        docs = list(docs)
        ref_skews: Dict[str, float] = {}
        if docs:
            ref_skews = dict(
                (docs[0].get("metadata") or {}).get("peer_clock_skew_s") or {}
            )
        segs: List[Seg] = []
        for i, doc in enumerate(docs):
            meta = doc.get("metadata") or {}
            epoch = float(meta.get("wall_epoch_s", 0.0))
            doc_node = meta.get("node", "")
            pid_names: Dict[int, str] = {}
            for ev in doc.get("traceEvents", []):
                if ev.get("ph") == "M" and ev.get("name") == "process_name":
                    pid_names[ev["pid"]] = ev.get("args", {}).get("name", "")
            for ev in doc.get("traceEvents", []):
                if ev.get("ph") != "X":
                    continue
                node = pid_names.get(ev.get("pid"), "") or doc_node
                shift = 0.0
                if i > 0 and auto_skew:
                    # Auto-correction keys on the EXPORTING node's identity:
                    # per-process deployments have one node per document.
                    key = doc_node or node
                    shift = ref_skews.get(key, 0.0)
                if skew_s and node in skew_s:
                    shift = skew_s[node]
                elif skew_s and doc_node in skew_s:
                    shift = skew_s[doc_node]
                args = ev.get("args", {})
                start = ev["ts"] / 1e6 + epoch + shift
                segs.append(
                    Seg(
                        name=ev.get("name", ""),
                        node=node,
                        start_s=start,
                        end_s=start + ev.get("dur", 0.0) / 1e6,
                        span_id=str(args.get("span_id", "")),
                        parent_id=str(args.get("parent_id", "")),
                        trace_id=str(args.get("trace_id", "")),
                        round=_round_of(args),
                        extra={
                            k: v for k, v in args.items()
                            if k not in ("trace_id", "span_id", "parent_id")
                        },
                    )
                )
        return cls(segs, slack_s=slack_s)

    # --- round inventory -----------------------------------------------------

    def rounds(self) -> List[int]:
        return sorted({s.round for s in self._fine if s.round is not None})

    def nodes(self) -> List[str]:
        return sorted(self._fine_by_node)

    # --- the backward gating walk -------------------------------------------

    def round_path(self, rnd: int, max_hops: int = 256) -> RoundPath:
        spans_r = [s for s in self._fine if s.round == rnd]
        if not spans_r:
            return RoundPath(round=rnd, gating_node=None)
        terminal = max(spans_r, key=lambda s: s.end_s)
        round_start = min(s.start_s for s in spans_r)

        hops: List[PathHop] = []
        visited: set = set()
        cur: Optional[Seg] = terminal
        cursor = terminal.end_s  # walked-down-to time on the path

        def clamp(upper: float, lower: float) -> float:
            # Attribution counts only time inside THIS round's window: the
            # walk may continue through earlier rounds for continuity, but
            # a prior round's span must not inflate this round's totals.
            return max(0.0, min(upper, terminal.end_s) - max(lower, round_start))

        while cur is not None and len(hops) < max_hops:
            visited.add(cur.span_id)

            # A wait span's END was caused by a remote arrival: jump to the
            # sender — unless the sender chain cycles back onto a span
            # already on the path (ack loops: our send -> peer's ack -> us),
            # in which case the wait falls through to the predecessor rule.
            resolver = WAIT_RESOLVERS.get(cur.name)
            # A wait span the walk entered within a sliver of its START
            # explains nothing by its arrivals — the cause is upstream of
            # the span itself (it started late). Skip arrival resolution
            # and chain to the same-node predecessor (e.g. the slow fit
            # that delayed this node's own gossip).
            can_jump = resolver is not None and cursor - cur.start_s >= 0.3
            if can_jump:
                jumped = False
                # Latest-first: the most recent arrival explains the wait's
                # end, but when its sender is already on the path (gossip
                # relays bounce content both ways), the next-latest arrival
                # — e.g. the slow origin's own contribution — still does.
                for arrival in self._resolving_arrivals(cur, resolver, rnd, cursor):
                    sender = self._sender_span(arrival, arrival.start_s, rnd)
                    if sender is None or sender.span_id in visited:
                        continue
                    boundary = max(cur.start_s, min(cursor, arrival.start_s))
                    hops.append(
                        PathHop(
                            node=cur.node, name=cur.name,
                            start_s=cur.start_s, end_s=cur.end_s,
                            attributed_s=clamp(min(cursor, cur.end_s), boundary),
                            kind="wait",
                        )
                    )
                    hops.append(
                        PathHop(
                            node=arrival.node, name=arrival.name,
                            start_s=arrival.start_s, end_s=arrival.end_s,
                            attributed_s=0.0, kind="recv",
                        )
                    )
                    cursor = boundary
                    cur = sender
                    jumped = True
                    break
                if jumped:
                    continue

            # Compute hop (or wait with no resolvable/fresh sender):
            # attribute [start, cursor], then walk the same-node
            # predecessor chain; a dead end falls back to the globally
            # latest unvisited span before this one (the walk must reach
            # round start, not stop at the first bookkeeping gap).
            # A span explains at most its own interval: time between its
            # end and the cursor is an unexplained gap, left unattributed
            # (visible as coverage < 1) rather than mis-charged here.
            hops.append(
                PathHop(
                    node=cur.node, name=cur.name,
                    start_s=cur.start_s, end_s=cur.end_s,
                    attributed_s=clamp(min(cursor, cur.end_s), cur.start_s),
                    kind="wait" if resolver is not None else "compute",
                )
            )
            cursor = min(cursor, cur.start_s)
            if cursor <= round_start + 1e-9:
                break
            nxt = self._predecessor(cur, visited, rnd)
            if nxt is None:
                nxt = self._global_predecessor(cur, visited, rnd)
            cur = nxt

        hops.reverse()
        attributed: Dict[str, float] = {}
        for h in hops:
            attributed[h.node] = attributed.get(h.node, 0.0) + h.attributed_s
        wall = terminal.end_s - round_start
        gating = max(attributed, key=lambda n: attributed[n]) if attributed else None
        return RoundPath(
            round=rnd,
            gating_node=gating,
            hops=hops,
            wall_s=wall,
            attributed_by_node=attributed,
            coverage=(sum(attributed.values()) / wall) if wall > 0 else 0.0,
        )

    def _resolving_arrivals(
        self, wait: Seg, names: Tuple[str, ...], rnd: int, cursor: float,
        limit: int = 8,
    ) -> List[Seg]:
        """Matching recv/apply spans on the waiting node that ended inside
        the wait window, AS OF the walk cursor (a span reached mid-interval
        via a relay jump is resolved by what had arrived by that moment,
        not by later traffic). ``names`` are tried in preference order
        (recv before apply: the recv span's parent link crosses the wire
        to the sender); within a name, latest arrivals first."""
        upper = min(wait.end_s, cursor) + self.slack_s
        for name in names:
            found = [
                s
                for s in self._recv
                if s.node == wait.node
                and s.name == name
                and (s.round is None or s.round == rnd)
                and wait.start_s - self.slack_s < s.end_s <= upper
            ]
            if found:
                found.sort(key=lambda s: s.end_s, reverse=True)
                return found[:limit]
        return []

    def _sender_span(self, arrival: Seg, cursor: float, rnd: int) -> Optional[Seg]:
        """Continue the walk on the sender: the frame left the sender around
        ``arrival.start_s``, so the gating span is the sender's fine span
        active (or last finished) at that moment. The arrival's parent link
        names the sender's span directly; a receiver-side parent (an apply
        nested in its recv) is walked up first, and a container parent (a
        whole-stage span) is refined to the sender's then-current fine
        span. Spans from LATER rounds are never picked — a backward walk
        must not wander into the future."""
        parent = self._by_id.get(arrival.parent_id)
        walked = 0
        while parent is not None and walked < 4 and _is_recv(parent.name):
            parent = self._by_id.get(parent.parent_id)
            walked += 1
        if (
            parent is not None
            and parent.name in FINE_SPANS
            and not self._future(parent, rnd)
        ):
            return parent
        node = parent.node if parent is not None else ""
        if not node:
            return None
        future_slack = min(0.25, self.slack_s)
        cands = [
            s
            for s in self._fine_by_node.get(node, [])
            if s.start_s <= cursor + future_slack and not self._future(s, rnd)
        ]
        if not cands:
            return None
        # Prefer a span actually covering the cursor; else the latest one.
        covering = [s for s in cands if s.end_s >= cursor - self.slack_s]
        pool = covering or cands
        return max(pool, key=lambda s: s.start_s)

    @staticmethod
    def _future(s: Seg, rnd: int) -> bool:
        return s.round is not None and s.round > rnd

    def _predecessor(self, cur: Seg, visited: set, rnd: int) -> Optional[Seg]:
        """Latest same-node fine span ending at or before ``cur`` starts."""
        best: Optional[Seg] = None
        for s in self._fine_by_node.get(cur.node, []):
            if s is cur or s.span_id in visited or self._future(s, rnd):
                continue
            if s.end_s <= cur.start_s + self.slack_s and s.start_s < cur.start_s:
                if best is None or s.end_s > best.end_s:
                    best = s
        return best

    def _global_predecessor(self, cur: Seg, visited: set, rnd: int) -> Optional[Seg]:
        """Cross-node fallback when a node's own history runs dry: the
        latest unvisited fine span (any node) that ended before ``cur``
        started — "what was the fleet doing just before this"."""
        best: Optional[Seg] = None
        for s in self._fine:
            if s.span_id in visited or self._future(s, rnd):
                continue
            if s.end_s <= cur.start_s + self.slack_s and s.start_s < cur.start_s:
                if best is None or s.end_s > best.end_s:
                    best = s
        return best

    # --- aggregate reports ---------------------------------------------------

    def stage_shares(self, rnd: Optional[int] = None) -> Dict[str, Any]:
        """Summed wall-clock by stage-span name (across nodes), with shares
        of the total — where a round's node-time goes, path or not."""
        spans = [
            s
            for s in self._fine
            if rnd is None or s.round == rnd
        ]
        totals: Dict[str, float] = {}
        for s in spans:
            totals[s.name] = totals.get(s.name, 0.0) + s.dur_s
        grand = sum(totals.values())
        return {
            "total_span_s": round(grand, 6),
            "by_stage_s": {k: round(v, 6) for k, v in sorted(totals.items())},
            "shares": {
                k: round(v / grand, 4) if grand > 0 else 0.0
                for k, v in sorted(totals.items())
            },
        }

    def overlap_report(self, rnd: Optional[int] = None) -> Dict[str, Any]:
        """Train<->diffuse overlap: how much of each node's ``diffuse:*``
        time overlaps its OWN ``fit`` time (the comm/compute overlap ROADMAP
        item 4 wants to create — ~0 while the stage machine serializes
        train -> gossip), plus the fleet-level fraction of diffusion time
        during which ANY node was fitting (the coordination headroom)."""
        fits: Dict[str, List[Tuple[float, float]]] = {}
        diffs: Dict[str, List[Tuple[float, float]]] = {}
        for s in self._fine:
            if rnd is not None and s.round != rnd:
                continue
            if s.name == "fit":
                fits.setdefault(s.node, []).append((s.start_s, s.end_s))
            elif s.name.startswith("diffuse:"):
                diffs.setdefault(s.node, []).append((s.start_s, s.end_s))
        all_fit = _merge_intervals([iv for l in fits.values() for iv in l])
        per_node = {}
        fit_total = sum(e - s for l in fits.values() for s, e in l)
        diff_total = 0.0
        same_node_overlap = 0.0
        fleet_overlap = 0.0
        for node, dl in diffs.items():
            dl_m = _merge_intervals(dl)
            node_diff = sum(e - s for s, e in dl_m)
            node_overlap = _intersection_s(dl_m, _merge_intervals(fits.get(node, [])))
            fleet = _intersection_s(dl_m, all_fit)
            diff_total += node_diff
            same_node_overlap += node_overlap
            fleet_overlap += fleet
            per_node[node] = {
                "diffuse_s": round(node_diff, 6),
                "overlap_with_own_fit_s": round(node_overlap, 6),
                "overlap_with_any_fit_s": round(fleet, 6),
            }
        return {
            "fit_total_s": round(fit_total, 6),
            "diffuse_total_s": round(diff_total, 6),
            "train_diffuse_overlap_s": round(same_node_overlap, 6),
            "train_diffuse_overlap_fraction": round(
                same_node_overlap / diff_total, 4
            )
            if diff_total > 0
            else 0.0,
            "diffuse_under_any_fit_fraction": round(fleet_overlap / diff_total, 4)
            if diff_total > 0
            else 0.0,
            "serialized_diffuse_s": round(diff_total - same_node_overlap, 6),
            "per_node": per_node,
            "note": "overlap_fraction ~0 means train -> diffuse is fully "
            "serialized on every node; serialized_diffuse_s is the headroom "
            "ROADMAP item 4 (comm/compute overlap) can reclaim",
        }

    # --- async window attribution --------------------------------------------

    def has_windows(self) -> bool:
        """True when the trace came from the async scheduler (window spans
        or close markers present)."""
        return bool(self._markers) or any(
            s.name in ("async_window_wait", "diffuse:async_model")
            for s in self._fine
        )

    def window_report(self, staleness_alpha: Optional[float] = None) -> Dict[str, Any]:
        """Per-window attribution for async (Papaya/FedBuff) traces.

        A window is a round to the backward gating walk — the async spans
        (``fit``, ``diffuse:async_model``, ``async_window_wait``) are
        registered fine spans, so :meth:`round_path` already answers "which
        CONTRIBUTOR gated this window" (the wait resolves through the
        ``recv:async_model`` whose arrival closed it, chasing back to the
        slow origin). On top of the walk, each window's ``window_close``
        marker (close reason, mean folded lag, fill) yields:

        * **close-reason breakdown** — fill target met vs live-shrunk
          target vs timeout, per window and aggregated;
        * **staleness-discount vs wall-clock attribution** — the two
          currencies the async scheduler can pay a straggler in: waiting
          for it (``wait_s``, wall-clock on the window's critical path) or
          accepting its stale contribution at a discount
          (``discount_fraction = 1 - (1+mean_lag)^-alpha``, aggregate
          weight given up to staleness). A fleet paying mostly wall-clock
          wants a smaller fill target; one paying mostly discount wants a
          larger alpha or a staleness cap.
        """
        if staleness_alpha is None:
            from p2pfl_tpu.config import Settings

            staleness_alpha = Settings.ASYNC_STALENESS_ALPHA
        # Markers by window, newest-wins per (window, node); windows come
        # from markers AND fine spans (a window that died before its close
        # marker still shows its path).
        marks: Dict[int, List[Seg]] = {}
        for m in self._markers:
            if m.round is not None:
                marks.setdefault(m.round, []).append(m)
        windows = sorted(set(self.rounds()) | set(marks))
        out_windows: Dict[str, Any] = {}
        reason_counts: Dict[str, int] = {}
        gating_counts: Dict[str, int] = {}
        total_wait_s = 0.0
        discount_weighted = 0.0
        for w in windows:
            path = self.round_path(w)
            if path.gating_node:
                gating_counts[path.gating_node] = (
                    gating_counts.get(path.gating_node, 0) + 1
                )
            wait_s = sum(
                s.dur_s
                for s in self._fine
                if s.round == w and s.name == "async_window_wait"
            )
            total_wait_s += wait_s
            wmarks = marks.get(w, [])
            reasons = sorted({str(m.extra.get("reason", "")) for m in wmarks} - {""})
            for r in reasons:
                reason_counts[r] = reason_counts.get(r, 0) + 1
            lags = [
                float(m.extra.get("mean_lag", 0.0))
                for m in wmarks
                if m.extra.get("mean_lag") is not None
            ]
            mean_lag = sum(lags) / len(lags) if lags else 0.0
            discount = 1.0 - (1.0 + mean_lag) ** (-float(staleness_alpha))
            discount_weighted += discount
            fills = [
                int(m.extra.get("fill", 0)) for m in wmarks if m.extra.get("fill")
            ]
            out_windows[str(w)] = {
                "gating_contributor": path.gating_node,
                "wall_s": path.to_dict()["wall_s"],
                "coverage": path.to_dict()["coverage"],
                "wait_s": round(wait_s, 6),
                "close_reasons": reasons,
                "mean_lag": round(mean_lag, 4),
                "staleness_discount": round(discount, 4),
                "fill": max(fills) if fills else None,
                "attributed_by_node": path.to_dict()["attributed_by_node"],
            }
        top = (
            max(gating_counts, key=lambda n: gating_counts[n])
            if gating_counts
            else None
        )
        n_win = len(windows)
        return {
            "windows": out_windows,
            "close_reason_counts": dict(sorted(reason_counts.items())),
            "gating_counts": gating_counts,
            "top_gating_contributor": top,
            "top_gating_fraction": (
                round(gating_counts.get(top, 0) / n_win, 4) if top and n_win else 0.0
            ),
            "staleness_alpha": float(staleness_alpha),
            "wait_wall_s_total": round(total_wait_s, 6),
            "mean_staleness_discount": (
                round(discount_weighted / n_win, 4) if n_win else 0.0
            ),
            "note": "wait_wall_s_total is the wall-clock currency paid "
            "waiting on contributions; mean_staleness_discount is the "
            "aggregate-weight currency paid accepting stale ones",
        }

    def report(self) -> Dict[str, Any]:
        """The full attribution report: one entry per round plus aggregates."""
        rounds = self.rounds()
        paths = {r: self.round_path(r) for r in rounds}
        gating_counts: Dict[str, int] = {}
        for p in paths.values():
            if p.gating_node:
                gating_counts[p.gating_node] = gating_counts.get(p.gating_node, 0) + 1
        top = max(gating_counts, key=lambda n: gating_counts[n]) if gating_counts else None
        return {
            **({"window_report": self.window_report()} if self.has_windows() else {}),
            "rounds": {str(r): paths[r].to_dict() for r in rounds},
            "stage_shares_by_round": {
                str(r): self.stage_shares(r) for r in rounds
            },
            "stage_shares": self.stage_shares(),
            "overlap": self.overlap_report(),
            "gating_node_counts": gating_counts,
            "top_gating_node": top,
            "top_gating_fraction": round(
                gating_counts.get(top, 0) / len(rounds), 4
            )
            if top and rounds
            else 0.0,
            "nodes": self.nodes(),
        }


def _round_of(args: Dict[str, Any]) -> Optional[int]:
    r = args.get("round")
    try:
        return int(r) if r is not None else None
    except (TypeError, ValueError):
        return None


def _merge_intervals(ivs: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not ivs:
        return []
    ivs = sorted(ivs)
    out = [list(ivs[0])]
    for s, e in ivs[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _intersection_s(
    a: List[Tuple[float, float]], b: List[Tuple[float, float]]
) -> float:
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def load_chrome_trace(path: str) -> Dict[str, Any]:
    """Read one exported trace document from disk (tiny convenience so the
    offline merge story is one import)."""
    with open(path) as f:
        return json.load(f)


__all__ = [
    "CriticalPathAnalyzer",
    "PathHop",
    "RoundPath",
    "Seg",
    "FINE_SPANS",
    "WAIT_RESOLVERS",
    "WINDOW_MARKER",
    "skew_from_registry",
    "load_chrome_trace",
]
