"""Export surfaces for the metrics registry.

* :func:`render_prometheus` — Prometheus text exposition format 0.0.4
  (``# HELP`` / ``# TYPE`` headers, escaped labels, cumulative histogram
  buckets with ``le`` plus ``_sum``/``_count``), scrapeable as-is.
* :func:`snapshot` — JSON-able dict of every family and series, the shape
  ``bench.py --telemetry`` embeds into its BENCH json and the
  ``make telemetry-check`` gate asserts against.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

from p2pfl_tpu.telemetry.metrics import Histogram, MetricsRegistry, REGISTRY


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in merged.items())
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry: MetricsRegistry = REGISTRY) -> str:
    """Render every family in ``registry`` as Prometheus exposition text."""
    out = []
    for fam in registry.collect():
        if fam.help:
            out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        out.append(f"# TYPE {fam.name} {fam.kind}")
        if isinstance(fam, Histogram):
            for labels, child in fam.samples():
                bounds, counts, total, count = child.snapshot()
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    le = _fmt_labels(labels, {"le": _fmt_value(b)})
                    out.append(f"{fam.name}_bucket{le} {cum}")
                cum += counts[-1]
                le = _fmt_labels(labels, {"le": "+Inf"})
                out.append(f"{fam.name}_bucket{le} {cum}")
                out.append(f"{fam.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}")
                out.append(f"{fam.name}_count{_fmt_labels(labels)} {count}")
        else:
            for labels, child in fam.samples():
                out.append(f"{fam.name}{_fmt_labels(labels)} {_fmt_value(child.value)}")
    return "\n".join(out) + "\n"


def snapshot(registry: MetricsRegistry = REGISTRY) -> Dict[str, Any]:
    """JSON-able snapshot: family name -> {type, help, samples: [...]}.

    Counter/gauge samples are ``{"labels": {...}, "value": v}``; histogram
    samples carry ``buckets`` (upper-bound -> non-cumulative count), ``sum``
    and ``count``.
    """
    snap: Dict[str, Any] = {}
    for fam in registry.collect():
        samples = []
        if isinstance(fam, Histogram):
            for labels, child in fam.samples():
                bounds, counts, total, count = child.snapshot()
                samples.append(
                    {
                        "labels": labels,
                        "buckets": {
                            **{_fmt_value(b): c for b, c in zip(bounds, counts)},
                            "+Inf": counts[-1],
                        },
                        "sum": total,
                        "count": count,
                    }
                )
        else:
            for labels, child in fam.samples():
                samples.append({"labels": labels, "value": child.value})
        snap[fam.name] = {"type": fam.kind, "help": fam.help, "samples": samples}
    return snap


__all__ = ["render_prometheus", "snapshot"]
