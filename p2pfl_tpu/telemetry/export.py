"""Export surfaces for the metrics registry.

* :func:`render_prometheus` — Prometheus text exposition format 0.0.4
  (``# HELP`` / ``# TYPE`` headers, escaped labels, cumulative histogram
  buckets with ``le`` plus ``_sum``/``_count``), scrapeable as-is. Every
  histogram family additionally exposes a ``<name>_quantile`` gauge family
  with ``quantile="0.5|0.9|0.99"`` labels (estimated by linear
  interpolation inside the covering bucket), and the process-wide sketch
  registry exposes ``p2pfl_sketch_<metric>`` gauge families in the same
  quantile-label form — dashboards read p50/p90/p99 directly instead of
  re-deriving them from bucket counts.
* :func:`snapshot` — JSON-able dict of every family and series, the shape
  ``bench.py --telemetry`` embeds into its BENCH json and the
  ``make telemetry-check`` gate asserts against.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from p2pfl_tpu.telemetry.metrics import Histogram, MetricsRegistry, REGISTRY

#: The quantiles exposed for histograms and sketches (Prometheus summary-
#: style ``quantile`` label values).
EXPORT_QUANTILES: Tuple[float, ...] = (0.5, 0.9, 0.99)


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in merged.items())
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def hist_quantile(
    bounds: Sequence[float], counts: Sequence[int], q: float
) -> float:
    """Estimate the ``q``-quantile from non-cumulative histogram buckets
    (linear interpolation inside the covering bucket; values in the +Inf
    bucket report the highest finite bound). NaN when empty."""
    total = sum(counts)
    if total <= 0:
        return float("nan")
    rank = min(1.0, max(0.0, q)) * total
    cum = 0.0
    lower = 0.0
    for b, c in zip(bounds, counts):
        if cum + c >= rank and c > 0:
            frac = (rank - cum) / c
            return lower + frac * (b - lower)
        cum += c
        lower = b
    return float(bounds[-1])  # +Inf bucket: clamp to the last finite bound


def _quantile_lines(
    name: str, rows: List[Tuple[Dict[str, str], Dict[float, float]]]
) -> List[str]:
    """Summary-style quantile gauge family lines (skips empty series)."""
    out: List[str] = []
    emitted_header = False
    for labels, quantiles in rows:
        for q, v in quantiles.items():
            if math.isnan(v):
                continue
            if not emitted_header:
                out.append(f"# TYPE {name} gauge")
                emitted_header = True
            lbl = _fmt_labels(labels, {"quantile": _fmt_value(q)})
            out.append(f"{name}{lbl} {_fmt_value(v)}")
    return out


def render_prometheus(registry: MetricsRegistry = REGISTRY) -> str:
    """Render every family in ``registry`` as Prometheus exposition text,
    followed by derived ``<name>_quantile`` families for histograms and
    ``p2pfl_sketch_<metric>`` families for the sketch registry."""
    out = []
    quantile_rows: List[Tuple[str, List[Tuple[Dict[str, str], Dict[float, float]]]]] = []
    for fam in registry.collect():
        if fam.help:
            out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        out.append(f"# TYPE {fam.name} {fam.kind}")
        if isinstance(fam, Histogram):
            fam_rows: List[Tuple[Dict[str, str], Dict[float, float]]] = []
            for labels, child in fam.samples():
                bounds, counts, total, count = child.snapshot()
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    le = _fmt_labels(labels, {"le": _fmt_value(b)})
                    out.append(f"{fam.name}_bucket{le} {cum}")
                cum += counts[-1]
                le = _fmt_labels(labels, {"le": "+Inf"})
                out.append(f"{fam.name}_bucket{le} {cum}")
                out.append(f"{fam.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}")
                out.append(f"{fam.name}_count{_fmt_labels(labels)} {count}")
                fam_rows.append(
                    (
                        labels,
                        {
                            q: hist_quantile(bounds, counts, q)
                            for q in EXPORT_QUANTILES
                        },
                    )
                )
            quantile_rows.append((f"{fam.name}_quantile", fam_rows))
        else:
            for labels, child in fam.samples():
                out.append(f"{fam.name}{_fmt_labels(labels)} {_fmt_value(child.value)}")
    for name, rows in quantile_rows:
        out.extend(_quantile_lines(name, rows))
    # Sketch registry quantiles (only when the default registry is asked —
    # the sketch registry is process-global like it).
    if registry is REGISTRY:
        from p2pfl_tpu.telemetry.sketches import SKETCHES

        by_metric: Dict[str, List[Tuple[Dict[str, str], Dict[float, float]]]] = {}
        for metric, node in SKETCHES.names():
            sk = SKETCHES.get(metric, node)
            if sk is None or sk.count <= 0:
                continue
            safe = "".join(
                ch if (ch.isalnum() or ch in "_:") else "_" for ch in metric
            ) or "_"
            by_metric.setdefault(safe, []).append(
                ({"node": node}, {q: sk.quantile(q) for q in EXPORT_QUANTILES})
            )
        for metric in sorted(by_metric):
            out.extend(
                _quantile_lines(f"p2pfl_sketch_{metric}", by_metric[metric])
            )
    return "\n".join(out) + "\n"


def snapshot(registry: MetricsRegistry = REGISTRY) -> Dict[str, Any]:
    """JSON-able snapshot: family name -> {type, help, samples: [...]}.

    Counter/gauge samples are ``{"labels": {...}, "value": v}``; histogram
    samples carry ``buckets`` (upper-bound -> non-cumulative count), ``sum``
    and ``count``.
    """
    snap: Dict[str, Any] = {}
    for fam in registry.collect():
        samples = []
        if isinstance(fam, Histogram):
            for labels, child in fam.samples():
                bounds, counts, total, count = child.snapshot()
                samples.append(
                    {
                        "labels": labels,
                        "buckets": {
                            **{_fmt_value(b): c for b, c in zip(bounds, counts)},
                            "+Inf": counts[-1],
                        },
                        "sum": total,
                        "count": count,
                        "quantiles": {
                            f"p{int(round(q * 100))}": hist_quantile(bounds, counts, q)
                            for q in EXPORT_QUANTILES
                        }
                        if count
                        else {},
                    }
                )
        else:
            for labels, child in fam.samples():
                samples.append({"labels": labels, "value": child.value})
        snap[fam.name] = {"type": fam.kind, "help": fam.help, "samples": samples}
    return snap


__all__ = ["EXPORT_QUANTILES", "hist_quantile", "render_prometheus", "snapshot"]
