"""Process-wide metrics registry: labeled counters, gauges, histograms.

Design constraints (the reason this exists instead of pulling in
prometheus_client, which the image doesn't ship):

* **lock-cheap hot path** — gossip ticks, heartbeats and per-frame byte
  accounting increment counters from several threads at once. A child
  (one metric + one label combination) is a slotted object holding a
  plain ``threading.Lock`` and a float; ``inc()`` is acquire/add/release,
  a fraction of a microsecond in CPython. Hot callers resolve
  ``metric.labels(...)`` once and keep the child reference.
* **process-wide** — one registry serves every in-process node (the
  in-memory federation runs many nodes per process), so per-node series
  carry a ``node`` label rather than per-node registries.
* **reset for harnesses** — ``REGISTRY.reset()`` clears *values* but keeps
  the families registered, so module-level metric handles stay valid
  across bench/test runs.

Exposition (Prometheus text format, JSON snapshot) lives in
:mod:`p2pfl_tpu.telemetry.export`.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

LabelValues = Tuple[str, ...]

#: Default histogram buckets: spans µs-scale hot-path costs through the
#: multi-minute aggregation timeouts seen in real federations (seconds).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


class _CounterChild:
    """One (metric, label-values) series. Hot-path object."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class _GaugeChild:
    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Sequence[float]) -> None:
        self._lock = threading.Lock()
        self._bounds = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Tuple[Tuple[float, ...], List[int], float, int]:
        """(bounds, per-bucket counts, sum, count) — counts are NON-cumulative."""
        with self._lock:
            return self._bounds, list(self._counts), self._sum, self._count

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._sum = 0.0
            self._count = 0


class _MetricFamily:
    """Base: owns the children table keyed by label-value tuples."""

    kind = "untyped"
    _child_cls: type = _CounterChild

    def __init__(self, name: str, help: str, labelnames: Sequence[str]) -> None:
        _validate_name(name)
        for ln in labelnames:
            _validate_name(ln)
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[LabelValues, object] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            # Label-less metric: materialize the single child eagerly so
            # bare .inc()/.set()/.observe() on the family works.
            self._children[()] = self._new_child()

    def _new_child(self):
        return self._child_cls()

    def labels(self, *values: object, **kv: object) -> object:
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(str(kv[ln]) for ln in self.labelnames)
            except KeyError as exc:
                raise ValueError(f"missing label {exc} for {self.name}") from exc
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {values}"
            )
        # Fast path: plain dict read (safe under the GIL); slow path locked.
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._new_child())
        return child

    def samples(self) -> Iterable[Tuple[Dict[str, str], object]]:
        """(labels-dict, child) pairs — a consistent point-in-time copy of
        the children table (values are read per-child by the exporter)."""
        with self._lock:
            items = list(self._children.items())
        for values, child in items:
            yield dict(zip(self.labelnames, values)), child

    def clear(self) -> None:
        """Reset all children's values (the family stays registered)."""
        with self._lock:
            items = list(self._children.values())
        for child in items:
            child._reset()  # type: ignore[attr-defined]

    # --- label-less convenience --------------------------------------------

    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels {self.labelnames}; use .labels()")
        return self._children[()]


class Counter(_MetricFamily):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    @property
    def value(self) -> float:
        return self._solo().value


class Gauge(_MetricFamily):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float) -> None:
        self._solo().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    @property
    def value(self) -> float:
        return self._solo().value


class Histogram(_MetricFamily):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bounds
        super().__init__(name, help, labelnames)

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._solo().observe(value)


def _validate_name(name: str) -> None:
    if not name or not (name[0].isalpha() or name[0] == "_"):
        raise ValueError(f"invalid metric/label name {name!r}")
    for ch in name:
        if not (ch.isalnum() or ch in "_:"):
            raise ValueError(f"invalid metric/label name {name!r}")


class MetricsRegistry:
    """Get-or-create home for metric families.

    ``counter/gauge/histogram`` are idempotent by name (the common pattern is
    a module-level handle), but re-registering a name with a different kind
    or label set raises — silent divergence between two call sites would
    corrupt the series.
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, _MetricFamily] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, labels: Sequence[str], **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as {existing.kind} "
                        f"with labels {existing.labelnames}"
                    )
                return existing
            fam = cls(name, help, labels, **kw)
            self._metrics[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels, buckets=buckets)

    def get(self, name: str) -> Optional[_MetricFamily]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[_MetricFamily]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Zero every series but keep families registered — module-level
        handles survive (bench/tests call this between runs)."""
        for fam in self.collect():
            fam.clear()

    def clear_families(self, names: Sequence[str]) -> None:
        """Zero ONLY the named families (unknown names are fine — the
        family may simply not have instrumented yet this process). The
        campaign engine's scenario scoping: back-to-back scenarios in one
        process must each start their chaos-fault / admission-rejection /
        agg-wait counters from zero or replay-count assertions (and the
        adaptive adversary's rejection observations) would see the previous
        scenario's tail, while unrelated process-lifetime series (ledger
        event totals, resource gauges) keep accumulating."""
        for name in names:
            fam = self.get(name)
            if fam is not None:
                fam.clear()


#: The process-wide registry every subsystem instruments into.
REGISTRY = MetricsRegistry()
