"""Run context and evidence bundles — the fed_doctor capture plane.

Every observability stream the stack emits (trajectory ledger, flight
recorder, metrics registry, observatory snapshots, supervisor reports,
campaign records, bench meta blocks) is useful alone but only tells the
causal story when *joined* — and joining requires a shared key. This
module provides both halves:

* **Run context** — a federation-wide run id minted once per experiment
  or engine launch: a seeded-deterministic body (so parity/campaign
  replays mint the same id) plus a host-unique suffix (so two hosts
  launching the same seed stay distinguishable). It rides the reserved
  trailing control-arg path on the gRPC transport (``__run__:`` next to
  ``__trace__``/``__digest__``) and the :class:`Envelope` dataclass on
  the in-memory transport, so every node in a federation — whichever
  peer kicked off learning — stamps the SAME id into its artifacts.

* **Evidence bundles** — :func:`write_bundle` collects every
  run-id-matching signal into one versioned ``artifacts/bundle_<run_id>/``
  directory with a manifest (member list, schema versions, sha256 for the
  canonical members, clock-era info), then runs the diagnosis engine over
  it and drops ``incident.json`` for ``scripts/fed_doctor.py`` and the
  fed_top DIAGNOSIS banner. The failure hooks (workflow crash,
  supervisor park, devobs trip, campaign violation, bench assertion)
  call it; the happy path never does — bundle cost is zero unless
  something went wrong or a human asked.

Manifest determinism contract (make doctor-check replays it): everything
outside the manifest's ``excluded`` section is a pure function of the
run — member names, kinds, schema versions, and the sha256 of canonical
ledger dumps. Wall-clock timestamps and the hashes of timestamped
members live only under ``excluded``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
import socket
import threading
import time
import traceback
from typing import Any, Dict, Iterable, List, Optional, Tuple

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry.metrics import REGISTRY

log = logging.getLogger("p2pfl_tpu")

#: bump when the common artifact header shape changes
ARTIFACT_SCHEMA_VERSION = 1
#: bump when the bundle manifest shape changes
BUNDLE_SCHEMA_VERSION = 1
#: reserved trailing control-arg prefix carrying the run id on the wire —
#: appended after the ``__trace__`` arg in ``_env_to_pb`` and popped first
#: (reverse order) in ``_pb_to_env``.
WIRE_ARG_PREFIX = "__run__:"

_BUNDLES = REGISTRY.counter(
    "p2pfl_doctor_bundles_total",
    "Evidence bundles written, by trigger (workflow_crash, supervisor_park, "
    "devobs_trip, campaign_violation, bench_assertion, manual).",
    labels=("trigger",),
)

_SAFE_RE = re.compile(r"[^A-Za-z0-9_.-]")

_lock = threading.Lock()
_run_id: str = ""


def _safe(name: str) -> str:
    return _SAFE_RE.sub("_", name) or "norun"


def _host_suffix() -> str:
    """4-hex host/process discriminator: two hosts launching the same
    seeded experiment mint distinguishable ids, while one host's id stays
    stable for the life of the process."""
    raw = f"{socket.gethostname()}:{os.getpid()}".encode()
    return hashlib.blake2b(raw, digest_size=2).hexdigest()


def mint_run_id(seed: Optional[int] = None, name: str = "") -> str:
    """Mint a run id: ``Settings.RUN_ID`` pin wins outright (CI replay
    harnesses need byte-stable manifests); otherwise a 12-hex body —
    seeded-deterministic when a seed is given, random when not — plus the
    host-unique suffix."""
    pinned = str(Settings.RUN_ID or "")
    if pinned:
        return pinned
    if seed is not None:
        body = hashlib.blake2b(
            f"p2pfl-run:{int(seed)}:{name}".encode(), digest_size=6
        ).hexdigest()
    else:
        import secrets

        body = secrets.token_hex(6)
    return f"{body}-{_host_suffix()}"


def _configure_siblings(rid: str) -> None:
    from p2pfl_tpu.telemetry.ledger import LEDGERS

    if not LEDGERS.run_id:
        LEDGERS.configure(rid)
    try:
        REGISTRY.gauge(
            "p2pfl_run_info",
            "Run-identity info metric: 1 for the active run id — joins "
            "Prometheus scrapes to ledger/flightrec/bundle artifacts.",
            labels=("run_id",),
        ).labels(rid).set(1.0)
    except Exception:  # metrics must never take the run context down
        log.debug("run_info gauge refresh failed", exc_info=True)


def establish_run(
    seed: Optional[int] = None,
    name: str = "",
    run_id: Optional[str] = None,
    fresh: bool = False,
) -> str:
    """Establish the ambient run id for this process. Resolution order:
    explicit ``run_id`` arg > ``Settings.RUN_ID`` pin > the id already
    configured into ``LEDGERS`` (parity/campaign scenario runners pin it
    there first — adopting it keeps their canonical dumps byte-identical)
    > mint. First establish wins for the life of the process unless
    ``fresh=True`` (a new ``set_start_learning`` kickoff is a new
    experiment)."""
    global _run_id
    from p2pfl_tpu.telemetry.ledger import LEDGERS

    with _lock:
        if _run_id and not fresh and run_id is None:
            return _run_id
        rid = (
            (run_id or "")
            or str(Settings.RUN_ID or "")
            # a FRESH establish is a new experiment: never re-adopt the
            # previous run's ledger pin
            or ("" if fresh else LEDGERS.run_id)
            or mint_run_id(seed, name)
        )
        _run_id = rid
    _configure_siblings(rid)
    return rid


def adopt_run_id(rid: str, force: bool = False) -> str:
    """Adopt a run id learned from the wire. First-wins: an established
    context ignores ids riding ordinary gossip/heartbeat frames (stale
    peers must not flip it mid-run); ``force=True`` — used for
    ``start_learning`` kickoff frames only — overwrites, so every node in
    a federation converges on the initiator's id."""
    global _run_id
    rid = str(rid or "")
    if not rid:
        return _run_id
    with _lock:
        if _run_id == rid or (_run_id and not force):
            return _run_id
        _run_id = rid
    _configure_siblings(rid)
    return rid


def current_run_id() -> str:
    """The ambient run id ("" before any establish/adopt). A
    ``Settings.RUN_ID`` pin always wins — replay harnesses see their
    pinned id even mid-run."""
    return str(Settings.RUN_ID or "") or _run_id


def reset_run() -> None:
    """Forget the ambient run id (test isolation)."""
    global _run_id
    with _lock:
        _run_id = ""


def artifact_header(
    node: str = "",
    kind: str = "",
    schema_version: int = ARTIFACT_SCHEMA_VERSION,
    run_id: Optional[str] = None,
) -> Dict[str, Any]:
    """The common versioned header every artifact carries: run id, schema
    version, emitting node, and clock-era info (wall + monotonic + the
    epoch mapping between them, so cross-artifact monotonic timestamps
    can be aligned after the fact). Old readers tolerate its absence."""
    wall = time.time()
    mono = time.monotonic()
    return {
        "run_id": current_run_id() if run_id is None else str(run_id),
        "schema_version": int(schema_version),
        "kind": str(kind),
        "node": str(node),
        "clock": {
            "wall": round(wall, 6),
            "mono": round(mono, 6),
            "mono_to_wall_epoch": round(wall - mono, 6),
        },
    }


# --- evidence bundles ---------------------------------------------------------


def bundle_dir(run_id: str, directory: Optional[str] = None) -> str:
    base = directory or str(Settings.DOCTOR_BUNDLE_DIR)
    return os.path.join(base, f"bundle_{_safe(run_id)}")


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_json(path: str, doc: Any) -> None:
    # pid alone is not unique here: two node threads crashing in one
    # process write the same bundle members concurrently.
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _doc_matches_run(doc: Any, rid: str) -> bool:
    """Pre-doctor artifacts (no header) are adopted; headered artifacts
    must match the bundle's run id (or carry none)."""
    if not isinstance(doc, dict):
        return True
    header = doc.get("header")
    if not isinstance(header, dict):
        return True
    return str(header.get("run_id", "")) in ("", rid)


#: sibling artifacts in the bundle's parent directory that get copied in
#: when their header matches the run (name -> manifest member kind).
_SIBLING_ARTIFACTS: Tuple[Tuple[str, str], ...] = (
    ("federation_snapshot.json", "snapshot"),
    ("parity_diff.json", "parity"),
)


def write_bundle(
    trigger: str,
    directory: Optional[str] = None,
    run_id: Optional[str] = None,
    context: Optional[Dict[str, Any]] = None,
    error: Optional[BaseException] = None,
    extra_docs: Optional[Dict[str, Any]] = None,
    diagnose: bool = True,
) -> Optional[str]:
    """Collect every run-matching signal into ``<dir>/bundle_<run_id>/``
    and return its path (None when disabled or on any internal failure —
    evidence capture must never compound the original fault)."""
    try:
        return _write_bundle(
            trigger, directory, run_id, context, error, extra_docs, diagnose
        )
    except Exception:
        log.exception("evidence bundle for trigger %r failed", trigger)
        return None


def _write_bundle(
    trigger: str,
    directory: Optional[str],
    run_id: Optional[str],
    context: Optional[Dict[str, Any]],
    error: Optional[BaseException],
    extra_docs: Optional[Dict[str, Any]],
    diagnose: bool,
) -> Optional[str]:
    if not Settings.DOCTOR_BUNDLE_ENABLED:
        return None
    from p2pfl_tpu.telemetry import export
    from p2pfl_tpu.telemetry import flight_recorder as flightrec_mod
    from p2pfl_tpu.telemetry.ledger import LEDGER_SCHEMA_VERSION, LEDGERS

    rid = current_run_id() if run_id is None else str(run_id)
    parent = directory or str(Settings.DOCTOR_BUNDLE_DIR)
    out = bundle_dir(rid or "norun", parent)
    os.makedirs(out, exist_ok=True)

    # (name, kind, schema_version, deterministic) — canonical ledger dumps
    # are the only members whose bytes are a pure function of the run.
    members: List[Tuple[str, str, int, bool]] = []

    for path in LEDGERS.dump_all(out):
        members.append((os.path.basename(path), "ledger", LEDGER_SCHEMA_VERSION, True))

    for rec in flightrec_mod.live_recorders():
        p = rec.dump(trigger, directory=out)
        if p:
            members.append(
                (
                    os.path.basename(p),
                    "flightrec",
                    flightrec_mod.FLIGHTREC_SCHEMA_VERSION,
                    False,
                )
            )

    _write_json(
        os.path.join(out, "metrics.json"),
        {
            "header": artifact_header(kind="metrics", run_id=rid),
            "families": export.snapshot(),
        },
    )
    members.append(("metrics.json", "metrics", ARTIFACT_SCHEMA_VERSION, False))
    prom_path = os.path.join(out, "metrics.prom")
    prom_tmp = f"{prom_path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(prom_tmp, "w", encoding="utf-8") as f:
        f.write(export.render_prometheus())
    os.replace(prom_tmp, prom_path)
    members.append(("metrics.prom", "prometheus", ARTIFACT_SCHEMA_VERSION, False))

    for name, kind in _SIBLING_ARTIFACTS:
        src = os.path.join(parent, name)
        if not os.path.isfile(src):
            continue
        try:
            with open(src, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except Exception:
            continue
        if _doc_matches_run(doc, rid):
            shutil.copyfile(src, os.path.join(out, name))
            members.append((name, kind, ARTIFACT_SCHEMA_VERSION, False))

    ctx_doc: Dict[str, Any] = {
        "header": artifact_header(kind="context", run_id=rid),
        "trigger": trigger,
        "context": dict(context or {}),
    }
    if error is not None:
        ctx_doc["error"] = {
            "type": type(error).__name__,
            "message": str(error),
            "traceback": traceback.format_exception(
                type(error), error, error.__traceback__
            ),
        }
    _write_json(os.path.join(out, "context.json"), ctx_doc)
    members.append(("context.json", "context", ARTIFACT_SCHEMA_VERSION, False))

    for name, doc in (extra_docs or {}).items():
        fname = f"{_safe(name)}.json"
        if isinstance(doc, dict) and "header" not in doc:
            doc = dict(doc)
            doc["header"] = artifact_header(kind=name, run_id=rid)
        _write_json(os.path.join(out, fname), doc)
        members.append((fname, name, ARTIFACT_SCHEMA_VERSION, False))

    det_members: List[Dict[str, Any]] = []
    excluded: Dict[str, Any] = {"written_at": round(time.time(), 6), "volatile_sha256": {}}
    for name, kind, ver, det in sorted(members):
        entry: Dict[str, Any] = {"name": name, "kind": kind, "schema_version": ver}
        sha = _sha256_file(os.path.join(out, name))
        if det:
            entry["sha256"] = sha
        else:
            excluded["volatile_sha256"][name] = sha
        det_members.append(entry)
    manifest = {
        "bundle": "evidence",
        "v": BUNDLE_SCHEMA_VERSION,
        "run_id": rid,
        "trigger": trigger,
        "members": det_members,
        "excluded": excluded,
    }
    _write_json(os.path.join(out, "manifest.json"), manifest)
    _BUNDLES.labels(trigger).inc()

    if diagnose:
        try:
            from p2pfl_tpu.telemetry import diagnosis

            findings = diagnosis.diagnose(diagnosis.load_evidence(out))
            incident = diagnosis.incident_doc(findings, run_id=rid, source=out)
            _write_json(os.path.join(out, "incident.json"), incident)
            # Latest-incident pointer next to federation_snapshot.json —
            # what the fed_top DIAGNOSIS banner reads.
            _write_json(os.path.join(parent, "incident.json"), incident)
        except Exception:
            log.exception("diagnosis over bundle %s failed", out)
    return out


def load_manifest(path: str) -> Optional[Dict[str, Any]]:
    """Read a bundle's manifest (``path`` is the bundle dir or the
    manifest file itself); None when absent/unreadable."""
    if os.path.isdir(path):
        path = os.path.join(path, "manifest.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except Exception:
        return None


def comparable_manifest(manifest: Dict[str, Any]) -> Dict[str, Any]:
    """The replay-deterministic projection of a manifest: everything but
    the ``excluded`` section (wall timestamps + volatile member hashes)."""
    return {k: v for k, v in manifest.items() if k != "excluded"}


__all__ = [
    "ARTIFACT_SCHEMA_VERSION",
    "BUNDLE_SCHEMA_VERSION",
    "WIRE_ARG_PREFIX",
    "mint_run_id",
    "establish_run",
    "adopt_run_id",
    "current_run_id",
    "reset_run",
    "artifact_header",
    "bundle_dir",
    "write_bundle",
    "load_manifest",
    "comparable_manifest",
]
