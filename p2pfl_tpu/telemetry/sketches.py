"""Mergeable, wire-encodable distribution summaries for population scale.

The observability plane built through PR 6 reports raw scalars: a digest
carries *the latest* step time, *the mean* window lag. That shape is O(fleet)
in two places — every observer keeps one table row per peer, and any fleet
statistic beyond an argmax needs every peer's raw stream. At 10k virtual
nodes (ROADMAP item 3) neither survives. The classical fix is sketches:
constant-size summaries that (a) answer quantile/cardinality queries with a
bounded error, and (b) MERGE — ``summary(A ∪ B) = merge(summary(A),
summary(B))`` — so fleet views compose from gossiped per-node summaries
without a coordinator ever seeing raw data. Papaya (arxiv 2111.04877) runs
population-scale monitoring on exactly this shape.

Two sketches, both versioned-wire-encodable (compact JSON-able dicts that
ride inside the health digest):

* :class:`QuantileSketch` — a DDSketch-style relative-error quantile sketch
  (Masson et al., VLDB 2019): logarithmic buckets ``index(x) =
  ceil(log_gamma(x))`` with ``gamma = (1+a)/(1-a)`` guarantee every
  quantile estimate is within relative error ``a`` of the true value, and
  merging is plain per-bucket count addition (associative, commutative).
  Memory is bounded by ``max_bins`` — lowest buckets collapse together, so
  upper quantiles (the p90/p99 an operator actually reads) keep their
  guarantee no matter how many values were folded. ~O(log range) buckets
  regardless of population.
* :class:`DistinctEstimator` — a HyperLogLog distinct counter (fixed
  register array, ~1.04/sqrt(m) relative error). Merge is element-wise
  register max, which makes re-merging the same estimator IDEMPOTENT —
  gossip may deliver a digest many times without inflating the count.

:class:`SketchRegistry` (module-global :data:`SKETCHES`) is the process-wide
home mirroring the metrics registry's shape: hot paths call
``SKETCHES.observe(name, node, value)``; digest collection reads a bounded
wire form; benches/tests ``reset()`` between runs. Counters need no sketch —
they are already merge-associative (addition) — so fleet counter merging
stays in the observatory.
"""

from __future__ import annotations

import base64
import hashlib
import math
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

#: Bump when a sketch wire format changes incompatibly. Decoders ignore
#: unknown-version payloads (the digest degrades to sketch-free, never dies).
SKETCH_WIRE_VERSION = 1

#: The standard sketch names the digest carries (telemetry call sites feed
#: these; anything else is caller-defined and travels just as well).
STANDARD_SKETCHES = ("step_time", "staleness", "update_norm", "agg_wait")

#: Values with magnitude below this are counted as zero (a log-bucketed
#: sketch cannot index 0; step times / lags / norms at true 0 are common).
_MIN_TRACKED = 1e-9

#: Value range the ON-DEVICE bucket window covers (device observatory):
#: update norms / losses below LO clip into the bottom bucket, above HI
#: into the top one. The window is a trace-time constant — the aux output
#: of a compiled scan must be static-shape.
DEVICE_BUCKET_LO = 1e-6
DEVICE_BUCKET_HI = 1e3


def device_bucket_spec(rel_err: Optional[float] = None) -> Tuple[float, int, int]:
    """``(gamma_log, lo_idx, nbins)`` of the static on-device DDSketch
    bucket window: the same ``index(x) = ceil(log(x)/gamma_log)`` rule the
    host sketches use, restricted to ``[DEVICE_BUCKET_LO, DEVICE_BUCKET_HI]``
    so a compiled scan can emit a fixed-length bucket-count vector per
    round. Host side, :meth:`QuantileSketch.fold_device_buckets` folds the
    counts back losslessly (same gamma) or through bucket midpoints."""
    if rel_err is None:
        from p2pfl_tpu.config import Settings

        rel_err = Settings.SKETCH_REL_ERR
    gamma_log = math.log((1.0 + rel_err) / (1.0 - rel_err))
    lo = int(math.ceil(math.log(DEVICE_BUCKET_LO) / gamma_log))
    hi = int(math.ceil(math.log(DEVICE_BUCKET_HI) / gamma_log))
    return gamma_log, lo, hi - lo + 1


def device_bucket_stats(
    values: Any, *, gamma_log: float, lo_idx: int, nbins: int
) -> Dict[str, Any]:
    """Jit-safe bucket statistics of ``|values|`` for the device observatory.

    Returns static-shape jnp arrays suitable for a ``lax.scan`` aux output:
    ``counts`` ([nbins] int32 DDSketch bucket counts, window-clipped),
    ``zeros`` (values below the sketch zero floor), and exact ``sum`` /
    ``min`` / ``max`` over the finite non-zero magnitudes (inf/-inf when
    none). Non-finite values contribute to NOTHING here — the NaN tripwire
    flags them separately."""
    import jax.numpy as jnp

    v = jnp.abs(jnp.asarray(values, jnp.float32).ravel())
    finite = jnp.isfinite(v)
    zero = finite & (v < _MIN_TRACKED)
    pos = finite & (v >= _MIN_TRACKED)
    idx = jnp.clip(
        jnp.ceil(
            jnp.log(jnp.maximum(v, _MIN_TRACKED)) / jnp.float32(gamma_log)
        ).astype(jnp.int32)
        - lo_idx,
        0,
        nbins - 1,
    )
    counts = jnp.zeros((nbins,), jnp.int32).at[idx].add(pos.astype(jnp.int32))
    return {
        "counts": counts,
        "zeros": zero.sum().astype(jnp.int32),
        "sum": jnp.where(pos, v, 0.0).sum(),
        "min": jnp.where(pos, v, jnp.inf).min(),
        "max": jnp.where(pos, v, -jnp.inf).max(),
    }


class QuantileSketch:
    """Relative-error quantile sketch over a stream of floats.

    Args:
        rel_err: guaranteed relative accuracy ``a`` of quantile estimates
            (bucket ``i`` spans ``(gamma^(i-1), gamma^i]`` with ``gamma =
            (1+a)/(1-a)``; reporting the bucket midpoint keeps every value
            in it within ``a`` relatively).
        max_bins: memory bound. Past it the LOWEST buckets collapse into one
            another (DDSketch's collapsing strategy), trading accuracy at
            the bottom of the distribution for a hard size cap — upper
            quantiles keep the guarantee.

    Negative values are supported through a mirrored store (update-norm
    deltas etc.); exact ``count/sum/min/max`` ride along for free.
    """

    __slots__ = (
        "rel_err", "max_bins", "_gamma_log", "_bins", "_neg",
        "zero_count", "count", "sum", "min", "max",
    )

    def __init__(self, rel_err: float = 0.02, max_bins: int = 128) -> None:
        if not 0.0 < rel_err < 1.0:
            raise ValueError(f"rel_err must be in (0, 1), got {rel_err}")
        if max_bins < 8:
            raise ValueError(f"max_bins must be >= 8, got {max_bins}")
        self.rel_err = float(rel_err)
        self.max_bins = int(max_bins)
        self._gamma_log = math.log((1.0 + rel_err) / (1.0 - rel_err))
        self._bins: Dict[int, float] = {}  # positive values
        self._neg: Dict[int, float] = {}  # sketch of -x for x < 0
        self.zero_count = 0.0
        self.count = 0.0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # --- feeding -------------------------------------------------------------

    def _index(self, x: float) -> int:
        return int(math.ceil(math.log(x) / self._gamma_log))

    def _value(self, index: int) -> float:
        # Bucket midpoint 2*gamma^i / (gamma + 1): within rel_err of every
        # value the bucket covers.
        gamma = math.exp(self._gamma_log)
        return 2.0 * gamma ** index / (gamma + 1.0)

    def add(self, value: float, n: float = 1.0) -> None:
        v = float(value)
        if not math.isfinite(v) or n <= 0:
            return
        self.count += n
        self.sum += v * n
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if abs(v) < _MIN_TRACKED:
            self.zero_count += n
        elif v > 0:
            i = self._index(v)
            self._bins[i] = self._bins.get(i, 0.0) + n
        else:
            i = self._index(-v)
            self._neg[i] = self._neg.get(i, 0.0) + n
        if len(self._bins) > self.max_bins or len(self._neg) > self.max_bins:
            self._collapse()

    def add_many(self, values: Iterable[float]) -> None:
        """Vectorized fold of an array (the fused-mesh path: 10k per-node
        stats per metric fold in one numpy pass, not 10k Python adds)."""
        arr = np.asarray(list(values) if not isinstance(values, np.ndarray) else values, np.float64).ravel()
        arr = arr[np.isfinite(arr)]
        if arr.size == 0:
            return
        self.count += float(arr.size)
        self.sum += float(arr.sum())
        self.min = min(self.min, float(arr.min()))
        self.max = max(self.max, float(arr.max()))
        zeros = np.abs(arr) < _MIN_TRACKED
        self.zero_count += float(zeros.sum())
        for store, vals in (
            (self._bins, arr[(~zeros) & (arr > 0)]),
            (self._neg, -arr[(~zeros) & (arr < 0)]),
        ):
            if vals.size == 0:
                continue
            idx = np.ceil(np.log(vals) / self._gamma_log).astype(np.int64)
            uniq, counts = np.unique(idx, return_counts=True)
            for i, c in zip(uniq.tolist(), counts.tolist()):
                store[i] = store.get(i, 0.0) + float(c)
        if len(self._bins) > self.max_bins or len(self._neg) > self.max_bins:
            self._collapse()

    def fold_device_buckets(
        self,
        gamma_log: float,
        lo_idx: int,
        counts: Any,
        *,
        zeros: float = 0.0,
        vsum: Optional[float] = None,
        vmin: Optional[float] = None,
        vmax: Optional[float] = None,
    ) -> None:
        """Fold an on-device bucket-count vector (:func:`device_bucket_stats`)
        into this sketch. Bucket ``j`` of ``counts`` holds the mass at
        absolute DDSketch index ``lo_idx + j`` under ``gamma_log``; each
        non-empty bucket re-folds through its midpoint at THIS sketch's
        accuracy (a no-op re-index when the gammas match, i.e. before any
        collapse). Exact ``vsum/vmin/vmax`` from the device ride along when
        given; otherwise the midpoints approximate them."""
        arr = np.asarray(counts, np.float64).ravel()
        nz = np.nonzero(arr > 0)[0]
        zeros = max(0.0, float(zeros))
        total = float(arr[nz].sum()) + zeros
        if total <= 0:
            return
        gl = float(gamma_log)
        mids = 2.0 * np.exp(gl * (lo_idx + nz)) / (math.exp(gl) + 1.0)
        self.count += total
        if vsum is not None and math.isfinite(float(vsum)):
            self.sum += float(vsum)
        else:
            self.sum += float((mids * arr[nz]).sum())
        if zeros > 0:
            self.zero_count += zeros
            self.min = min(self.min, 0.0)
            self.max = max(self.max, 0.0)
        if nz.size:
            lo_v = float(vmin) if vmin is not None and math.isfinite(float(vmin)) else float(mids.min())
            hi_v = float(vmax) if vmax is not None and math.isfinite(float(vmax)) else float(mids.max())
            self.min = min(self.min, lo_v)
            self.max = max(self.max, hi_v)
            for m, c in zip(mids.tolist(), arr[nz].tolist()):
                i = self._index(m)
                self._bins[i] = self._bins.get(i, 0.0) + float(c)
        if len(self._bins) > self.max_bins or len(self._neg) > self.max_bins:
            self._collapse()

    def _collapse(self) -> None:
        """Halve the resolution until within ``max_bins``: re-bucket every
        index ``i -> ceil(i/2)`` under ``gamma^2``. Bucket ``i`` covers
        ``(gamma^(i-1), gamma^i]``, so both ``2j-1`` and ``2j`` land inside
        the coarse ``(gamma^(2j-2), gamma^(2j)]`` — the sketch stays a valid
        DDSketch at the doubled gamma, and the accuracy loss is UNIFORM
        across the range (``rel_err`` is updated to the new guarantee)
        instead of sacrificing whole quantile ranges the way a lowest-bin
        rollup would under a tight wire cap.
        """
        while len(self._bins) > self.max_bins or len(self._neg) > self.max_bins:
            self._gamma_log *= 2.0
            g = math.exp(self._gamma_log)
            self.rel_err = (g - 1.0) / (g + 1.0)
            for attr in ("_bins", "_neg"):
                old = getattr(self, attr)
                coarse: Dict[int, float] = {}
                for i, c in old.items():
                    j = -((-i) // 2)  # ceil(i/2), exact for negative ints too
                    coarse[j] = coarse.get(j, 0.0) + c
                setattr(self, attr, coarse)

    # --- querying ------------------------------------------------------------

    def quantile(self, q: float) -> float:
        """Estimated ``q``-quantile (``0 <= q <= 1``); NaN when empty.

        Walk order: most-negative buckets first, then zero, then positive
        ascending. Estimates clamp into the exact observed ``[min, max]``.
        """
        if self.count <= 0:
            return float("nan")
        q = min(1.0, max(0.0, float(q)))
        if q == 0.0:
            return self.min
        if q == 1.0:
            return self.max
        rank = q * (self.count - 1.0)
        seen = 0.0
        for i in sorted(self._neg, reverse=True):  # most negative first
            seen += self._neg[i]
            if seen > rank:
                return max(self.min, min(self.max, -self._value(i)))
        seen += self.zero_count
        if seen > rank:
            return max(self.min, min(self.max, 0.0))
        for i in sorted(self._bins):
            seen += self._bins[i]
            if seen > rank:
                return max(self.min, min(self.max, self._value(i)))
        return self.max

    def quantiles(self, qs: Iterable[float] = (0.5, 0.9, 0.99)) -> Dict[str, float]:
        return {f"p{int(round(q * 100))}": self.quantile(q) for q in qs}

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count > 0 else float("nan")

    # --- merging -------------------------------------------------------------

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Return a NEW sketch summarizing both streams.

        Same-accuracy sketches merge by per-bucket count addition —
        associative and commutative by construction. A different-accuracy
        peer (version skew) degrades gracefully: its buckets re-fold through
        their midpoints at THIS sketch's accuracy.
        """
        out = self.copy()
        out.merge_in(other)
        return out

    def merge_in(self, other: "QuantileSketch") -> None:
        if other.count <= 0:
            return
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        self.zero_count += other.zero_count
        same = abs(other.rel_err - self.rel_err) < 1e-12
        for mine, theirs, sign in ((self._bins, other._bins, 1.0), (self._neg, other._neg, -1.0)):
            for i, c in theirs.items():
                j = i if same else self._index(other._value(i))
                mine[j] = mine.get(j, 0.0) + c
        if len(self._bins) > self.max_bins or len(self._neg) > self.max_bins:
            self._collapse()

    def copy(self) -> "QuantileSketch":
        out = QuantileSketch(self.rel_err, self.max_bins)
        out._bins = dict(self._bins)
        out._neg = dict(self._neg)
        out.zero_count = self.zero_count
        out.count = self.count
        out.sum = self.sum
        out.min = self.min
        out.max = self.max
        return out

    # --- wire codec ----------------------------------------------------------

    def to_wire(self, max_bins: Optional[int] = None) -> Dict[str, Any]:
        """Compact JSON-able form. ``max_bins`` bounds the WIRE size below
        the in-memory bound (digests must stay beat-cheap)."""
        src = self
        if max_bins is not None and (
            len(self._bins) > max_bins or len(self._neg) > max_bins
        ):
            src = self.copy()
            src.max_bins = int(max_bins)
            src._collapse()

        def enc(store: Dict[int, float]) -> List[List[float]]:
            return [
                [i, int(c) if float(c).is_integer() else round(c, 3)]
                for i, c in sorted(store.items())
            ]

        wire: Dict[str, Any] = {
            "v": SKETCH_WIRE_VERSION,
            "e": src.rel_err,
            "c": int(src.count) if float(src.count).is_integer() else src.count,
            "s": round(src.sum, 9),
            "b": enc(src._bins),
        }
        if src._neg:
            wire["g"] = enc(src._neg)
        if src.zero_count:
            wire["z"] = int(src.zero_count)
        if src.count > 0:
            wire["lo"] = src.min
            wire["hi"] = src.max
        return wire

    @classmethod
    def from_wire(cls, wire: Any) -> Optional["QuantileSketch"]:
        """Best-effort decode; ``None`` for malformed/unknown payloads."""
        if not isinstance(wire, dict):
            return None
        try:
            if int(wire.get("v", 0)) != SKETCH_WIRE_VERSION:
                return None
            out = cls(rel_err=float(wire.get("e", 0.02)))
            for key, store in (("b", out._bins), ("g", out._neg)):
                for pair in wire.get(key, ()):
                    i, c = int(pair[0]), float(pair[1])
                    if not math.isfinite(c) or c < 0:
                        return None  # hostile: NaN/Inf/negative bucket mass
                    if c > 0:
                        store[i] = store.get(i, 0.0) + c
            out.zero_count = max(0.0, float(wire.get("z", 0.0)))
            out.count = max(0.0, float(wire.get("c", 0.0)))
            out.sum = float(wire.get("s", 0.0))
            out.min = float(wire.get("lo", math.inf))
            out.max = float(wire.get("hi", -math.inf))
        except (TypeError, ValueError, IndexError, OverflowError):
            return None
        # Internal consistency: the bucket mass must not exceed the claimed
        # count (a hostile digest must not fabricate quantile weight). The
        # tolerance absorbs the wire's per-bucket count rounding.
        mass = sum(out._bins.values()) + sum(out._neg.values()) + out.zero_count
        if out.count < mass - 1.0 or not math.isfinite(out.count):
            return None
        return out


class DistinctEstimator:
    """HyperLogLog distinct counter with fixed-size registers.

    ``m`` registers give ~``1.04/sqrt(m)`` relative error (m=128: ~9%) in
    ``m`` bytes of state. :meth:`merge` is element-wise max — idempotent
    (``merge(a, a) == a``), which is what lets gossip re-deliver digests
    without double counting contributors.
    """

    __slots__ = ("m", "_registers")

    def __init__(self, m: int = 128) -> None:
        if m < 16 or m & (m - 1):
            raise ValueError(f"m must be a power of two >= 16, got {m}")
        self.m = m
        self._registers = bytearray(m)

    def add(self, item: str) -> None:
        h = int.from_bytes(
            hashlib.blake2b(item.encode("utf-8"), digest_size=8).digest(), "big"
        )
        p = self.m.bit_length() - 1
        j = h & (self.m - 1)
        w = h >> p
        # Rank of the first set bit in the remaining 64-p bits (1-based).
        rank = (64 - p) - w.bit_length() + 1
        if rank > self._registers[j]:
            self._registers[j] = rank

    def estimate(self) -> float:
        m = self.m
        raw = (_hll_alpha(m) * m * m) / sum(2.0 ** -r for r in self._registers)
        zeros = self._registers.count(0)
        if raw <= 2.5 * m and zeros:  # small-range linear counting
            return m * math.log(m / zeros)
        return raw

    def merge(self, other: "DistinctEstimator") -> "DistinctEstimator":
        out = DistinctEstimator(self.m)
        out._registers = bytearray(self._registers)
        out.merge_in(other)
        return out

    def merge_in(self, other: "DistinctEstimator") -> None:
        if other.m != self.m:  # version skew: fold through the estimate
            for i in range(int(round(other.estimate()))):
                self.add(f"~skew~{i}")
            return
        for j, r in enumerate(other._registers):
            if r > self._registers[j]:
                self._registers[j] = r

    def to_wire(self) -> str:
        return base64.b64encode(bytes(self._registers)).decode("ascii")

    @classmethod
    def from_wire(cls, wire: Any) -> Optional["DistinctEstimator"]:
        if not isinstance(wire, str):
            return None
        try:
            raw = base64.b64decode(wire.encode("ascii"), validate=True)
        except (ValueError, UnicodeEncodeError):
            return None
        m = len(raw)
        if m < 16 or m & (m - 1) or any(b > 64 for b in raw):
            return None
        out = cls(m)
        out._registers = bytearray(raw)
        return out


def _hll_alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


class SketchRegistry:
    """Process-wide (name, node) -> sketch table, mirroring the metrics
    registry's shape: one registry serves every in-process node; hot paths
    observe, digest collection reads a bounded wire form, harnesses reset.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._quantiles: Dict[Tuple[str, str], QuantileSketch] = {}
        self._distinct: Dict[str, DistinctEstimator] = {}

    def observe(self, name: str, node: str, value: float) -> None:
        """Fold one value into the (name, node) quantile sketch. Never
        raises — observability must not break the observed path."""
        try:
            from p2pfl_tpu.config import Settings

            key = (name, node)
            with self._lock:
                sk = self._quantiles.get(key)
                if sk is None:
                    sk = QuantileSketch(
                        rel_err=Settings.SKETCH_REL_ERR,
                        max_bins=Settings.SKETCH_MAX_BINS,
                    )
                    self._quantiles[key] = sk
                sk.add(value)
        except Exception:  # noqa: BLE001
            pass

    def fold_buckets(
        self,
        name: str,
        node: str,
        gamma_log: float,
        lo_idx: int,
        counts: Any,
        *,
        zeros: float = 0.0,
        vsum: Optional[float] = None,
        vmin: Optional[float] = None,
        vmax: Optional[float] = None,
    ) -> None:
        """Fold an on-device bucket-count vector into the (name, node)
        sketch — the device observatory's per-chunk entry point. Never
        raises."""
        try:
            from p2pfl_tpu.config import Settings

            key = (name, node)
            with self._lock:
                sk = self._quantiles.get(key)
                if sk is None:
                    sk = QuantileSketch(
                        rel_err=Settings.SKETCH_REL_ERR,
                        max_bins=Settings.SKETCH_MAX_BINS,
                    )
                    self._quantiles[key] = sk
                sk.fold_device_buckets(
                    gamma_log, lo_idx, counts,
                    zeros=zeros, vsum=vsum, vmin=vmin, vmax=vmax,
                )
        except Exception:  # noqa: BLE001
            pass

    def distinct_add(self, node: str, item: str) -> None:
        """Fold one contributor identity into ``node``'s distinct counter."""
        try:
            with self._lock:
                est = self._distinct.get(node)
                if est is None:
                    est = DistinctEstimator()
                    self._distinct[node] = est
                est.add(item)
        except Exception:  # noqa: BLE001
            pass

    def get(self, name: str, node: str) -> Optional[QuantileSketch]:
        with self._lock:
            sk = self._quantiles.get((name, node))
            return sk.copy() if sk is not None else None

    def get_distinct(self, node: str) -> Optional[DistinctEstimator]:
        with self._lock:
            est = self._distinct.get(node)
            if est is None:
                return None
            out = DistinctEstimator(est.m)
            out._registers = bytearray(est._registers)
            return out

    def wire_for(self, node: str, max_bins: int = 48) -> Dict[str, Any]:
        """All of ``node``'s sketches in wire form (bin count bounded for
        the digest), plus the distinct counter under ``"__distinct__"``."""
        out: Dict[str, Any] = {}
        with self._lock:
            items = [
                (name, sk) for (name, n), sk in self._quantiles.items() if n == node
            ]
            est = self._distinct.get(node)
            est_wire = est.to_wire() if est is not None else None
        for name, sk in items:
            if sk.count > 0:
                out[name] = sk.to_wire(max_bins=max_bins)
        if est_wire is not None:
            out["__distinct__"] = est_wire
        return out

    def names(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._quantiles)

    def reset(self) -> None:
        with self._lock:
            self._quantiles.clear()
            self._distinct.clear()


#: The process-wide sketch registry every subsystem observes into.
SKETCHES = SketchRegistry()


__all__ = [
    "DEVICE_BUCKET_HI",
    "DEVICE_BUCKET_LO",
    "DistinctEstimator",
    "QuantileSketch",
    "SKETCHES",
    "SKETCH_WIRE_VERSION",
    "STANDARD_SKETCHES",
    "SketchRegistry",
    "device_bucket_spec",
    "device_bucket_stats",
]
