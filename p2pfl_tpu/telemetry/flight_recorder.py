"""Flight recorder: a bounded ring of structured events, dumped postmortem.

Every node keeps the last ``Settings.FLIGHTREC_CAPACITY`` notable events —
stage transitions, model-plane sends/recvs, admission rejections, injected
chaos faults, peer deaths, digest deltas — cheaply in memory. Nobody reads
it while things work; when a node crashes (``Node.crash()``, a workflow
exception) or the aggregation stall patience fires, the ring dumps to
``artifacts/flightrec_<node>.json`` so the postmortem for exactly the
failures PR 3's chaos plane injects is a file, not N processes' interleaved
logs.

Recording is a deque append under a small lock (the deque's ``maxlen``
drops the oldest event; drops are counted in
``p2pfl_flightrec_events_dropped_total``). Dumping never raises — a broken
disk must not break the crash path it is documenting.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry.metrics import REGISTRY

log = logging.getLogger("p2pfl_tpu")

#: dump-doc schema: v2 added the common versioned "header" block
#: (run_id / schema_version / node / clock era). v1 readers that only
#: know the legacy top-level keys keep working — those keys are retained.
FLIGHTREC_SCHEMA_VERSION = 2

# Live-recorder registry: the evidence-bundle writer needs to dump every
# recorder in the process, not just the one owned by the failing
# component. Weak references — a recorder's lifetime is its owner's.
_LIVE: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()


def live_recorders() -> List["FlightRecorder"]:
    """Every recorder still alive in this process, sorted by node address
    (stable member ordering for bundle manifests)."""
    with _LIVE_LOCK:
        recs = list(_LIVE)
    return sorted(recs, key=lambda r: r._addr)


def reset_live_recorders() -> None:
    """Forget all live recorders (test/scenario isolation — a stale ring
    from a previous scenario must not leak into the next bundle)."""
    with _LIVE_LOCK:
        _LIVE.clear()

_DROPPED = REGISTRY.counter(
    "p2pfl_flightrec_events_dropped_total",
    "Flight-recorder events evicted by the ring bound (oldest first)",
    labels=("node",),
)
_DUMPS = REGISTRY.counter(
    "p2pfl_flightrec_dumps_total",
    "Flight-recorder postmortem dumps written, by trigger",
    labels=("node", "trigger"),
)


def _safe_name(addr: str) -> str:
    """Address -> filesystem-safe dump-file stem ("127.0.0.1:50051" and
    in-memory "node-3" both must map to a writable name)."""
    return re.sub(r"[^A-Za-z0-9_.-]", "_", addr) or "node"


class FlightRecorder:
    """Per-node bounded event ring + postmortem dumper."""

    def __init__(self, addr: str, capacity: Optional[int] = None) -> None:
        self._addr = addr
        cap = int(capacity if capacity is not None else Settings.FLIGHTREC_CAPACITY)
        self._events: deque = deque(maxlen=max(1, cap))
        self._lock = threading.Lock()
        self._dropped = _DROPPED.labels(addr)
        with _LIVE_LOCK:
            _LIVE.add(self)

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    def record(self, kind: str, **detail: Any) -> None:
        """Append one event. ``detail`` values must be JSON-able (strings /
        numbers — callers pass addresses, rounds, byte counts).

        Timestamps are stored on the MONOTONIC clock only; the mono->wall
        mapping is computed when events are read (:meth:`events` /
        :meth:`dump`), not frozen at construction — an NTP step mid-run
        therefore shifts all reported wall times consistently instead of
        splitting the ring across two clock eras.
        """
        ev = {"t_mono": round(time.monotonic(), 6), "kind": kind}
        ev.update(detail)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped.inc()
            self._events.append(ev)

    @staticmethod
    def _mono_to_wall_epoch() -> float:
        """CURRENT mono->wall mapping (wall seconds at monotonic 0)."""
        return time.time() - time.monotonic()

    def events(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first, with wall-clock ``t`` derived from
        the stored monotonic stamp at READ time."""
        epoch = self._mono_to_wall_epoch()
        with self._lock:
            raw = [dict(e) for e in self._events]
        for e in raw:
            e["t"] = round(e["t_mono"] + epoch, 6)
        return raw

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # --- postmortem ----------------------------------------------------------

    def dump_path(self, directory: str = "artifacts") -> str:
        return os.path.join(directory, f"flightrec_{_safe_name(self._addr)}.json")

    def dump(self, trigger: str, directory: str = "artifacts") -> Optional[str]:
        """Write the ring (newest last) to ``flightrec_<node>.json``.

        Called from crash paths and transport threads: swallows every error
        (logged) and returns ``None`` on failure, the path on success. A
        later dump for the same node overwrites — the freshest postmortem
        wins.
        """
        try:
            from p2pfl_tpu.telemetry.bundle import artifact_header

            events = self.events()
            path = self.dump_path(directory)
            os.makedirs(directory, exist_ok=True)
            # pid alone collides when two threads dump into one bundle dir
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "header": artifact_header(
                            node=self._addr,
                            kind="flightrec",
                            schema_version=FLIGHTREC_SCHEMA_VERSION,
                        ),
                        "node": self._addr,
                        "trigger": trigger,
                        # Both clocks at dump time plus the mapping used for
                        # the events' wall "t": a postmortem reader can both
                        # line events up with other hosts' logs (wall) and
                        # compute exact in-process gaps (mono, step-free).
                        "dumped_at": time.time(),
                        "dumped_at_mono": time.monotonic(),
                        "mono_to_wall_epoch": self._mono_to_wall_epoch(),
                        "dropped_before_ring": self._dropped.value,
                        "events": events,
                    },
                    f,
                    indent=1,
                )
            os.replace(tmp, path)
            _DUMPS.labels(self._addr, trigger).inc()
            log.warning(
                "(%s) flight recorder dumped %d events to %s (trigger=%s)",
                self._addr, len(events), path, trigger,
            )
            return path
        except Exception:  # noqa: BLE001 — never break the crash path
            log.exception("(%s) flight-recorder dump failed", self._addr)
            return None


__all__ = [
    "FLIGHTREC_SCHEMA_VERSION",
    "FlightRecorder",
    "live_recorders",
    "reset_live_recorders",
]
