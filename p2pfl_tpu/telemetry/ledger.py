"""Trajectory ledger — the canonical event stream both backends emit.

The fused mesh (``parallel/simulation.py``) and the real gRPC/in-memory wire
are two execution paths that agree only by convention: nothing *certified*
that an n=512 fused result describes the same federation an 8-node wire run
does. This module is the observable half of that certification (ROADMAP
item 5; Papaya — arxiv 2111.04877 — trusts its simulator precisely because
sim and production share one recorded execution path): a deterministic,
seed-stable, append-only ledger of **versioned structured events**

========================  =====================================================
kind                      fields (beyond ``v``/``kind``/``round``)
========================  =====================================================
``round_open``            ``members`` — the elected committee, sorted
``window_open``           async: the window index in ``round``
``contribution_folded``   ``sender``, ``lag``, ``num_samples``
``aggregate_committed``   ``hash`` (content hash of the adopted params),
                          ``contributors`` (sorted), ``num_samples``;
                          optional ``origin`` (``train``/``full_model``/
                          ``window``) and ``reason`` (async close reason)
``round_close``           —
``window_close``          —
``membership``            ``event`` (join/rejoin/leave/evict/recover),
                          ``peer``
``chaos_fault``           ``fault`` (churn/recovery/byzantine), ``peer``,
                          step detail fields
``admission_rejected``    ``sender``, ``reason`` (deduped per
                          (round, sender, reason) — a gossip loop
                          re-shipping one bad frame is one trajectory fact)
========================  =====================================================

emitted from the sync and async schedulers, the aggregators, wire admission,
the membership/observatory plane, the chaos plane AND the fused-mesh round
step — same schema, either backend. Events carry **no wall-clock**: the
ledger records *what the federation did*, not when, which is what makes the
same seeded scenario produce byte-identical ledgers across runs and across
backends (timing lives in the tracer / flight recorder).

Each per-node ledger is an append-only bounded ring with monotonic live
sequence numbers; :meth:`TrajectoryLedger.dump` writes
``artifacts/ledger_<node>.jsonl`` in **canonical** form — events sorted by
``(round, kind rank, sender, …)`` with canonical sequence numbers — so two
runs that produced the same event *set* produce byte-identical files
regardless of transport-thread interleaving (``canonical=False`` preserves
arrival order + live seq for debugging). ``scripts/parity_diff.py`` aligns
two dumps and localizes the first divergent event; ``bench.py --parity``
and ``make parity-check`` are the gates built on it.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry.metrics import REGISTRY

#: bump when an event's field semantics change; readers tolerate (and skip)
#: versions they don't know.
LEDGER_SCHEMA_VERSION = 1

#: canonical within-round ordering of event kinds (scenario facts before the
#: contributions they shaped, contributions before the aggregate they fed).
KIND_RANK = {
    "round_open": 0,
    "window_open": 0,
    "chaos_fault": 1,
    "membership": 2,
    "admission_rejected": 3,
    "privacy_masked": 3,
    "contribution_folded": 4,
    "aggregate_committed": 5,
    "window_close": 6,
    "round_close": 6,
}

#: kinds parity_diff compares by default — the trajectory proper. The rest
#: (chaos faults, admission rejections, membership) are environment /
#: defense facts that legitimately differ between backends (the fused mesh
#: has no wire to drop frames from) and are compared only on request.
TRAJECTORY_KINDS = (
    "round_open",
    "window_open",
    "contribution_folded",
    "aggregate_committed",
    "window_close",
    "round_close",
)

#: provenance fields stripped from CANONICAL events/dumps: which code path
#: committed first (``origin``: own aggregate vs adopted full model — the
#: values are bit-identical, first wins) and why an async window closed
#: (``reason``) are timing facts, not trajectory facts; keeping them would
#: break byte-identical dumps across reruns. Raw events keep them.
NONCANONICAL_FIELDS = ("origin", "reason")

_EVENTS = REGISTRY.counter(
    "p2pfl_ledger_events_total",
    "Trajectory-ledger events appended, by node and event kind",
    labels=("node", "kind"),
)


def canonical_params_hash(params: Any) -> str:
    """Content hash of a parameter pytree, stable across backends.

    Canonicalization rules (documented in docs/components/parity.md):

    * leaves are taken in ``jax.tree.leaves`` order (the tree's flatten
      order — identical for a :class:`ModelHandle` params tree and the
      fused mesh's per-node slice of the stacked population);
    * float leaves are cast to little-endian float32, ``-0.0`` is
      normalized to ``+0.0`` and every NaN payload collapses to the one
      canonical quiet NaN — a hash difference always means a *value*
      difference;
    * integer/bool leaves are cast to little-endian int64 / uint8;
    * each leaf contributes its index, shape and dtype class, so a
      reshape can never alias a value change.

    Returns ``"sha256:<hex>"``.
    """
    import numpy as np

    if isinstance(params, (list, tuple)):
        leaves = list(params)
    else:
        import jax

        leaves = jax.tree.leaves(params)
    h = hashlib.sha256()
    h.update(f"pfl-ledger-hash-v1:{len(leaves)};".encode())
    for i, leaf in enumerate(leaves):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            a = np.ascontiguousarray(a, dtype="<f4") + np.float32(0.0)
            a = np.where(np.isnan(a), np.float32(np.nan), a)
            kind = "f"
        elif np.issubdtype(a.dtype, np.bool_):
            a = np.ascontiguousarray(a, dtype="u1")
            kind = "b"
        else:
            a = np.ascontiguousarray(a, dtype="<i8")
            kind = "i"
        h.update(f"{i}:{kind}:{a.shape};".encode())
        h.update(a.tobytes(order="C"))
    return f"sha256:{h.hexdigest()}"


def _canonical_sort_key(ev: Dict[str, Any]):
    rnd = ev.get("round")
    return (
        rnd if isinstance(rnd, (int, float)) else -1,
        KIND_RANK.get(ev.get("kind"), 9),
        str(ev.get("kind", "")),
        str(ev.get("sender", ev.get("peer", ""))),
        json.dumps(
            {k: v for k, v in ev.items() if k != "seq"},
            sort_keys=True, separators=(",", ":"),
        ),
    )


class TrajectoryLedger:
    """One node's append-only event ring (bounded by LEDGER_CAPACITY)."""

    def __init__(self, node: str, run_id: str = "", campaign: str = "") -> None:
        self.node = node
        self.run_id = run_id
        #: campaign id (campaigns/engine.py) — scopes this ledger's dumps to
        #: one sampled campaign scenario; empty outside campaign runs.
        self.campaign = campaign
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(16, int(Settings.LEDGER_CAPACITY)))
        self._seq = 0
        self._dropped = 0
        #: last round/window opened — stamps events whose emitter doesn't
        #: know the round (membership transitions, admission rejections).
        self.current_round: Optional[int] = None
        #: dedup keys already emitted (admission rejections collapse to one
        #: trajectory fact per (round, sender, reason)).
        self._dedup: set = set()

    def emit(
        self,
        kind: str,
        round: Optional[int] = None,
        dedup_key: Optional[tuple] = None,
        **fields: Any,
    ) -> bool:
        """Append one event; returns False when deduped. ``round`` stays
        None when the emitter has no round context (membership transitions,
        pre-session chaos steps) — a timing-dependent guess here would
        break the byte-identical-across-runs guarantee the canonical dump
        makes. ``current_round`` (updated by round/window_open) is offered
        to emitters that WANT a best-effort stamp (wire admission)."""
        with self._lock:
            if dedup_key is not None:
                if dedup_key in self._dedup:
                    return False
                self._dedup.add(dedup_key)
            if kind in ("round_open", "window_open") and round is not None:
                self.current_round = int(round)
            ev: Dict[str, Any] = {
                "v": LEDGER_SCHEMA_VERSION,
                "seq": self._seq,
                "kind": kind,
                "round": int(round) if round is not None else None,
            }
            ev.update(fields)
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)
            self._seq += 1
        _EVENTS.labels(self.node, kind).inc()
        return True

    # --- reading -------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def tail(self, n: int) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in list(self._events)[-max(0, int(n)):]]

    def canonical_events(self) -> List[Dict[str, Any]]:
        """Events in canonical order (round, kind rank, sender, payload)
        with canonical sequence numbers — byte-stable across runs that
        produced the same event set."""
        evs = sorted(
            (
                {k: v for k, v in ev.items() if k not in NONCANONICAL_FIELDS}
                for ev in self.events()
            ),
            key=_canonical_sort_key,
        )
        out = []
        for i, ev in enumerate(evs):
            ev["seq"] = i
            out.append(ev)
        return out

    # --- dumping -------------------------------------------------------------

    def dump(self, path: str, canonical: bool = True) -> str:
        """Write the ledger as JSONL (header line + one event per line).
        Canonical mode (default) re-orders deterministically and re-numbers
        ``seq``; ``canonical=False`` keeps arrival order + live seq."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        header = {
            "ledger": "trajectory",
            "v": LEDGER_SCHEMA_VERSION,
            "node": self.node,
            "run_id": self.run_id,
            "canonical": bool(canonical),
            "dropped": self._dropped,
        }
        if self.campaign:
            # Present ONLY for campaign runs: pre-campaign dumps (and their
            # committed baselines) stay byte-identical.
            header["campaign"] = self.campaign
        evs = self.canonical_events() if canonical else self.events()
        # pid alone collides when two threads dump into one bundle dir
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header, sort_keys=True, separators=(",", ":")) + "\n")
            for ev in evs:
                f.write(json.dumps(ev, sort_keys=True, separators=(",", ":")) + "\n")
        os.replace(tmp, path)
        return path


def _safe_name(node: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", node)


class LedgerHub:
    """Process-wide per-node ledger registry (the REGISTRY/SKETCHES
    pattern): emission points address ledgers by node name, tests and the
    dump path enumerate them. Every method is a cheap no-op while
    ``Settings.LEDGER_ENABLED`` is off."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ledgers: Dict[str, TrajectoryLedger] = {}
        self._run_id = ""
        self._campaign = ""

    @staticmethod
    def enabled() -> bool:
        return bool(Settings.LEDGER_ENABLED)

    @property
    def campaign(self) -> str:
        """The active campaign scope (empty outside campaign runs)."""
        with self._lock:
            return self._campaign

    @property
    def run_id(self) -> str:
        """The configured run id ("" until :meth:`configure`) — the ambient
        run context (telemetry/bundle.py) adopts a scenario-pinned id from
        here instead of minting over it."""
        with self._lock:
            return self._run_id

    def configure(self, run_id: str, campaign: Optional[str] = None) -> None:
        """Set the experiment-wide run id stamped into every ledger created
        (or already live) in this process — the parity benches derive it
        from the scenario seed so both backends' dumps carry the same id.
        ``campaign`` (campaigns/engine.py) additionally stamps the sampled
        campaign's id into dump headers; passing ``None`` leaves the current
        campaign scope untouched, ``""`` clears it."""
        with self._lock:
            self._run_id = str(run_id)
            if campaign is not None:
                self._campaign = str(campaign)
            for led in self._ledgers.values():
                led.run_id = self._run_id
                led.campaign = self._campaign

    def get(self, node: str) -> TrajectoryLedger:
        with self._lock:
            led = self._ledgers.get(node)
            if led is None:
                led = TrajectoryLedger(
                    node, run_id=self._run_id, campaign=self._campaign
                )
                self._ledgers[node] = led
            return led

    def peek(self, node: str) -> Optional[TrajectoryLedger]:
        with self._lock:
            return self._ledgers.get(node)

    def emit(self, node: str, kind: str, **fields: Any) -> bool:
        if not self.enabled():
            return False
        return self.get(node).emit(kind, **fields)

    def nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._ledgers)

    def dump_all(self, directory: str, canonical: bool = True) -> List[str]:
        """Write ``ledger_<node>.jsonl`` per live ledger; returns paths."""
        paths = []
        for node in self.nodes():
            led = self.peek(node)
            if led is None:
                continue
            paths.append(
                led.dump(
                    os.path.join(directory, f"ledger_{_safe_name(node)}.jsonl"),
                    canonical=canonical,
                )
            )
        return paths

    def reset(self) -> None:
        # The campaign scope deliberately SURVIVES reset: one campaign spans
        # many scenario runs, each of which resets the hub between backends
        # (run_scenario_wire/fused). The engine clears it explicitly with
        # configure(run_id, campaign="") when the campaign ends.
        with self._lock:
            self._ledgers.clear()
            self._run_id = ""


#: process-wide hub every emission point writes through.
LEDGERS = LedgerHub()


__all__ = [
    "LEDGER_SCHEMA_VERSION",
    "KIND_RANK",
    "TRAJECTORY_KINDS",
    "TrajectoryLedger",
    "LedgerHub",
    "LEDGERS",
    "canonical_params_hash",
]
