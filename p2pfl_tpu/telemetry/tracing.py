"""Distributed round tracing: spans whose IDs ride the gossip wire.

A *span* is a named, timed interval on one node (a stage, a gossip wait, a
received-frame handler). Spans form a tree through a ``contextvars``-based
current-span slot: entering ``TRACER.span(...)`` makes the new span the
parent of anything opened inside it — including on the *receiving* node,
because the wire context (``"<trace_id>:<span_id>"``) is stamped onto every
outbound :class:`~p2pfl_tpu.comm.envelope.Envelope` built inside a span and
re-attached around inbound dispatch. One experiment therefore produces ONE
trace id shared by every node it touches, and cross-node questions — where
did round N's wall-clock go, how long did model diffusion take between
sender and receiver — fall out of the span table.

Wire formats:

* ``Envelope.trace`` — carried natively by the in-memory transport and as a
  reserved trailing ``__trace__:`` arg on gRPC control frames.
* ``TRACE_META_KEY`` (``"__trace__"``) — the PFLT weights-frame header slot
  (same mechanism as the ``__codec__`` spec), used because the gRPC weights
  oneof has no args field.

Export: :meth:`Tracer.export_chrome_trace` renders the span buffer as Chrome
trace-event JSON — loadable in Perfetto / chrome://tracing, matching
``management/profiler.py``'s XLA-trace viewer story. Each node becomes a
"process" row; spans carry trace/span ids and the round in ``args``.
"""

from __future__ import annotations

import contextlib
import contextvars
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from p2pfl_tpu.telemetry.metrics import REGISTRY

_SPANS_DROPPED = REGISTRY.counter(
    "p2pfl_trace_spans_dropped_total",
    "Spans evicted from the bounded tracer buffer (oldest first) — nonzero "
    "means the exported trace is a suffix of the experiment",
)

#: PFLT weights-frame metadata key carrying the sender's wire context.
TRACE_META_KEY = "__trace__"

#: Reserved prefix for the trailing gRPC control-frame trace arg.
WIRE_ARG_PREFIX = "__trace__:"

_current: contextvars.ContextVar[Optional["SpanContext"]] = contextvars.ContextVar(
    "p2pfl_tpu_span", default=None
)


@dataclass(frozen=True)
class SpanContext:
    trace_id: str
    span_id: str

    def wire(self) -> str:
        return f"{self.trace_id}:{self.span_id}"


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str
    node: str
    start_s: float  # module-epoch-relative seconds (shared in-process clock)
    dur_s: float
    tid: int
    args: Dict[str, Any] = field(default_factory=dict)


def new_id() -> str:
    return secrets.token_hex(8)


def current_context() -> Optional[SpanContext]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else None


def current_wire() -> str:
    """Wire form of the active span context ("" outside any span) — what
    Envelope constructors stamp onto outbound frames."""
    ctx = _current.get()
    return ctx.wire() if ctx is not None else ""


def parse_wire(wire: str) -> Optional[SpanContext]:
    if not wire:
        return None
    trace_id, sep, span_id = wire.partition(":")
    if not sep or not trace_id or not span_id:
        return None
    return SpanContext(trace_id, span_id)


@contextlib.contextmanager
def attach_wire(wire: str) -> Iterator[Optional[SpanContext]]:
    """Adopt a remote span context for the enclosed block, so spans opened
    inside parent onto the SENDER's span (no-op for empty/malformed wire)."""
    ctx = parse_wire(wire)
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


class Tracer:
    """Bounded process-wide span buffer + span factory.

    All in-process nodes share one tracer (and one monotonic clock), so
    cross-node timelines line up without clock-sync machinery. A real
    multi-host deployment has one tracer PER PROCESS, each on its own
    clock: every exported trace therefore carries a wall-clock epoch
    anchor (:meth:`wall_epoch`), and
    :mod:`p2pfl_tpu.telemetry.critical_path` merges per-process exports
    onto one timeline, correcting residual NTP skew with the heartbeat
    clock-skew gauge (``CommunicationProtocol.export_trace`` annotates
    each dump with its node's per-peer skew snapshot).
    """

    def __init__(self, max_spans: Optional[int] = None) -> None:
        if max_spans is None:
            # Deferred import: config is dependency-free, but keeping the
            # read lazy lets tests construct bespoke tracers with explicit
            # caps without touching Settings.
            from p2pfl_tpu.config import Settings

            max_spans = Settings.TRACE_MAX_SPANS
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        # Wall clock at construction — kept for reference only; the export
        # anchor is RECOMPUTED at export time (see wall_epoch) so an NTP
        # step between construction and export cannot skew the mapping.
        self._epoch_wall_at_init = time.time()
        self.dropped = 0  # spans evicted by the bound

    def wall_epoch(self) -> float:
        """Wall-clock time (epoch seconds) corresponding to span time 0.

        ``span.start_s + wall_epoch()`` maps any span onto the wall clock.
        Recomputed from the CURRENT wall clock on every call: the monotonic
        span clock never steps, so anchoring through "now" reflects any NTP
        corrections since construction instead of freezing the stale offset.
        """
        return time.time() - (time.perf_counter() - self._epoch)

    def new_trace_id(self) -> str:
        return new_id()

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        node: str = "",
        trace_id: Optional[str] = None,
        **args: Any,
    ) -> Iterator[SpanContext]:
        """Open a span as a child of the current context (or a fresh trace).

        ``trace_id`` pins the span to a known trace (e.g. the experiment
        trace adopted from a start_learning frame) regardless of ambient
        context; the parent link is kept only when it belongs to the same
        trace.
        """
        parent = _current.get()
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None else new_id()
        parent_id = (
            parent.span_id if parent is not None and parent.trace_id == trace_id else ""
        )
        ctx = SpanContext(trace_id, new_id())
        token = _current.set(ctx)
        t0 = time.perf_counter()
        try:
            yield ctx
        finally:
            t1 = time.perf_counter()
            _current.reset(token)
            self._record(
                Span(
                    name=name,
                    trace_id=trace_id,
                    span_id=ctx.span_id,
                    parent_id=parent_id,
                    node=node,
                    start_s=t0 - self._epoch,
                    dur_s=t1 - t0,
                    tid=threading.get_ident() & 0xFFFFFFFF,
                    args={k: v for k, v in args.items() if v is not None},
                )
            )

    @contextlib.contextmanager
    def recv_span(
        self, name: str, node: str, wire: str, **args: Any
    ) -> Iterator[None]:
        """Receiver-side span parented onto the sender's wire context.

        No-op (and records nothing) when ``wire`` is empty — untraced
        traffic like heartbeats must not churn the buffer.
        """
        if not wire:
            yield
            return
        with attach_wire(wire):
            with self.span(name, node=node, **args):
                yield

    def _record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
                _SPANS_DROPPED.inc()
            self._spans.append(span)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # --- export -------------------------------------------------------------

    def export_chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}`` object
        form). Nodes map to process rows via ``process_name`` metadata
        events; every span is a complete ("X") event with trace/span ids in
        ``args`` so Perfetto queries can join cross-node spans on trace id.

        Events are sorted by ``(ts, pid, tid, name)`` so identical span sets
        always export byte-identically, and the top-level ``metadata`` block
        carries the wall-clock epoch anchor (``wall_epoch_s``: wall seconds
        at span time 0, recomputed at export) — the key that lets
        :mod:`p2pfl_tpu.telemetry.critical_path` merge traces exported by
        DIFFERENT processes onto one timeline.
        """
        spans = self.spans()
        pids: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []
        for s in spans:
            pid = pids.setdefault(s.node or "process", len(pids) + 1)
            events.append(
                {
                    "name": s.name,
                    "cat": "p2pfl_tpu",
                    "ph": "X",
                    "ts": round(s.start_s * 1e6, 1),
                    "dur": round(s.dur_s * 1e6, 1),
                    "pid": pid,
                    "tid": s.tid,
                    "args": {
                        **s.args,
                        "trace_id": s.trace_id,
                        "span_id": s.span_id,
                        "parent_id": s.parent_id,
                    },
                }
            )
        events.sort(key=lambda e: (e["ts"], e["pid"], e["tid"], e["name"]))
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": node},
            }
            for node, pid in pids.items()
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "metadata": {
                "wall_epoch_s": self.wall_epoch(),
                "wall_epoch_at_init_s": self._epoch_wall_at_init,
                "exported_at_s": time.time(),
                "ts_unit": "us since tracer epoch (monotonic)",
            },
        }


#: The process-wide tracer every subsystem records spans into.
TRACER = Tracer()
