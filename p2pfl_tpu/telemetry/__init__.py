"""Federation telemetry plane: metrics registry + distributed round tracing.

Two halves, both dependency-free (stdlib only) so every layer of the
framework can import them without cycles:

* :mod:`p2pfl_tpu.telemetry.metrics` — process-wide registry of labeled
  counters / gauges / histograms with lock-cheap hot-path increments
  (a child increment is one small-lock add, well under 2µs).
* :mod:`p2pfl_tpu.telemetry.tracing` — span context managers whose
  trace/span IDs ride the gossip wire (``Envelope.trace`` + the PFLT
  ``__trace__`` header slot), so one round's wall-clock is attributable
  across nodes; per-round timelines export as Chrome trace-event JSON
  (Perfetto-viewable, same viewer story as ``management/profiler.py``'s
  XLA traces).

Export surfaces live in :mod:`p2pfl_tpu.telemetry.export`: Prometheus text
exposition and a JSON snapshot of the registry.

The federation observatory builds on both halves:

* :mod:`p2pfl_tpu.telemetry.sketches` — mergeable, wire-encodable
  distribution summaries (relative-error quantile sketches + a HyperLogLog
  distinct estimator) that keep fleet views sublinear in population,
* :mod:`p2pfl_tpu.telemetry.digest` — the versioned per-node health digest
  piggybacked on heartbeats (``Envelope.digest``; v2 carries sketches),
* :mod:`p2pfl_tpu.telemetry.observatory` — the per-node fleet view with
  derived straggler / suspect / link scores (``p2pfl_fed_*`` section),
  TTL eviction and bounded population-overflow tracking,
* :mod:`p2pfl_tpu.telemetry.flight_recorder` — the bounded postmortem
  event ring dumped to ``artifacts/flightrec_<node>.json`` on failure,
* :mod:`p2pfl_tpu.telemetry.ledger` — the deterministic trajectory ledger
  both execution backends (wire and fused mesh) emit identically; the
  sim↔real parity gate (``scripts/parity_diff.py``, ``bench.py --parity``)
  is built on its canonical dumps.

The performance attribution plane builds on the tracer:

* :mod:`p2pfl_tpu.telemetry.critical_path` — per-round critical paths
  (gating node + span chain) over the federation span DAG, stage
  wall-clock shares, and the train<->diffuse overlap report; merges
  per-process trace exports with wall-clock anchors + heartbeat
  clock-skew correction.
"""

from p2pfl_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
)
from p2pfl_tpu.telemetry.tracing import TRACER, Tracer  # noqa: F401
from p2pfl_tpu.telemetry.critical_path import (  # noqa: F401
    CriticalPathAnalyzer,
)
from p2pfl_tpu.telemetry.sketches import (  # noqa: F401
    DistinctEstimator,
    QuantileSketch,
    SKETCHES,
)
from p2pfl_tpu.telemetry.ledger import (  # noqa: F401
    LEDGERS,
    TrajectoryLedger,
    canonical_params_hash,
)

__all__ = [
    "Counter",
    "CriticalPathAnalyzer",
    "DistinctEstimator",
    "Gauge",
    "Histogram",
    "LEDGERS",
    "MetricsRegistry",
    "QuantileSketch",
    "REGISTRY",
    "SKETCHES",
    "TRACER",
    "Tracer",
    "TrajectoryLedger",
    "canonical_params_hash",
]
