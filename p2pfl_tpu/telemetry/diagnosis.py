"""fed_doctor — automated root-cause diagnosis over evidence bundles.

The streams a bundle joins (trajectory ledger, flight recorder, metrics
snapshot, observatory snapshot, parity report, trigger context) each
answer a narrow question; incidents live in their INTERSECTION. This
module holds the evidence-joined rule catalog: every rule states the
anomaly it claims, cites the member signals that support it (the
*evidence chain*), runs the checks that could disprove it (the
*exonerating checks*), and reports a confidence that grows with
independent corroboration. ``diagnose`` ranks surviving findings by
(severity, confidence) and the result renders both machine-readable
(``incident.json``, consumed by the fed_top DIAGNOSIS banner) and
human-readable (``scripts/fed_doctor.py``).

Calibration contract (enforced by ``make doctor-check``): a clean run
yields ZERO findings — every rule requires an explicit anomaly signal,
never just "metrics exist" — and on the seeded fault scenarios the
injected fault must rank first.
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry.metrics import REGISTRY

#: bump when the incident-report shape changes
INCIDENT_SCHEMA_VERSION = 1

_DIAGNOSES = REGISTRY.counter(
    "p2pfl_doctor_diagnoses_total",
    "Diagnosis findings emitted by the fed_doctor rule catalog, by rule",
    labels=("rule",),
)

# --- rule thresholds (module constants — doctor-check calibrates against
# these; a clean 3-node control run must clear every one of them) -------------

#: admission rejections attributed to one sender before byzantine_active fires
BYZANTINE_REJECTION_BURST = 2
#: share of all rejections the top sender must hold (a *concentrated* burst)
BYZANTINE_CONCENTRATION = 0.6
#: observatory straggler score at/above which straggler_gating engages
STRAGGLER_SCORE_MIN = 1.5
#: decode-flavored rejection events before codec_corruption_storm fires
CODEC_STORM_EVENTS = 3
#: flight-recorder "recompile" events before recompile_storm fires
RECOMPILE_STORM_EVENTS = 3
#: rejection reasons that indicate structural corruption, not adversarial
#: content — they route to codec_corruption_storm instead of byzantine_active
CODEC_REASONS = ("decode", "codec", "corrupt", "deserialize", "dtype", "shape")


@dataclass
class Finding:
    """One diagnosed incident cause."""

    rule: str
    severity: str  # "critical" | "warning" | "info"
    confidence: float  # 0..1, grows with independent corroboration
    summary: str
    #: evidence chain: which bundle members said what, in support
    evidence: List[str] = field(default_factory=list)
    #: exonerating checks that RAN and came back clean (what was ruled out)
    exonerated: List[str] = field(default_factory=list)
    #: machine-readable specifics (peers, counts, rounds)
    data: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Evidence:
    """Everything a bundle (or a live artifacts/ dir) yields, parsed."""

    source: str = ""
    run_id: str = ""
    manifest: Optional[Dict[str, Any]] = None
    #: node -> ledger events (ledger_<node>.jsonl bodies, headers stripped)
    ledgers: Dict[str, List[Dict[str, Any]]] = field(default_factory=dict)
    #: node -> flightrec doc (flightrec_<node>.json)
    flightrecs: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    #: federation_snapshot.json (observatory / population / supervisor doc)
    snapshot: Optional[Dict[str, Any]] = None
    #: metrics.json "families" section (export.snapshot shape)
    metrics: Dict[str, Any] = field(default_factory=dict)
    #: parity_diff.json
    parity: Optional[Dict[str, Any]] = None
    #: context.json (trigger + optional error block)
    context: Optional[Dict[str, Any]] = None

    # --- joined accessors ---------------------------------------------------

    def ledger_events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for evs in self.ledgers.values():
            for ev in evs:
                if kind is None or ev.get("kind") == kind:
                    out.append(ev)
        return out

    def flight_events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for doc in self.flightrecs.values():
            for ev in doc.get("events", ()):
                if kind is None or ev.get("kind") == kind:
                    out.append(ev)
        return out

    def metric_total(self, name: str, **labels: str) -> float:
        fam = self.metrics.get(name)
        if not fam:
            return 0.0
        total = 0.0
        for s in fam.get("samples", ()):
            slabels = s.get("labels", {})
            if all(slabels.get(k) == v for k, v in labels.items()):
                total += float(s.get("value", 0.0))
        return total

    def metric_group(self, name: str, by: str) -> Dict[str, float]:
        """Sum a counter/gauge family's samples grouped by one label."""
        fam = self.metrics.get(name)
        out: Dict[str, float] = {}
        if not fam:
            return out
        for s in fam.get("samples", ()):
            key = s.get("labels", {}).get(by, "")
            out[key] = out.get(key, 0.0) + float(s.get("value", 0.0))
        return out

    def peer_scores(self) -> Dict[str, Dict[str, float]]:
        if not self.snapshot:
            return {}
        out: Dict[str, Dict[str, float]] = {}
        for peer, entry in (self.snapshot.get("peers") or {}).items():
            scores = entry.get("scores") or {
                k: entry[k] for k in ("straggler", "suspect", "link") if k in entry
            }
            if scores:
                out[peer] = {k: float(v) for k, v in scores.items()}
        return out

    def trigger(self) -> str:
        return str((self.context or {}).get("trigger", ""))


def _read_json(path: str) -> Optional[Any]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except Exception:
        return None


def load_evidence(path: str) -> Evidence:
    """Parse a bundle directory OR a live ``artifacts/`` directory — same
    member naming either way, a bundle just guarantees completeness and
    run-id coherence (its manifest records both)."""
    ev = Evidence(source=path)
    ev.manifest = _read_json(os.path.join(path, "manifest.json"))
    if ev.manifest:
        ev.run_id = str(ev.manifest.get("run_id", ""))
    for lpath in sorted(glob.glob(os.path.join(path, "ledger_*.jsonl"))):
        events: List[Dict[str, Any]] = []
        node = os.path.basename(lpath)[len("ledger_"):-len(".jsonl")]
        try:
            with open(lpath, "r", encoding="utf-8") as f:
                for i, line in enumerate(f):
                    line = line.strip()
                    if not line:
                        continue
                    doc = json.loads(line)
                    if i == 0 and doc.get("ledger") == "trajectory":
                        node = str(doc.get("node", node))
                        if not ev.run_id:
                            ev.run_id = str(doc.get("run_id", ""))
                        continue
                    events.append(doc)
        except Exception:
            continue
        ev.ledgers[node] = events
    for fpath in sorted(glob.glob(os.path.join(path, "flightrec_*.json"))):
        doc = _read_json(fpath)
        if isinstance(doc, dict):
            ev.flightrecs[str(doc.get("node", os.path.basename(fpath)))] = doc
            if not ev.run_id:
                ev.run_id = str((doc.get("header") or {}).get("run_id", ""))
    snap = _read_json(os.path.join(path, "federation_snapshot.json"))
    if isinstance(snap, dict):
        ev.snapshot = snap
        if not ev.run_id:
            ev.run_id = str((snap.get("header") or {}).get("run_id", ""))
    metrics_doc = _read_json(os.path.join(path, "metrics.json"))
    if isinstance(metrics_doc, dict):
        ev.metrics = metrics_doc.get("families", metrics_doc)
    parity = _read_json(os.path.join(path, "parity_diff.json"))
    if isinstance(parity, dict):
        ev.parity = parity
    ctx = _read_json(os.path.join(path, "context.json"))
    if isinstance(ctx, dict):
        ev.context = ctx
    return ev


# --- the rule catalog ---------------------------------------------------------
#
# Each rule: Evidence -> Optional[Finding]. Rules must be conservative —
# fire only on explicit anomaly signals, cite every member consulted, and
# record the checks that could have disproved them.


def _rejections(ev: Evidence) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(per-sender event counts, per-reason event counts) from the
    deduped ledger admission stream (the metric keeps raw counts; the
    ledger keeps one fact per (round, sender, reason) — better for
    burst shape)."""
    by_sender: Dict[str, int] = {}
    by_reason: Dict[str, int] = {}
    for e in ev.ledger_events("admission_rejected"):
        s, r = str(e.get("sender", "?")), str(e.get("reason", "?"))
        by_sender[s] = by_sender.get(s, 0) + 1
        by_reason[r] = by_reason.get(r, 0) + 1
    return by_sender, by_reason


def _codec_flavored(reason: str) -> bool:
    reason = reason.lower()
    return any(tag in reason for tag in CODEC_REASONS)


def _chaos_byzantine(ev: Evidence) -> Tuple[float, List[str]]:
    """(count, evidence lines) for injected byzantine behavior — chaos
    metric + chaos_fault ledger events."""
    lines: List[str] = []
    count = 0.0
    for fault, n in ev.metric_group("p2pfl_chaos_faults_total", "fault").items():
        if fault.startswith("byzantine") and n > 0:
            count += n
            lines.append(f"metrics: p2pfl_chaos_faults_total{{fault={fault}}} = {n:g}")
    byz_events = [
        e for e in ev.ledger_events("chaos_fault")
        if str(e.get("fault", "")).startswith("byzantine")
    ]
    if byz_events:
        count += len(byz_events)
        peers = sorted({str(e.get("peer", "?")) for e in byz_events})
        lines.append(f"ledger: chaos_fault byzantine events for {', '.join(peers)}")
    return count, lines


def rule_byzantine_active(ev: Evidence) -> Optional[Finding]:
    """A concentrated admission-rejection burst attributed to one sender,
    corroborated by suspect score and/or injected chaos adversaries."""
    by_sender, by_reason = _rejections(ev)
    if not by_sender:
        return None
    # Structural-corruption storms are a different disease (codec rule).
    codec_n = sum(n for r, n in by_reason.items() if _codec_flavored(r))
    total = sum(by_sender.values())
    if codec_n > total / 2:
        return None
    top_sender, top_n = max(by_sender.items(), key=lambda kv: kv[1])
    if top_n < BYZANTINE_REJECTION_BURST or top_n < BYZANTINE_CONCENTRATION * total:
        return None
    evidence = [
        f"ledger: {top_n} admission_rejected event(s) name {top_sender} "
        f"as sender ({top_n}/{total} of all rejections)",
    ]
    metric_n = ev.metric_total("p2pfl_updates_rejected_total", source=top_sender)
    if metric_n:
        evidence.append(
            f"metrics: p2pfl_updates_rejected_total{{source={top_sender}}} "
            f"= {metric_n:g} raw frames"
        )
    confidence = 0.6
    suspect = ev.peer_scores().get(top_sender, {}).get("suspect", 0.0)
    if suspect > 0:
        confidence += 0.15
        evidence.append(
            f"snapshot: observatory suspect score {suspect:g} for {top_sender}"
        )
    chaos_n, chaos_lines = _chaos_byzantine(ev)
    if chaos_n:
        confidence += 0.2
        evidence.extend(chaos_lines)
    exonerated = []
    if not any(_codec_flavored(r) for r in by_reason):
        exonerated.append(
            "codec corruption ruled out: every rejection reason is "
            "admission-plane (norm/claim screening), none decode-flavored"
        )
    lost = {str(e.get("peer")) for e in ev.flight_events("peer_lost")}
    if top_sender not in lost:
        exonerated.append(
            f"churn ruled out: no peer_lost event for {top_sender} — it kept "
            "heartbeating while its frames were rejected"
        )
    return Finding(
        rule="byzantine_active",
        severity="critical",
        confidence=min(0.95, confidence),
        summary=(
            f"{top_sender} is behaving adversarially: the fleet rejected "
            f"{top_n} of its model-plane frames"
            + (" (seeded chaos adversary confirmed)" if chaos_n else "")
        ),
        evidence=evidence,
        exonerated=exonerated,
        data={"peer": top_sender, "rejections": top_n, "suspect_score": suspect},
    )


def rule_adversary_under_rejection(ev: Evidence) -> Optional[Finding]:
    """Chaos says an adversary is injecting poisoned frames, yet admission
    rejected (almost) nothing — the defense is not engaging."""
    chaos_n, chaos_lines = _chaos_byzantine(ev)
    if not chaos_n:
        return None
    by_sender, _ = _rejections(ev)
    rejected = sum(by_sender.values())
    metric_rej = sum(ev.metric_group("p2pfl_updates_rejected_total", "source").values())
    if rejected > 0 or metric_rej > 0:
        return None
    return Finding(
        rule="adversary_under_rejection",
        severity="critical",
        confidence=0.8,
        summary=(
            f"an active adversary ({chaos_n:g} corrupted frame(s) injected) "
            "produced ZERO admission rejections — screening is not engaging"
        ),
        evidence=chaos_lines
        + ["ledger+metrics: no admission_rejected events, rejected_total = 0"],
        exonerated=[],
        data={"injected": chaos_n, "rejections": 0},
    )


def rule_codec_corruption_storm(ev: Evidence) -> Optional[Finding]:
    """Decode-flavored rejections across multiple frames/senders: wire or
    codec corruption, not one adversary's content."""
    by_sender, by_reason = _rejections(ev)
    codec_events = [
        e for e in ev.ledger_events("admission_rejected")
        if _codec_flavored(str(e.get("reason", "")))
    ]
    if len(codec_events) < CODEC_STORM_EVENTS:
        return None
    senders = sorted({str(e.get("sender", "?")) for e in codec_events})
    reasons = sorted({str(e.get("reason", "?")) for e in codec_events})
    return Finding(
        rule="codec_corruption_storm",
        severity="critical",
        confidence=0.6 + (0.2 if len(senders) > 1 else 0.0),
        summary=(
            f"{len(codec_events)} structurally-undecodable frames from "
            f"{len(senders)} sender(s) — codec/wire corruption, not "
            "adversarial content"
        ),
        evidence=[
            f"ledger: {len(codec_events)} decode-flavored admission_rejected "
            f"event(s), reasons: {', '.join(reasons)}",
            f"senders involved: {', '.join(senders)}",
        ],
        exonerated=(
            ["single-adversary hypothesis weakened: corruption spans "
             f"{len(senders)} independent senders"] if len(senders) > 1 else []
        ),
        data={"events": len(codec_events), "senders": senders, "reasons": reasons},
    )


def rule_straggler_gating(ev: Evidence) -> Optional[Finding]:
    """One peer runs far behind the fleet AND aggregation measurably waited
    on (or gave up on) someone — lateness alone is not an incident."""
    scores = ev.peer_scores()
    if not scores:
        return None
    top_peer, top = max(
        scores.items(), key=lambda kv: kv[1].get("straggler", 0.0)
    )
    s = top.get("straggler", 0.0)
    if s < STRAGGLER_SCORE_MIN:
        return None
    gating: List[str] = []
    stalls = ev.metric_total("p2pfl_aggregation_stall_partials_total")
    timeouts = ev.metric_total("p2pfl_aggregation_timeout_partials_total")
    if stalls:
        gating.append(
            f"metrics: p2pfl_aggregation_stall_partials_total = {stalls:g}"
        )
    if timeouts:
        gating.append(
            f"metrics: p2pfl_aggregation_timeout_partials_total = {timeouts:g}"
        )
    slow_evs = [
        e for e in ev.ledger_events("chaos_fault")
        if str(e.get("fault", "")) in ("slow", "delay")
    ]
    fault_delays = ev.metric_group("p2pfl_chaos_faults_total", "fault").get("delay", 0)
    if not gating and not slow_evs and not fault_delays:
        return None
    confidence = 0.55 + 0.15 * bool(gating) + 0.1 * bool(slow_evs or fault_delays)
    evidence = [
        f"snapshot: observatory straggler score {s:g} for {top_peer} "
        "(round lag + late entry + step-time z-score)",
        *gating,
    ]
    if slow_evs or fault_delays:
        evidence.append(
            "chaos: injected slow-host/delay faults present "
            f"(delay count {fault_delays:g})"
        )
    exonerated = []
    if top_peer not in {str(e.get("peer")) for e in ev.flight_events("peer_lost")}:
        exonerated.append(
            f"death ruled out: {top_peer} kept heartbeating (no peer_lost)"
        )
    if scores.get(top_peer, {}).get("suspect", 0.0) == 0.0:
        exonerated.append(
            f"byzantine ruled out: suspect score 0 for {top_peer} — slow, "
            "not malicious"
        )
    return Finding(
        rule="straggler_gating",
        severity="warning",
        confidence=min(0.9, confidence),
        summary=(
            f"{top_peer} straggles the fleet (score {s:g}) and round "
            "progress is gated on it"
        ),
        evidence=evidence,
        exonerated=exonerated,
        data={"peer": top_peer, "straggler_score": s},
    )


def rule_churn_starved_cohort(ev: Evidence) -> Optional[Finding]:
    """Peers died mid-round without recovering, and aggregation had to
    proceed without (or wait for) their contributions."""
    lost = {str(e.get("peer")) for e in ev.flight_events("peer_lost")}
    recovered = {str(e.get("peer")) for e in ev.flight_events("peer_recovered")}
    dead = sorted(lost - recovered)
    if not dead:
        return None
    dead_contrib = ev.metric_total("p2pfl_aggregation_dead_contributors_total")
    stalls = ev.metric_total("p2pfl_aggregation_stall_partials_total")
    timeouts = ev.metric_total("p2pfl_aggregation_timeout_partials_total")
    crash_n = ev.metric_group("p2pfl_chaos_faults_total", "fault").get("crash", 0.0)
    if not (dead_contrib or stalls or timeouts or crash_n):
        return None
    evidence = [
        f"flightrec: peer_lost without recovery for {', '.join(dead)}",
    ]
    confidence = 0.6
    if dead_contrib:
        evidence.append(
            "metrics: p2pfl_aggregation_dead_contributors_total = "
            f"{dead_contrib:g} — aggregation dropped dead peers' shares"
        )
        confidence += 0.1
    if stalls or timeouts:
        evidence.append(
            f"metrics: stall/timeout partial aggregations = {stalls + timeouts:g}"
        )
        confidence += 0.05
    if crash_n:
        evidence.append(
            f"chaos: {crash_n:g} frame(s) blackholed by injected crash faults"
        )
        confidence += 0.15
    return Finding(
        rule="churn_starved_cohort",
        severity="critical",
        confidence=min(0.95, confidence),
        summary=(
            f"{len(dead)} peer(s) died mid-run without recovering "
            f"({', '.join(dead)}); the cohort aggregated without them"
        ),
        evidence=evidence,
        exonerated=[
            "heartbeat false-death ruled out: no peer_recovered follows the "
            "loss — the peers are genuinely gone"
        ],
        data={"dead": dead, "dead_contributors": dead_contrib},
    )


def rule_heartbeat_false_death(ev: Evidence) -> Optional[Finding]:
    """Peers declared dead then observed alive again, with no injected
    crash to explain the loss: the failure detector flapped."""
    lost = {str(e.get("peer")) for e in ev.flight_events("peer_lost")}
    recovered = {str(e.get("peer")) for e in ev.flight_events("peer_recovered")}
    flapped = sorted(lost & recovered)
    if not flapped:
        return None
    crash_n = ev.metric_group("p2pfl_chaos_faults_total", "fault").get("crash", 0.0)
    partition_n = ev.metric_group("p2pfl_chaos_faults_total", "fault").get(
        "partition", 0.0
    )
    if crash_n or partition_n:
        return None  # the flap has a legitimate cause — not a detector bug
    return Finding(
        rule="heartbeat_false_death",
        severity="warning",
        confidence=0.6,
        summary=(
            f"{len(flapped)} peer(s) were declared dead then recovered "
            f"({', '.join(flapped)}) with no injected crash/partition — "
            "heartbeat patience is too tight for this link"
        ),
        evidence=[
            f"flightrec: peer_lost AND peer_recovered for {', '.join(flapped)}",
            "chaos: zero crash/partition faults — nothing explains the loss",
        ],
        exonerated=[],
        data={"peers": flapped},
    )


def rule_partition_heal_asymmetry(ev: Evidence) -> Optional[Finding]:
    """After an injected partition, some observers healed a peer and
    others that lost it did not — the heal did not propagate fleet-wide."""
    partition_n = ev.metric_group("p2pfl_chaos_faults_total", "fault").get(
        "partition", 0.0
    )
    if not partition_n:
        return None
    lost_by: Dict[str, set] = {}
    rec_by: Dict[str, set] = {}
    for node, doc in ev.flightrecs.items():
        for e in doc.get("events", ()):
            if e.get("kind") == "peer_lost":
                lost_by.setdefault(str(e.get("peer")), set()).add(node)
            elif e.get("kind") == "peer_recovered":
                rec_by.setdefault(str(e.get("peer")), set()).add(node)
    asym = {
        peer: sorted(lost_by[peer] - rec_by.get(peer, set()))
        for peer in lost_by
        if rec_by.get(peer) and (lost_by[peer] - rec_by.get(peer, set()))
    }
    if not asym:
        return None
    lines = [
        f"flightrec: {peer} recovered at {sorted(rec_by[peer])} but not at "
        f"{still}" for peer, still in sorted(asym.items())
    ]
    return Finding(
        rule="partition_heal_asymmetry",
        severity="warning",
        confidence=0.65,
        summary=(
            f"partition healed asymmetrically: {len(asym)} peer(s) "
            "recovered on one side of the fleet but stayed dead on the other"
        ),
        evidence=[
            f"chaos: {partition_n:g} frame(s) blocked by injected partition",
            *lines,
        ],
        exonerated=[],
        data={"peers": {p: s for p, s in asym.items()}},
    )


def rule_oom_degrade_ladder(ev: Evidence) -> Optional[Finding]:
    """The supervisor restarted on OOM and climbed the degrade ladder —
    the configured shape does not fit the device."""
    oom = ev.metric_total("p2pfl_supervisor_restarts_total", kind="oom")
    err = ((ev.context or {}).get("error") or {}).get("message", "")
    ctx_oom = "RESOURCE_EXHAUSTED" in str(err)
    if not oom and not ctx_oom:
        return None
    degrades = sum(
        ev.metric_group("p2pfl_supervisor_degrade_steps_total", "action").values()
    )
    evidence = []
    if oom:
        evidence.append(
            f"metrics: p2pfl_supervisor_restarts_total{{kind=oom}} = {oom:g}"
        )
    if ctx_oom:
        evidence.append("context: RESOURCE_EXHAUSTED in the triggering error")
    if degrades:
        evidence.append(
            f"metrics: {degrades:g} degrade-ladder step(s) taken "
            "(chunk/cohort shrinking)"
        )
    return Finding(
        rule="oom_degrade_ladder",
        severity="critical",
        confidence=min(0.9, 0.7 + 0.1 * bool(degrades) + 0.1 * (oom > 1)),
        summary=(
            "device memory exhausted: the supervisor restarted on OOM"
            + (f" and took {degrades:g} degrade step(s)" if degrades else "")
            + " — the population shape does not fit this accelerator"
        ),
        evidence=evidence,
        exonerated=[],
        data={"oom_restarts": oom, "degrade_steps": degrades},
    )


def rule_parity_divergence(ev: Evidence) -> Optional[Finding]:
    """The two backends' trajectory ledgers diverged — localized to the
    first differing event."""
    if not ev.parity or ev.parity.get("status") != "DIVERGED":
        return None
    first = ev.parity.get("first_divergence") or {}
    where = ", ".join(
        f"{k}={first[k]}" for k in ("round", "kind", "sender") if k in first
    )
    return Finding(
        rule="parity_divergence",
        severity="critical",
        confidence=0.9,
        summary=(
            "wire and fused backends diverged"
            + (f" — first at {where}" if where else "")
        ),
        evidence=[
            "parity_diff: status DIVERGED after "
            f"{ev.parity.get('compared_events', '?')} aligned event(s)",
            f"parity_diff: first_divergence {first}" if first else
            "parity_diff: no aligned prefix at all",
        ],
        exonerated=[],
        data={"first_divergence": first},
    )


def rule_recompile_storm(ev: Evidence) -> Optional[Finding]:
    """Repeated XLA recompilation mid-run — a shape/donation bug turning
    every chunk into a compile."""
    recompiles = [
        e for e in ev.flight_events()
        if "recompile" in str(e.get("kind", "")).lower()
    ]
    if len(recompiles) < RECOMPILE_STORM_EVENTS:
        return None
    return Finding(
        rule="recompile_storm",
        severity="warning",
        confidence=0.7,
        summary=(
            f"{len(recompiles)} recompilation events mid-run — static "
            "shapes are varying across chunks (cache-defeating)"
        ),
        evidence=[
            f"flightrec: {len(recompiles)} 'recompile' event(s) recorded",
        ],
        exonerated=[],
        data={"events": len(recompiles)},
    )


def rule_device_tripwire(ev: Evidence) -> Optional[Finding]:
    """The device observatory tripped (non-finite params / loss
    divergence) — numeric fault localized by the trip context."""
    trig = ev.trigger()
    ctx = (ev.context or {}).get("context") or {}
    trips = ev.flight_events("devobs_trip")
    if trig != "devobs_trip" and not trips:
        return None
    kind = str(ctx.get("kind") or (trips[0].get("trip_kind") if trips else "?"))
    where = ctx.get("round", trips[0].get("round") if trips else "?")
    evidence = []
    if trig == "devobs_trip":
        evidence.append(f"context: trigger devobs_trip (kind={kind}, round={where})")
    if trips:
        evidence.append(f"flightrec: {len(trips)} devobs_trip event(s)")
    mesh_trips = sum(ev.metric_group("p2pfl_mesh_trips_total", "kind").values())
    if mesh_trips:
        evidence.append(f"metrics: p2pfl_mesh_trips_total = {mesh_trips:g}")
    return Finding(
        rule="device_tripwire",
        severity="critical",
        confidence=0.85,
        summary=(
            f"device health guard tripped: {kind} at round {where} — "
            "the parameter stream went numerically bad in-scan"
        ),
        evidence=evidence,
        exonerated=[],
        data={"kind": kind, "round": where},
    )


_SEVERITY_RANK = {"critical": 0, "warning": 1, "info": 2}

RULES: Tuple[Callable[[Evidence], Optional[Finding]], ...] = (
    rule_device_tripwire,
    rule_parity_divergence,
    rule_oom_degrade_ladder,
    rule_byzantine_active,
    rule_adversary_under_rejection,
    rule_codec_corruption_storm,
    rule_churn_starved_cohort,
    rule_straggler_gating,
    rule_partition_heal_asymmetry,
    rule_heartbeat_false_death,
    rule_recompile_storm,
)


def diagnose(ev: Evidence) -> List[Finding]:
    """Run the full catalog, drop findings below
    ``Settings.DOCTOR_MIN_CONFIDENCE``, rank by (severity, confidence)."""
    findings: List[Finding] = []
    floor = float(Settings.DOCTOR_MIN_CONFIDENCE)
    for rule in RULES:
        try:
            f = rule(ev)
        except Exception:  # a broken rule must not hide the others
            continue
        if f is not None and f.confidence >= floor:
            findings.append(f)
            _DIAGNOSES.labels(f.rule).inc()
    findings.sort(
        key=lambda f: (_SEVERITY_RANK.get(f.severity, 9), -f.confidence, f.rule)
    )
    return findings


def incident_doc(
    findings: List[Finding], run_id: str = "", source: str = ""
) -> Dict[str, Any]:
    """Machine-readable incident report (what ``incident.json`` holds and
    the fed_top DIAGNOSIS banner consumes)."""
    return {
        "incident": "fed_doctor",
        "v": INCIDENT_SCHEMA_VERSION,
        "run_id": run_id,
        "source": source,
        "findings": [asdict(f) for f in findings],
        "top": findings[0].rule if findings else None,
    }


def render_report(doc: Dict[str, Any]) -> str:
    """Human-readable rendering of an incident doc."""
    lines: List[str] = []
    rid = doc.get("run_id") or "-"
    lines.append(f"fed_doctor incident report  (run {rid})")
    lines.append(f"source: {doc.get('source') or '-'}")
    findings = doc.get("findings") or []
    if not findings:
        lines.append("")
        lines.append("no findings — every rule came back clean.")
        return "\n".join(lines)
    lines.append(f"findings: {len(findings)} (ranked)")
    for i, f in enumerate(findings, 1):
        lines.append("")
        lines.append(
            f"#{i} [{f.get('severity', '?').upper()}] {f.get('rule')} "
            f"(confidence {float(f.get('confidence', 0)):.0%})"
        )
        lines.append(f"   {f.get('summary')}")
        for e in f.get("evidence") or []:
            lines.append(f"   + {e}")
        for x in f.get("exonerated") or []:
            lines.append(f"   - {x}")
    return "\n".join(lines)


__all__ = [
    "INCIDENT_SCHEMA_VERSION",
    "Evidence",
    "Finding",
    "RULES",
    "diagnose",
    "incident_doc",
    "load_evidence",
    "render_report",
]
