"""Gossiped health digests: one node's vitals, compact enough to ride a beat.

In a decentralized federation there is no coordinator to scrape the
telemetry registry (PR 2), so every node's rich local view is trapped in its
own process. The fix is to make observability itself ride the membership
wire: each node periodically snapshots a :class:`HealthDigest` — current
round/stage, learner throughput, wire traffic, aggregation progress,
admission rejections (attributed per sender), chaos faults, device memory —
and piggybacks it on the heartbeat it was already broadcasting.

Wire format: the encoded digest travels in ``Envelope.digest`` (carried
natively by the in-memory transport; the gRPC transport maps it onto a
reserved trailing control arg with :data:`WIRE_ARG_PREFIX`, exactly like
``Envelope.trace`` — see ``grpc_protocol._env_to_pb``). The payload itself
is versioned compact JSON:

* **absent digests are fine** — a digest-free (older) node's beats dispatch
  unchanged, and its peers simply have no fleet entry for it;
* **unknown versions are tolerated** — :func:`decode` keeps every field it
  recognizes and ignores the rest, so a newer node's digest still feeds an
  older observatory instead of breaking membership.

The federation-wide assembly of these digests lives in
:mod:`p2pfl_tpu.telemetry.observatory`.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Optional

from p2pfl_tpu.telemetry.metrics import REGISTRY
from p2pfl_tpu.telemetry.sketches import SKETCHES

log = logging.getLogger("p2pfl_tpu")

#: Bump when the digest schema changes incompatibly. Decoders keep reading
#: newer digests best-effort (known fields only). v2 adds the ``sk`` sketch
#: table (mergeable quantile sketches + distinct-contributor estimator);
#: v1 digests decode with an empty table and stay first-class citizens.
DIGEST_VERSION = 2

#: Reserved prefix for the trailing gRPC control-frame digest arg (the
#: ``__trace__:`` pattern — the proto schema predates digests and protoc is
#: not in the image to regenerate it).
WIRE_ARG_PREFIX = "__digest__:"

#: Digest payloads above this are dropped at decode: a v2 digest is a few
#: KB of JSON (four bounded sketches + scalars — size is a function of the
#: bin cap, NOT of fleet size or stream length); anything larger is corrupt
#: or hostile (heartbeats must stay cheap — they are the failure detector).
MAX_DIGEST_BYTES = 16384

#: Per-sketch wire bucket cap inside a digest (in-memory sketches may hold
#: Settings.SKETCH_MAX_BINS; the wire form re-collapses to this).
DIGEST_SKETCH_BINS = 48


@dataclass
class HealthDigest:
    """One node's self-reported vitals at a point in time.

    All counters are cumulative process-lifetime values (the observatory
    differentiates); gauges are instantaneous. Unknown/unavailable values
    stay at their defaults — consumers must treat 0/-1/"" as "not reported".
    """

    node: str
    ts: float = 0.0  # sender wall clock (time.time())
    version: int = DIGEST_VERSION
    # Round machine.
    round: int = -1  # -1: no experiment in progress
    total_rounds: int = -1
    stage: str = ""
    # Scheduler ("sync" | "async"; "" when idle or from an older peer). In
    # async mode ``round`` counts WINDOWS and ``staleness`` is the mean
    # window lag folded in the node's last aggregation — the fleet sees who
    # is consuming fresh contributions and who is surviving on stale ones.
    mode: str = ""
    staleness: float = 0.0
    # Learner.
    steps_per_s: float = 0.0
    jit_compile_s: float = 0.0
    # Wire.
    tx_bytes: float = 0.0
    rx_bytes: float = 0.0
    queue_depth: float = 0.0
    # Model-plane TX bytes split by wire codec (topk / topk-int8 / topk-int4
    # / dense — comm/delta.py CODEC_LABELS): the attribution that tells the
    # fleet which encoder is actually carrying the model plane. Empty for
    # pre-codec-label (older) peers — always tolerated.
    tx_by_codec: Dict[str, float] = field(default_factory=dict)
    # Aggregation.
    agg_waits: int = 0  # completed aggregation waits (histogram count)
    agg_wait_s: float = 0.0  # cumulative seconds spent waiting
    contributors: float = 0.0  # contributors merged in the last aggregation
    # Defense / fault planes.
    rejections: Dict[str, float] = field(default_factory=dict)  # reason -> n
    rejected_by_source: Dict[str, float] = field(default_factory=dict)
    faults_seen: float = 0.0  # chaos faults injected at this node's sends
    # Privacy plane: cumulative (epsilon, PRIVACY_DELTA)-DP spend of this
    # node's training. None = the node never reported a budget (DP off /
    # pre-privacy peer — always tolerated, omitted on the wire); 0 = DP
    # active, nothing released yet (a genuine zero-spend claim); -1 = no
    # valid DP claim (noise off / non-private steps — JSON cannot carry
    # inf). None and 0 are distinct on purpose: absent telemetry must not
    # render as an active zero-spend guarantee.
    dp_epsilon: Optional[float] = None
    # Engine supervisor (fused engines): cumulative restarts and degrade-
    # ladder steps this node's supervisor performed. None = never
    # supervised (wire nodes, pre-supervisor peers — omitted on the wire,
    # always tolerated), distinct from a genuine 0 like dp_epsilon above.
    restarts: Optional[int] = None
    degrade: Optional[int] = None
    # Device.
    mem_bytes: float = 0.0
    # Distribution sketches (v2+): name -> QuantileSketch wire dict, plus
    # the HyperLogLog distinct-contributor estimator under "__distinct__".
    # Stored in WIRE form — decoding is lazy (the observatory decodes only
    # when it merges fleet quantiles), and absent/{} means a v1 peer.
    sketches: Dict[str, Any] = field(default_factory=dict)

    # --- sketch accessors ----------------------------------------------------

    def sketch(self, name: str):
        """Decode one carried quantile sketch (None when absent/invalid)."""
        from p2pfl_tpu.telemetry.sketches import QuantileSketch

        return QuantileSketch.from_wire(self.sketches.get(name))

    def distinct(self):
        """Decode the distinct-contributor estimator (None when absent)."""
        from p2pfl_tpu.telemetry.sketches import DistinctEstimator

        return DistinctEstimator.from_wire(self.sketches.get("__distinct__"))

    # --- wire codec ---------------------------------------------------------

    def encode(self) -> str:
        """Compact JSON, stable key order (diffable in flight-recorder
        dumps and deterministic for tests). An empty sketch table is
        omitted entirely — a v1-shaped digest encodes byte-identically to
        the v1 wire (modulo the version stamp)."""
        d = asdict(self)
        d["v"] = d.pop("version")
        sk = d.pop("sketches", None)
        if sk:
            d["sk"] = sk
        if not d.get("tx_by_codec"):
            d.pop("tx_by_codec", None)  # keep pre-codec-label beats byte-identical
        if d.get("dp_epsilon") is None:
            d.pop("dp_epsilon", None)  # no budget reported: omit, don't claim 0
        for opt in ("restarts", "degrade"):
            if d.get(opt) is None:
                d.pop(opt, None)  # unsupervised node: omit, keep old wire shape
        return json.dumps(d, separators=(",", ":"), sort_keys=True)


def decode(payload: str) -> Optional["HealthDigest"]:
    """Best-effort decode: ``None`` for malformed/oversized payloads; for a
    NEWER version, every recognized field is kept and the rest ignored, so
    version skew degrades to a sparser digest instead of a dead peer entry."""
    if not payload or len(payload) > MAX_DIGEST_BYTES:
        return None
    try:
        raw = json.loads(payload)
    except (ValueError, TypeError):
        return None
    if not isinstance(raw, dict) or not isinstance(raw.get("node"), str):
        return None
    dig = HealthDigest(node=raw["node"])
    try:
        dig.version = int(raw.get("v", raw.get("version", DIGEST_VERSION)))
    except (TypeError, ValueError):
        dig.version = DIGEST_VERSION
    for name, kind in (
        ("ts", float), ("round", int), ("total_rounds", int), ("stage", str),
        ("mode", str), ("staleness", float),
        ("steps_per_s", float), ("jit_compile_s", float),
        ("tx_bytes", float), ("rx_bytes", float), ("queue_depth", float),
        ("agg_waits", int), ("agg_wait_s", float), ("contributors", float),
        ("faults_seen", float), ("mem_bytes", float), ("dp_epsilon", float),
        ("restarts", int), ("degrade", int),
    ):
        v = raw.get(name)
        if v is None:
            continue
        try:
            setattr(dig, name, kind(v))
        except (TypeError, ValueError):
            pass  # a newer version may have retyped the field — keep default
    for name in ("rejections", "rejected_by_source", "tx_by_codec"):
        v = raw.get(name)
        if isinstance(v, dict):
            table = {}
            for k, n in v.items():
                try:
                    table[str(k)] = float(n)
                except (TypeError, ValueError):
                    continue
            setattr(dig, name, table)
    # v2 sketch table: kept in WIRE form (decoded lazily by consumers, so a
    # malformed sketch degrades to "absent" at merge time, never at ingest).
    # A v1 payload simply has no "sk" — empty table, fully functional digest.
    sk = raw.get("sk")
    if isinstance(sk, dict):
        dig.sketches = {
            str(k): v for k, v in sk.items()
            if isinstance(v, dict) or (k == "__distinct__" and isinstance(v, str))
        }
    return dig


# --- collection -------------------------------------------------------------


def _series_sum(name: str, node: str, group_by: Optional[str] = None) -> Any:
    """Sum a family's series for ``node``; with ``group_by``, a dict keyed by
    that label instead of a scalar."""
    fam = REGISTRY.get(name)
    if fam is None:
        return {} if group_by else 0.0
    if group_by:
        out: Dict[str, float] = {}
        for labels, child in fam.samples():
            if labels.get("node") != node:
                continue
            key = labels.get(group_by, "?")
            out[key] = out.get(key, 0.0) + child.value
        return out
    return sum(c.value for lbl, c in fam.samples() if lbl.get("node") == node)


def _gauge_value(name: str, node: str) -> float:
    fam = REGISTRY.get(name)
    if fam is None:
        return 0.0
    for labels, child in fam.samples():
        if labels.get("node") == node:
            return float(child.value)
    return 0.0


def _gauge_value_opt(name: str, node: str) -> Optional[float]:
    """Like :func:`_gauge_value` but ``None`` when the node has no series —
    'never reported' must stay distinguishable from a genuine 0.0."""
    fam = REGISTRY.get(name)
    if fam is None:
        return None
    for labels, child in fam.samples():
        if labels.get("node") == node:
            return float(child.value)
    return None


def device_mem_bytes() -> float:
    """Accelerator memory in use, best effort: backend memory stats when the
    platform exposes them, else the sum of live jax array buffers (process-
    wide — in-process federations share one device). The live-array sweep is
    O(live arrays), so it is TTL-cached (``Settings.DEVOBS_MEM_TTL_S``)
    behind the profiler's watermark helper instead of paid on every digest
    beat. 0.0 when JAX is absent or the backend reports nothing."""
    try:
        from p2pfl_tpu.management.profiler import device_memory_watermark

        return float(device_memory_watermark().get("bytes_in_use", 0.0))
    except Exception:  # noqa: BLE001 — digest collection must never raise
        return 0.0


def collect(addr: str, state: Any = None) -> HealthDigest:
    """Snapshot ``addr``'s vitals from the process-wide registry (plus the
    node's :class:`~p2pfl_tpu.node_state.NodeState` when provided — round,
    stage, total_rounds are state-only facts).

    Cheap: a handful of locked gauge reads; called once per heartbeat
    period. Never raises — a broken collector must not stop the beat.
    """
    dig = HealthDigest(node=addr, ts=time.time())
    try:
        if state is not None:
            r = getattr(state, "round", None)
            dig.round = -1 if r is None else int(r)
            t = getattr(state, "total_rounds", None)
            dig.total_rounds = -1 if t is None else int(t)
            dig.stage = str(getattr(state, "current_stage", "") or "")
            if getattr(state, "experiment", None) is not None:
                dig.mode = str(getattr(state, "fed_mode", "") or "")
        dig.steps_per_s = _gauge_value("p2pfl_learner_steps_per_second", addr)
        dig.jit_compile_s = _gauge_value("p2pfl_learner_jit_compile_seconds", addr)
        dig.tx_bytes = float(_series_sum("p2pfl_gossip_tx_bytes_total", addr))
        dig.tx_by_codec = _series_sum(
            "p2pfl_gossip_tx_bytes_total", addr, group_by="codec"
        )
        dig.rx_bytes = float(_series_sum("p2pfl_gossip_rx_bytes_total", addr))
        dig.queue_depth = _gauge_value("p2pfl_gossip_queue_depth", addr)
        wait = REGISTRY.get("p2pfl_aggregation_wait_seconds")
        if wait is not None:
            for labels, child in wait.samples():
                if labels.get("node") == addr:
                    dig.agg_waits = int(child.count)
                    dig.agg_wait_s = float(child.sum)
                    break
        dig.contributors = _gauge_value("p2pfl_aggregation_contributors", addr)
        dig.rejections = _series_sum(
            "p2pfl_updates_rejected_total", addr, group_by="reason"
        )
        by_source = _series_sum(
            "p2pfl_updates_rejected_total", addr, group_by="source"
        )
        # "?" is the unattributed bucket (direct API calls) — not a peer.
        by_source.pop("?", None)
        dig.rejected_by_source = by_source
        dig.staleness = _gauge_value("p2pfl_async_staleness", addr)
        dig.faults_seen = float(_series_sum("p2pfl_chaos_faults_total", addr))
        dig.dp_epsilon = _gauge_value_opt("p2pfl_privacy_epsilon", addr)
        # Supervisor vitals: only nodes that ever ran supervised have the
        # series — everyone else keeps None (omitted on the wire).
        for fam_name, attr in (
            ("p2pfl_supervisor_restarts_total", "restarts"),
            ("p2pfl_supervisor_degrade_steps_total", "degrade"),
        ):
            fam = REGISTRY.get(fam_name)
            if fam is not None:
                vals = [
                    c.value for lbl, c in fam.samples()
                    if lbl.get("node") == addr
                ]
                if vals:
                    setattr(dig, attr, int(sum(vals)))
        dig.mem_bytes = device_mem_bytes()
        # v2: the node's distribution sketches (step-time, staleness,
        # update-norm, agg-wait) + distinct-contributor estimator, wire
        # bins bounded so the beat stays cheap regardless of stream length.
        dig.sketches = SKETCHES.wire_for(addr, max_bins=DIGEST_SKETCH_BINS)
    except Exception:  # noqa: BLE001
        log.exception("(%s) health-digest collection failed", addr)
    return dig


__all__ = [
    "DIGEST_SKETCH_BINS",
    "DIGEST_VERSION",
    "HealthDigest",
    "MAX_DIGEST_BYTES",
    "WIRE_ARG_PREFIX",
    "collect",
    "decode",
    "device_mem_bytes",
]
