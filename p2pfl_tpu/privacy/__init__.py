"""Privacy plane: committee-based secure aggregation + DP-SGD budget.

* :mod:`p2pfl_tpu.privacy.masking` — pairwise mask algebra (DH key
  agreement, per-round PRG streams, the exactly-cancelling integer
  lattice).
* :mod:`p2pfl_tpu.privacy.secagg` — the per-node :class:`PrivacyPlane`
  (masked encode/finalize, repairs, journal round-trip).
* :mod:`p2pfl_tpu.privacy.budget` — the per-node RDP privacy-budget ledger
  surfaced through digest / observatory / ``fed_top``.

See ``docs/components/privacy.md`` for the threat model, the mask
protocol, and the budget semantics.
"""

from p2pfl_tpu.privacy.budget import BUDGETS, PrivacyBudgetLedger, wire_epsilon
from p2pfl_tpu.privacy.masking import (
    PairwiseMasker,
    center_ring,
    lattice_qmax,
    ring_dtype,
    round_secret,
    shared_support,
    signed_share,
)
from p2pfl_tpu.privacy.secagg import (
    MASKED_INFO_KEY,
    MASKED_META_KEY,
    PrivacyPlane,
    masked_info,
)

__all__ = [
    "BUDGETS",
    "MASKED_INFO_KEY",
    "MASKED_META_KEY",
    "PairwiseMasker",
    "PrivacyBudgetLedger",
    "PrivacyPlane",
    "center_ring",
    "lattice_qmax",
    "masked_info",
    "ring_dtype",
    "round_secret",
    "shared_support",
    "signed_share",
    "wire_epsilon",
]
