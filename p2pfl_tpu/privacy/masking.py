"""Pairwise mask algebra for committee-based secure aggregation.

The DisAgg insight (arxiv 2605.13708): secure aggregation does not need a
trusted server — an *aggregator committee* whose members exchange pairwise
masks can compute the sum of its updates without any member (or observer)
seeing an individual one. This federation already elects a per-round
committee by voting (the trainset), so the trust structure exists; this
module supplies the mask algebra that rides it.

Three layers, each exactly-cancelling by construction:

* **Key agreement** — each node mints a per-session finite-field
  Diffie-Hellman keypair (RFC 3526 group 14, stdlib ``pow`` — the
  ``cryptography`` package is optional in this image, so X25519 is not
  assumed) and broadcasts the public half on the gossip wire
  (``privacy_key``). A pair's shared secret is the SHA-256 of the DH shared
  value bound to the sorted pair, so both ends derive the same secret and
  no third party can.
* **Per-round mask streams** — the stream KDF is two-stage:
  ``round_secret = SHA256(pair_secret, round)`` scopes the pair secret to
  one round, and the per-tensor stream is a PRG seeded from
  ``SHA256(round_secret, tensor)``. The two-stage split is load-bearing
  for dropout repair: a survivor reveals ONLY the round-scoped secret
  (``privacy_repair``), which reconstructs the dead pair's masks for that
  round and nothing else — a wire observer who captures every reveal of
  round ``r`` learns nothing about any other round's streams, even when a
  crash-restarted masker resumes with the same journaled keypair. The
  lexicographically smaller address ADDS the stream, the larger SUBTRACTS
  it, so the pair's net contribution to any sum that contains both is the
  zero vector of the ring — exactly, in integer arithmetic, not to float
  epsilon.
* **Integer lattice** — masked values live in Z mod 2**PRIVACY_RING_BITS.
  Senders clamp (clipping-at-sender) and quantize their delta values onto
  a shared lattice; masks are uniform ring elements; sums wrap. Pairwise
  cancellation in a modular ring is exact, which is what makes masked
  FedAvg bit-exact with the same pipeline run maskless — the property the
  privacy tests and ``bench.py --privacy`` assert.

Threat model note: the PRG is numpy's PCG64 (fast, deterministic across
platforms), keyed from SHA-256-derived seeds. That defends the
honest-but-curious peer and the wire observer — the threat model of
``docs/components/privacy.md`` — not a cryptanalytic adversary; the seed
derivation is the single swap point for a crypto-grade stream.
"""

from __future__ import annotations

import hashlib
import secrets
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# RFC 3526 MODP group 14 (2048-bit) — stdlib-only DH. The generator is 2.
_MODP_P = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
_MODP_G = 2

#: Hex digits of a group-14 public key (2048 bits).
_PUBKEY_HEX_LEN = 512


def _sha(*parts: bytes) -> bytes:
    h = hashlib.sha256()
    for p in parts:
        h.update(len(p).to_bytes(4, "big"))
        h.update(p)
    return h.digest()


def _seed64(*parts: bytes) -> int:
    """Stable 64-bit PRG seed from hashed parts."""
    return int.from_bytes(_sha(*parts)[:8], "big")


def ring_dtype(bits: int) -> np.dtype:
    """Unsigned IN-MEMORY dtype of the masked lattice. For sub-word rings
    (12-bit) the carrier wraps mod 2**16, which is mod-2**12-consistent
    (4096 divides 65536): sums and pairwise cancellations reduce correctly
    at decode time via ``% ring``. The WIRE form of a 12-bit lattice is the
    packed two-values-per-three-bytes layout (:func:`pack_ring`)."""
    if bits <= 16:
        return np.dtype(np.uint16)
    return np.dtype(np.uint32)


def pack_ring(vals: np.ndarray, bits: int) -> np.ndarray:
    """Wire-pack lattice values. 12-bit rings pack two values into three
    bytes (values are reduced ``% ring`` first — in-memory carriers may
    hold unreduced mod-2**16 sums); wider rings ship their native bytes."""
    if bits != 12:
        return np.ascontiguousarray(vals, ring_dtype(bits)).view(np.uint8)
    v = (np.asarray(vals, np.uint32) % (1 << 12)).astype(np.uint16)
    if v.size % 2:
        v = np.concatenate([v, np.zeros(1, np.uint16)])
    a, b = v[0::2].astype(np.uint32), v[1::2].astype(np.uint32)
    out = np.empty(3 * a.size, np.uint8)
    out[0::3] = a & 0xFF
    out[1::3] = (a >> 8) | ((b & 0xF) << 4)
    out[2::3] = b >> 4
    return out


def unpack_ring(buf: np.ndarray, k: int, bits: int) -> np.ndarray:
    """Invert :func:`pack_ring` into ``k`` lattice values. Raises
    ``ValueError`` on a plane whose length disagrees with ``k`` — a hostile
    frame dies here before any value is summed."""
    buf = np.asarray(buf, np.uint8)
    dt = ring_dtype(bits)
    if bits != 12:
        if buf.size != k * dt.itemsize:
            raise ValueError("masked plane length disagrees with k")
        return buf.view(dt).copy()
    pairs = (k + 1) // 2
    if buf.size != 3 * pairs:
        raise ValueError("masked plane length disagrees with k")
    b0 = buf[0::3].astype(np.uint16)
    b1 = buf[1::3].astype(np.uint16)
    b2 = buf[2::3].astype(np.uint16)
    a = b0 | ((b1 & 0xF) << 8)
    b = (b1 >> 4) | (b2 << 4)
    out = np.empty(2 * pairs, np.uint16)
    out[0::2] = a
    out[1::2] = b
    return out[:k].copy()


#: Protocol constant (NOT a knob — both ends must derive the same lattice):
#: the honest committee sum is kept this factor inside the signed half of
#: the ring, so a mask share that failed to cancel — uniform over the ring —
#: lands OUTSIDE the honest bound with probability ~(1 - 1/HEADROOM) per
#: coordinate, and the committee-side range check (a max over the whole
#: support, so the per-frame miss probability is ~HEADROOM**-k) actually
#: bites. Without headroom the honest bound would span the whole ring and a
#: wrapped sum would be indistinguishable from a large honest one.
LATTICE_HEADROOM = 2


def lattice_qmax(bits: int, committee_size: int) -> int:
    """Largest per-sender lattice magnitude that keeps the committee sum
    decodable AND range-checkable: ``n * qmax * LATTICE_HEADROOM`` stays
    inside the signed half of the ring."""
    if committee_size < 1:
        raise ValueError("committee must be non-empty")
    qmax = ((1 << (bits - 1)) - 1) // (committee_size * LATTICE_HEADROOM)
    if qmax < 1:
        raise ValueError(
            f"ring of {bits} bits cannot carry a committee of "
            f"{committee_size} (qmax < 1) — raise PRIVACY_RING_BITS"
        )
    return qmax


def center_ring(acc: np.ndarray, bits: int) -> np.ndarray:
    """Reinterpret an unsigned mod-2**bits accumulator as the signed sum it
    encodes (valid while the true sum's magnitude < 2**(bits-1)). Reduces
    ``% ring`` first: sub-word rings ride wider unsigned carriers whose
    wrap (mod 2**16) is ring-consistent but leaves values unreduced."""
    ring = 1 << bits
    half = 1 << (bits - 1)
    a = acc.astype(np.int64) % ring
    return np.where(a >= half, a - ring, a)


def shared_support(
    round: int, tensor_idx: int, size: int, ratio: float
) -> np.ndarray:
    """Shared pseudorandom rand-k support for one tensor of one masked
    round — a pure function of PUBLIC state (round, tensor geometry,
    ratio), so every committee member derives the same indices and the
    wire ships none. Sorted int64 positions."""
    k = max(1, min(size, int(round_half_up(size * ratio))))
    seed = _seed64(
        b"p2pfl-privacy-support",
        int(round).to_bytes(8, "big", signed=True),
        int(tensor_idx).to_bytes(4, "big"),
        int(size).to_bytes(8, "big"),
        repr(float(ratio)).encode(),
    )
    rng = np.random.Generator(np.random.PCG64(seed))
    idx = rng.choice(size, size=k, replace=False)
    idx.sort()
    return idx.astype(np.int64)


def round_half_up(x: float) -> int:
    return int(np.floor(x + 0.5))


def round_secret(pair_secret: bytes, round: int) -> bytes:
    """Round-scoped derivation of a pair secret — the ONLY value the repair
    path ever puts on the wire. One-way: holding ``round_secret(s, r)``
    yields round ``r``'s mask streams and no other round's (the pair secret
    itself never leaves the two endpoints' memory/journal)."""
    return _sha(
        b"p2pfl-privacy-round",
        pair_secret,
        int(round).to_bytes(8, "big", signed=True),
    )


class PairwiseMasker:
    """One node's key material + mask generator.

    Owns the per-session DH keypair, learns peers' public keys from the
    ``privacy_key`` gossip, caches pair secrets, and renders per-round mask
    streams. Export/import round-trips through the PR 10 NodeJournal so a
    crashed masker resumes with the same seeds (its re-sent masked frame
    cancels exactly like the lost one would have).
    """

    def __init__(self, addr: str, _private: Optional[int] = None) -> None:
        self.addr = addr
        self._private = (
            _private if _private is not None else secrets.randbits(256)
        )
        self._public = pow(_MODP_G, self._private, _MODP_P)
        self._peer_keys: Dict[str, int] = {}
        self._pair_secrets: Dict[str, bytes] = {}

    # --- key agreement -------------------------------------------------------

    def public_key_hex(self) -> str:
        return format(self._public, f"0{_PUBKEY_HEX_LEN}x")

    def learn_key(self, peer: str, pubkey_hex: str) -> bool:
        """Store ``peer``'s public key; returns True when it was new.
        Malformed keys are dropped (False) — an unparseable key must not
        wedge the handshake."""
        if peer == self.addr:
            return False
        try:
            pub = int(pubkey_hex, 16)
        except (TypeError, ValueError):
            return False
        if not 1 < pub < _MODP_P - 1:
            return False
        if self._peer_keys.get(peer) == pub:
            return False
        self._peer_keys[peer] = pub
        self._pair_secrets.pop(peer, None)
        return True

    def knows(self, peer: str) -> bool:
        return peer == self.addr or peer in self._peer_keys

    def known_peers(self) -> List[str]:
        return sorted(self._peer_keys)

    def pair_secret(self, peer: str) -> bytes:
        """Shared secret with ``peer`` (requires its public key)."""
        sec = self._pair_secrets.get(peer)
        if sec is not None:
            return sec
        pub = self._peer_keys.get(peer)
        if pub is None:
            raise KeyError(f"no public key for {peer}")
        shared = pow(pub, self._private, _MODP_P)
        a, b = sorted((self.addr, peer))
        sec = _sha(
            b"p2pfl-privacy-pair",
            shared.to_bytes((shared.bit_length() + 7) // 8 or 1, "big"),
            a.encode(),
            b.encode(),
        )
        self._pair_secrets[peer] = sec
        return sec

    # --- mask streams --------------------------------------------------------

    @staticmethod
    def stream(
        round_sec: bytes, tensor_idx: int, k: int, bits: int
    ) -> np.ndarray:
        """The pair's uniform ring-element stream for one tensor of the
        round baked into ``round_sec`` (:func:`round_secret`): both ends
        render the identical array from the shared secret."""
        seed = _seed64(
            b"p2pfl-privacy-mask",
            round_sec,
            int(tensor_idx).to_bytes(4, "big"),
        )
        rng = np.random.Generator(np.random.PCG64(seed))
        return rng.integers(0, 1 << bits, size=int(k), dtype=np.uint64).astype(
            ring_dtype(bits)
        )

    def pair_round_secret(self, peer: str, round: int) -> bytes:
        """Round-scoped pair secret with ``peer`` — the revealable form."""
        return round_secret(self.pair_secret(peer), round)

    def pair_share(
        self,
        peer: str,
        round: int,
        tensor_idx: int,
        k: int,
        bits: int,
        *,
        owner: Optional[str] = None,
    ) -> np.ndarray:
        """SIGNED mask share the pair member ``owner`` (default: self) adds
        for the pair (owner, peer): ``+stream`` when owner sorts first,
        ``-stream`` (mod ring) otherwise — so owner's and peer's shares sum
        to zero in the ring."""
        owner = owner or self.addr
        return signed_share(
            self.pair_round_secret(peer, round), owner, peer, tensor_idx, k, bits
        )

    def total_mask(
        self,
        committee: Sequence[str],
        round: int,
        tensor_idx: int,
        k: int,
        bits: int,
    ) -> np.ndarray:
        """Sum of this node's signed shares against every OTHER committee
        member — the vector added to its lattice values on the wire."""
        dt = ring_dtype(bits)
        acc = np.zeros(int(k), dt)
        for peer in committee:
            if peer == self.addr:
                continue
            acc = acc + self.pair_share(peer, round, tensor_idx, k, bits)
        return acc.astype(dt)

    # --- recovery journal round-trip (PR 10 NodeJournal) ---------------------

    def export_state(self) -> Dict[str, str]:
        """Journalable key material: the session private key plus every
        learned peer key. Plaintext on disk — the same trust the journal
        already extends to model params; the threat model doc states it."""
        return {
            "private": format(self._private, "x"),
            "peers": {p: format(k, "x") for p, k in self._peer_keys.items()},
        }

    @classmethod
    def import_state(cls, addr: str, st: Dict) -> "PairwiseMasker":
        m = cls(addr, _private=int(st["private"], 16))
        for p, k in (st.get("peers") or {}).items():
            try:
                m._peer_keys[str(p)] = int(k, 16)
            except (TypeError, ValueError):
                continue
        return m


def signed_share(
    round_sec: bytes,
    owner: str,
    peer: str,
    tensor_idx: int,
    k: int,
    bits: int,
) -> np.ndarray:
    """Render the signed mask share ``owner`` contributes for the pair
    (owner, peer) from the ROUND-SCOPED secret (:func:`round_secret`) — the
    repair path: a survivor reveals its round-scoped secret with a dead
    masker (``privacy_repair``) and any aggregator reconstructs the share
    to subtract, without the dead peer and without learning any other
    round's streams."""
    stream = PairwiseMasker.stream(round_sec, tensor_idx, k, bits)
    if owner < peer:
        return stream
    dt = ring_dtype(bits)
    return (np.zeros_like(stream) - stream).astype(dt)


__all__ = [
    "LATTICE_HEADROOM",
    "PairwiseMasker",
    "center_ring",
    "lattice_qmax",
    "pack_ring",
    "ring_dtype",
    "round_secret",
    "shared_support",
    "signed_share",
    "unpack_ring",
]
