"""Per-node privacy-budget ledger.

Wires the RDP accountant (:mod:`p2pfl_tpu.learning.privacy` — conservative
Gaussian-mechanism composition, no subsampling-amplification claim) into a
process-wide per-node ledger the rest of the federation can see:

* the learner reports every fit's DP-SGD step count (and any NON-private
  steps, which void the guarantee — epsilon must read ``inf``, never 0);
* the ledger exposes the cumulative ``(epsilon, delta)`` spend through the
  ``p2pfl_privacy_epsilon`` gauge, the health digest (``dp_epsilon`` field,
  absent-tolerated like every digest field), the observatory snapshot, and
  ``fed_top``'s EPS column — a node's remaining budget is a fleet-visible
  operational fact, not a local print statement.

Epsilon conventions: ``-1`` in wire/serialized forms means "no DP claim"
(infinite epsilon or no DP steps at all) because JSON cannot carry ``inf``;
in-process the ledger reports the honest float (``math.inf`` included).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, Optional

from p2pfl_tpu.config import Settings
from p2pfl_tpu.learning.privacy import dp_sgd_privacy_spent
from p2pfl_tpu.telemetry import REGISTRY

_EPSILON = REGISTRY.gauge(
    "p2pfl_privacy_epsilon",
    "Cumulative (epsilon, PRIVACY_DELTA)-DP spend of this node's training "
    "(conservative Gaussian RDP composition; -1 = no valid DP claim — "
    "noise off or non-private steps taken)",
    labels=("node",),
)
_DP_STEPS = REGISTRY.counter(
    "p2pfl_privacy_dp_steps_total",
    "Training steps taken under the DP-SGD mechanism",
    labels=("node",),
)


class PrivacyBudgetLedger:
    """Process-wide {node -> cumulative DP accounting}. Thread-safe; one
    instance (:data:`BUDGETS`) serves every in-process node."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._acct: Dict[str, Dict[str, Any]] = {}

    def record(
        self,
        node: str,
        *,
        clip_norm: float,
        noise_multiplier: float,
        dp_steps: int = 0,
        nonprivate_steps: int = 0,
    ) -> None:
        """Fold one fit's step counts into ``node``'s ledger entry and
        refresh the gauge. Mixing sigma/clip across fits keeps the WEAKEST
        configuration (smallest sigma, largest clip) — the conservative
        direction for a composed bound."""
        with self._lock:
            a = self._acct.setdefault(
                node,
                {
                    "clip_norm": 0.0,
                    "noise_multiplier": math.inf,
                    "dp_steps": 0,
                    "nonprivate_steps": 0,
                },
            )
            if dp_steps > 0:
                a["clip_norm"] = max(a["clip_norm"], float(clip_norm))
                a["noise_multiplier"] = min(
                    a["noise_multiplier"], float(noise_multiplier)
                )
                a["dp_steps"] += int(dp_steps)
            a["nonprivate_steps"] += int(nonprivate_steps)
            spent = self._spent_locked(node)
        if dp_steps > 0:
            _DP_STEPS.labels(node).inc(dp_steps)
        _EPSILON.labels(node).set(wire_epsilon(spent["epsilon"]))

    def _spent_locked(self, node: str) -> Dict[str, Any]:
        a = self._acct.get(node)
        if a is None or (a["dp_steps"] == 0 and a["nonprivate_steps"] == 0):
            return dp_sgd_privacy_spent(0.0, 0.0, 0, Settings.PRIVACY_DELTA)
        sigma = a["noise_multiplier"]
        return dp_sgd_privacy_spent(
            0.0 if math.isinf(sigma) else sigma,
            a["clip_norm"],
            a["dp_steps"],
            Settings.PRIVACY_DELTA,
            nonprivate_steps=a["nonprivate_steps"],
        )

    def spent(self, node: str) -> Dict[str, Any]:
        """Cumulative accountant summary for ``node`` (epsilon may be 0 —
        nothing released — or ``inf`` — guarantee voided)."""
        with self._lock:
            return self._spent_locked(node)

    def epsilon(self, node: str) -> float:
        return float(self.spent(node)["epsilon"])

    def reset(self, node: Optional[str] = None) -> None:
        with self._lock:
            if node is None:
                self._acct.clear()
            else:
                self._acct.pop(node, None)


def wire_epsilon(eps: float) -> float:
    """JSON/metric-safe epsilon: ``-1`` encodes "no valid DP claim"
    (``inf``) and "no DP steps" (0 with no mechanism) both round-trip."""
    if eps is None or math.isinf(eps) or math.isnan(eps):
        return -1.0
    return float(eps)


#: The process-wide privacy-budget ledger.
BUDGETS = PrivacyBudgetLedger()

__all__ = ["BUDGETS", "PrivacyBudgetLedger", "wire_epsilon"]
