"""Committee-based secure aggregation on the gossip wire.

One masked round, end to end (sync scheduler; the async scheduler runs the
DP half of the plane only — see ``docs/components/privacy.md``):

1. **Bootstrap** — every node broadcasts its session public key
   (``privacy_key``); :class:`~p2pfl_tpu.privacy.masking.PairwiseMasker`
   derives pair secrets on demand.
2. **Encode** (:meth:`PrivacyPlane.mask_own`) — the trainer computes its
   round delta against the shared round anchor, adds the error-feedback
   residual, samples it on the round's SHARED rand-k support (public seed →
   zero index bytes on the wire), clamps each value to
   ``±PRIVACY_VALUE_RANGE`` (clipping-at-sender), quantizes onto the
   integer lattice, and adds its pairwise mask total. The EF residual
   absorbs clamp + lattice error element-exactly, like the PR 12 quant
   codec's residual does.
3. **Gossip** — masked frames ride the normal partial-model gossip
   (codec label ``masked``); lattice vectors ADD mod the ring, so partial
   aggregation, contributor dedup, coverage tracking and overlap drains all
   work unchanged (:class:`~p2pfl_tpu.learning.aggregators.masked.
   MaskedFedAvg`).
4. **Screen** — the committee cannot norm-screen a masked frame (its values
   are uniform ring elements by design — the admission-vs-secrecy tension);
   :meth:`p2pfl_tpu.comm.admission.AdmissionController.screen_masked`
   validates everything that IS checkable (ring dtype, per-tensor support
   sizes, declared round/committee) and the committee-side range check at
   finalize catches what is not.
5. **Finalize** (:meth:`PrivacyPlane.finalize`) — with every committee
   member present the pairwise masks have already cancelled in the merged
   sum; for each missing masker the survivors' revealed ROUND-SCOPED pair
   secrets (``privacy_repair`` — ``H(pair_secret, round)``, never the pair
   secret itself, so a captured reveal opens one round's streams and no
   other's even across a journaled crash-restart) reconstruct the
   uncancelled shares to subtract. The
   centered lattice sum is range-checked (``n * qmax`` — only a ring wrap,
   i.e. a hostile or unrepaired mask share, can exceed it), dequantized,
   averaged with UNIT weights (the DisAgg committee mean; the
   unauthenticated ``num_samples`` claim cannot weight what it cannot
   inspect), and scattered onto the anchor.

Masked FedAvg is bit-exact with the identical pipeline run maskless: the
masks cancel in modular integer arithmetic, not to float epsilon — the
property ``tests/test_privacy.py`` and ``bench.py --privacy`` assert.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2pfl_tpu.config import Settings
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops.serialization import deserialize_arrays, serialize_arrays
from p2pfl_tpu.privacy.masking import (
    PairwiseMasker,
    center_ring,
    lattice_qmax,
    pack_ring,
    ring_dtype,
    round_secret,
    shared_support,
    signed_share,
    unpack_ring,
)
from p2pfl_tpu.telemetry import REGISTRY

log = logging.getLogger("p2pfl_tpu")

#: Frame-metadata key marking a masked lattice frame. The payload's arrays
#: are per-float-tensor lattice vectors over the round's shared support;
#: non-float leaves ship nothing (finalize carries the anchor's value).
MASKED_META_KEY = "__masked__"

#: additional_info key carried on in-process masked handles.
MASKED_INFO_KEY = "__masked__"

_MASKED_FRAMES = REGISTRY.counter(
    "p2pfl_privacy_masked_frames_total",
    "Masked lattice frames encoded for the wire",
    labels=("node",),
)
_MASKED_ROUNDS = REGISTRY.counter(
    "p2pfl_privacy_masked_rounds_total",
    "Masked-round finalizations by outcome (ok / unrepaired / range / "
    "structure)",
    labels=("node", "outcome"),
)
_REPAIRS = REGISTRY.counter(
    "p2pfl_privacy_repairs_total",
    "Mask-repair shares by role (tx = revealed own round-scoped pair "
    "secret for a dead masker, rx = stored a survivor's reveal, applied = "
    "subtracted at finalize)",
    labels=("node", "role"),
)


def masked_info(handle: ModelHandle) -> Optional[Dict[str, Any]]:
    """The masked-lattice descriptor of an in-process handle, or ``None``
    for a plaintext model handle."""
    info = handle.additional_info.get(MASKED_INFO_KEY)
    return info if isinstance(info, dict) else None


class PrivacyPlane:
    """Per-node secure-aggregation state (held on
    :class:`~p2pfl_tpu.node_state.NodeState` like the delta codec and the
    admission controller). Thread-safe: encode runs on the stage thread,
    repairs and key learning on transport threads."""

    def __init__(self, addr: str) -> None:
        self.addr = addr
        self._lock = threading.RLock()
        self.masker = PairwiseMasker(addr)
        # Error-feedback residual, float32 flat per tensor (None until the
        # first masked encode; dropped when the model structure changes).
        self._residual: Optional[List[np.ndarray]] = None
        # (round, survivor, dead) -> ROUND-SCOPED secret revealed for
        # repair. First write wins: a later frame claiming the same pair
        # must not displace a stored reveal (a hostile overwrite would make
        # finalize subtract garbage and trip the range check).
        self._repairs: Dict[Tuple[int, str, str], bytes] = {}
        # rounds whose repairs we already broadcast per dead peer (dedup).
        self._repairs_sent: set = set()
        # round -> committee the masks were generated against (registered
        # by mask_own/finalize; validates repair claims). Bounded.
        self._committees: Dict[int, frozenset] = {}

    # --- key agreement (privacy_key command) ---------------------------------

    def key_payload(self) -> str:
        return self.masker.public_key_hex()

    def learn_key(self, peer: str, pubkey_hex: str) -> bool:
        with self._lock:
            return self.masker.learn_key(peer, pubkey_hex)

    def knows_keys(self, peers: Sequence[str]) -> bool:
        with self._lock:
            return all(self.masker.knows(p) for p in peers)

    def missing_keys(self, peers: Sequence[str]) -> List[str]:
        with self._lock:
            return [p for p in peers if not self.masker.knows(p)]

    # --- geometry ------------------------------------------------------------

    @staticmethod
    def lattice_params(committee_size: int) -> Tuple[int, int, float]:
        """(ring bits, qmax, scale) of a masked round for ``committee_size``
        members — a pure function of public configuration, so every member
        derives the same lattice."""
        bits = Settings.PRIVACY_RING_BITS
        if committee_size > Settings.PRIVACY_MAX_COMMITTEE:
            raise ValueError(
                f"masked committee of {committee_size} exceeds "
                f"PRIVACY_MAX_COMMITTEE={Settings.PRIVACY_MAX_COMMITTEE}"
            )
        qmax = lattice_qmax(bits, committee_size)
        scale = Settings.PRIVACY_VALUE_RANGE / qmax
        return bits, qmax, scale

    @staticmethod
    def supports(round: int, shapes: Sequence[tuple], dtypes: Sequence) -> List[Optional[np.ndarray]]:
        """Shared rand-k support per tensor (``None`` for non-float leaves,
        which masked frames do not carry)."""
        out: List[Optional[np.ndarray]] = []
        for i, (shape, dt) in enumerate(zip(shapes, dtypes)):
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if not np.issubdtype(np.dtype(dt), np.floating) or size == 0:
                out.append(None)
                continue
            out.append(
                shared_support(round, i, size, Settings.PRIVACY_MASK_RATIO)
            )
        return out

    # --- encode --------------------------------------------------------------

    def mask_own(
        self,
        model: ModelHandle,
        anchor_leaves: Sequence[np.ndarray],
        round: int,
        committee: Sequence[str],
        *,
        mask: bool = True,
    ) -> ModelHandle:
        """Masked lattice handle of this node's round contribution.

        ``mask=False`` runs the IDENTICAL lattice pipeline with a zero mask
        — the bit-exactness comparator (and the fallback when a committee
        member's key is missing would poison the sum anyway; callers decide).
        Raises ``ValueError`` when a committee pubkey is missing with
        ``mask=True``.
        """
        committee = sorted(set(committee))
        self.note_committee(round, committee)
        bits, qmax, scale = self.lattice_params(len(committee))
        dt = ring_dtype(bits)
        leaves = model.get_parameters()
        anchors = [
            np.ascontiguousarray(a, np.float32).reshape(-1) for a in anchor_leaves
        ]
        if len(leaves) != len(anchors):
            raise ValueError("model/anchor structure mismatch")
        with self._lock:
            if mask:
                missing = self.missing_keys([p for p in committee if p != self.addr])
                if missing:
                    raise ValueError(f"missing committee pubkeys: {missing}")
            if self._residual is not None and len(self._residual) != len(leaves):
                self._residual = None
            if self._residual is None:
                self._residual = [
                    np.zeros((a.size,), np.float32) for a in anchors
                ]
            shapes = [tuple(np.asarray(l).shape) for l in leaves]
            dtypes = [np.asarray(l).dtype for l in leaves]
            supports = self.supports(round, shapes, dtypes)
            lattices: List[np.ndarray] = []
            ks: List[int] = []
            for i, (leaf, anchor) in enumerate(zip(leaves, anchors)):
                idx = supports[i]
                if idx is None:
                    ks.append(0)
                    continue
                flat = np.ascontiguousarray(leaf, np.float32).reshape(-1)
                if self._residual[i].size != flat.size:
                    self._residual[i] = np.zeros((flat.size,), np.float32)
                acc = (flat - anchor) + self._residual[i]
                if not np.isfinite(acc).all():
                    # A diverged tensor must not launder NaNs through the
                    # lattice: transmit zero, keep the finite residual parts.
                    acc = np.where(np.isfinite(acc), acc, 0.0).astype(np.float32)
                v = acc[idx]
                q = np.clip(
                    np.rint(np.clip(v, -Settings.PRIVACY_VALUE_RANGE,
                                    Settings.PRIVACY_VALUE_RANGE) / scale),
                    -qmax, qmax,
                ).astype(np.int64)
                # Element-exact error feedback: residual[idx] becomes
                # acc[idx] - q*scale, everything else keeps the full delta.
                resid = acc.copy()
                resid[idx] = (v - q.astype(np.float32) * np.float32(scale)).astype(
                    np.float32
                )
                self._residual[i] = resid
                lat = (q % (1 << bits)).astype(dt)
                if mask:
                    lat = (
                        lat
                        + self.masker.total_mask(committee, round, i, idx.size, bits)
                    ).astype(dt)
                lattices.append(lat)
                ks.append(int(idx.size))
            _MASKED_FRAMES.labels(self.addr).inc()
            return ModelHandle(
                params=lattices,
                contributors=[self.addr],
                num_samples=model.get_num_samples(),
                additional_info={
                    MASKED_INFO_KEY: {
                        "round": int(round),
                        "bits": int(bits),
                        "n": len(committee),
                        "ks": ks,
                    }
                },
            )

    # --- wire codec ----------------------------------------------------------

    @staticmethod
    def encode_frame(handle: ModelHandle, wire_ctx: str = "") -> bytes:
        """Serialize a masked lattice handle for the gossip wire: one
        bit-packed value plane per masked tensor (12-bit rings pack
        two-per-three-bytes — 1.5 B/value; the shared support ships no
        index bytes at all), lattice descriptor + federation metadata in
        the frame header."""
        info = masked_info(handle)
        if info is None:
            raise ValueError("not a masked handle")
        bits = int(info["bits"])
        planes = [pack_ring(a, bits) for a in handle.get_parameters()]
        meta: Dict[str, Any] = {
            "contributors": list(handle.contributors),
            "num_samples": int(handle.get_num_samples()),
            MASKED_META_KEY: dict(info),
        }
        if wire_ctx:
            from p2pfl_tpu.telemetry import tracing

            meta[tracing.TRACE_META_KEY] = wire_ctx
        return serialize_arrays(planes, meta)

    @staticmethod
    def parse_frame(
        arrays: Sequence[np.ndarray], meta: Dict[str, Any]
    ) -> List[np.ndarray]:
        """Unpack a masked frame's value planes into in-memory lattice
        vectors. Raises ``ValueError`` on any geometry a hostile frame
        controls (unknown ring, plane/k disagreement, tensor count) —
        callers surface that as a counted ``corrupt`` rejection BEFORE any
        value can enter a lattice sum."""
        info = meta.get(MASKED_META_KEY)
        if not isinstance(info, dict):
            raise ValueError("not a masked frame")
        bits = int(info.get("bits", 0))
        if bits not in (12, 16, 32):
            raise ValueError(f"unknown masked ring width {bits}")
        ks = [int(k) for k in (info.get("ks") or []) if int(k) > 0]
        if len(arrays) != len(ks):
            raise ValueError("masked frame tensor count disagrees with ks")
        return [unpack_ring(np.asarray(a), k, bits) for a, k in zip(arrays, ks)]

    @staticmethod
    def is_masked_frame(meta: Dict[str, Any]) -> bool:
        return isinstance(meta.get(MASKED_META_KEY), dict)

    @staticmethod
    def handle_from_frame(
        arrays: Sequence[np.ndarray],
        meta: Dict[str, Any],
        contributors: List[str],
        num_samples: int,
    ) -> ModelHandle:
        """In-process masked handle from an admission-screened wire frame."""
        return ModelHandle(
            params=[np.asarray(a) for a in arrays],
            contributors=contributors,
            num_samples=num_samples,
            additional_info={MASKED_INFO_KEY: dict(meta[MASKED_META_KEY])},
        )

    # --- repairs (masker dropout) --------------------------------------------

    def note_committee(self, round: int, committee: Sequence[str]) -> None:
        """Register the committee a masked round's masks were generated
        against (called by :meth:`mask_own` and :meth:`finalize`). Repair
        claims for the round are validated against it; bounded to the last
        few rounds so a long session cannot grow it."""
        with self._lock:
            self._committees[int(round)] = frozenset(committee)
            while len(self._committees) > 8:
                del self._committees[min(self._committees)]

    def repair_secrets_for(self, dead: str, round: int) -> Optional[str]:
        """Hex ROUND-SCOPED secret (``H(pair_secret, round)``) to reveal
        for ``dead`` — never the raw pair secret, which derives every
        round's mask streams and must not hit the wire (None when unknown
        or already revealed for this round)."""
        with self._lock:
            if not self.masker.knows(dead) or dead == self.addr:
                return None
            key = (int(round), dead)
            if key in self._repairs_sent:
                return None
            self._repairs_sent.add(key)
            sec = round_secret(self.masker.pair_secret(dead), round)
        _REPAIRS.labels(self.addr, "tx").inc()
        return sec.hex()

    def note_repair(
        self, round: int, survivor: str, dead: str, secret_hex: str
    ) -> bool:
        """Store a survivor's revealed round-scoped secret (transport
        thread; ``survivor`` is the frame's transport source, so the claim
        is bound to the sender). First write wins per (round, survivor,
        dead), and both parties must be members of the round's registered
        committee — a peer outside it has no pair share in the sum and its
        'reveal' could only corrupt finalize. A round with no registered
        committee rejects every claim: any aggregator that will finalize
        round ``r`` ran :meth:`mask_own` (which registers) at round start,
        before a mid-round death can be detected, so the only frames this
        drops are ones nobody here could validate or use."""
        try:
            sec = bytes.fromhex(secret_hex)
        except (TypeError, ValueError):
            return False
        if len(sec) != 32 or survivor == dead:
            return False
        key = (int(round), survivor, dead)
        with self._lock:
            members = self._committees.get(key[0])
            if members is None or survivor not in members or dead not in members:
                return False
            if key in self._repairs:
                return False
            self._repairs[key] = sec
        _REPAIRS.labels(self.addr, "rx").inc()
        return True

    # --- finalize ------------------------------------------------------------

    def finalize(
        self,
        handle: ModelHandle,
        committee: Sequence[str],
        anchor_leaves: Sequence[np.ndarray],
        anchor_round: Optional[int] = None,
    ) -> Tuple[Optional[List[np.ndarray]], str]:
        """Unmask the merged committee sum into model-shaped parameters.

        ``anchor_round``, when given, must match the aggregate's declared
        round: the lattice deltas were computed against that round's anchor,
        and scattering them onto any other base would silently corrupt the
        mean (counted as ``structure``).

        Returns ``(params, "ok")`` or ``(None, reason)`` with ``reason`` in
        ``{"unrepaired", "range", "structure"}`` — the caller falls back to
        its own plaintext model and the outcome is counted either way.
        """
        info = masked_info(handle)
        if info is None:
            return None, self._outcome("structure")
        committee = sorted(set(committee))
        round = int(info.get("round", -1))
        bits = int(info.get("bits", 0))
        declared_n = int(info.get("n", 0))
        if bits != Settings.PRIVACY_RING_BITS or declared_n != len(committee):
            return None, self._outcome("structure")
        if anchor_round is not None and int(anchor_round) != round:
            log.warning(
                "(%s) masked round %s: anchor is for round %s — refusing to "
                "scatter onto the wrong base", self.addr, round, anchor_round,
            )
            return None, self._outcome("structure")
        self.note_committee(round, committee)
        try:
            _, qmax, scale = self.lattice_params(declared_n)
        except ValueError:
            return None, self._outcome("structure")
        dt = ring_dtype(bits)
        present = sorted(set(handle.contributors) & set(committee))
        missing = sorted(set(committee) - set(present))
        if not present:
            return None, self._outcome("structure")
        anchors = [
            np.ascontiguousarray(a, np.float32) for a in anchor_leaves
        ]
        shapes = [tuple(a.shape) for a in anchors]
        dtypes = [a.dtype for a in anchors]
        supports = self.supports(round, shapes, dtypes)
        lattices = [np.asarray(a) for a in handle.get_parameters()]
        masked_supports = [s for s in supports if s is not None]
        if len(lattices) != len(masked_supports) or any(
            l.dtype != dt or l.shape != (s.size,)
            for l, s in zip(lattices, masked_supports)
        ):
            return None, self._outcome("structure")
        # Subtract the uncancelled shares of every (present, missing) pair:
        # our own round-scoped pair secrets cover pairs involving us,
        # survivors' repair reveals (already round-scoped) cover the rest.
        # Any still-unknown secret aborts — an uncancelled mask share is
        # uniform ring noise, not an aggregate.
        corrections: List[Tuple[bytes, str, str]] = []
        with self._lock:
            for i_addr in present:
                for d_addr in missing:
                    if i_addr == self.addr:
                        sec = (
                            self.masker.pair_round_secret(d_addr, round)
                            if self.masker.knows(d_addr)
                            else None
                        )
                    else:
                        sec = self._repairs.get((round, i_addr, d_addr))
                    if sec is None:
                        log.warning(
                            "(%s) masked round %s: no repair share for pair "
                            "(%s, %s) — falling back to plaintext",
                            self.addr, round, i_addr, d_addr,
                        )
                        return None, self._outcome("unrepaired")
                    corrections.append((sec, i_addr, d_addr))
        out: List[np.ndarray] = []
        li = 0
        n = len(present)
        for i, anchor in enumerate(anchors):
            idx = supports[i]
            if idx is None:
                out.append(anchor.astype(dtypes[i], copy=True))
                continue
            lat = lattices[li].copy()
            for sec, i_addr, d_addr in corrections:
                lat = (
                    lat - signed_share(sec, i_addr, d_addr, i, idx.size, bits)
                ).astype(dt)
            li += 1
            t = center_ring(lat, bits)
            # Committee-side range check: an honest sum of |q| <= qmax over
            # n members is bounded; beyond it a mask share failed to cancel
            # (hostile frame, wrong pair secret) — reject before the values
            # can touch the model or the next round's anchor.
            bound = int(n * qmax * Settings.PRIVACY_RANGE_MULT)
            if t.size and int(np.abs(t).max()) > bound:
                log.warning(
                    "(%s) masked round %s: lattice sum out of range "
                    "(|t|max=%d > %d) — rejecting the masked aggregate",
                    self.addr, round, int(np.abs(t).max()), bound,
                )
                return None, self._outcome("range")
            vbar = (t.astype(np.float64) * float(scale) / n).astype(np.float32)
            flat = anchor.reshape(-1).astype(np.float32, copy=True)
            flat[idx] = flat[idx] + vbar
            out.append(flat.reshape(shapes[i]).astype(dtypes[i]))
        if corrections:
            _REPAIRS.labels(self.addr, "applied").inc(len(corrections))
        from p2pfl_tpu.telemetry.ledger import LEDGERS

        if LEDGERS.enabled():
            LEDGERS.get(self.addr).emit(
                "privacy_masked",
                round=round,
                dedup_key=("privacy_masked", round),
                members=present,
                repaired=missing,
            )
        return out, self._outcome("ok")

    def _outcome(self, outcome: str) -> str:
        _MASKED_ROUNDS.labels(self.addr, outcome).inc()
        return outcome

    # --- recovery journal (PR 10 NodeJournal) --------------------------------

    def export_state(self) -> Dict[str, Any]:
        with self._lock:
            return {"masker": self.masker.export_state()}

    def import_state(self, st: Dict[str, Any]) -> None:
        masker = (st or {}).get("masker")
        if not masker:
            return
        with self._lock:
            try:
                self.masker = PairwiseMasker.import_state(self.addr, masker)
            except (KeyError, TypeError, ValueError):
                log.warning(
                    "(%s) journaled privacy key material unreadable — "
                    "minting a fresh session keypair", self.addr,
                )

    def reset(self) -> None:
        with self._lock:
            self._residual = None
            self._repairs.clear()
            self._repairs_sent.clear()
            self._committees.clear()


__all__ = [
    "MASKED_INFO_KEY",
    "MASKED_META_KEY",
    "PrivacyPlane",
    "masked_info",
]
