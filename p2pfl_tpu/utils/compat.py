"""JAX version compatibility shims.

The kernels and tests target the current ``jax.shard_map`` API (``check_vma``
varying-mesh-axis checking, ``ShapeDtypeStruct(vma=...)``); older jax releases
(< 0.5) only ship ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling and reject the ``vma`` kwarg. These helpers pick the
available spelling at import so every caller — ring attention, sequence/
pipeline parallelism, the attention tests — runs unchanged on both.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

import jax

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")

_SDS_HAS_VMA = "vma" in inspect.signature(jax.ShapeDtypeStruct.__init__).parameters


def shard_map(
    f: Callable,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: bool = True,
) -> Callable:
    """``jax.shard_map`` with the new-API signature on every jax version.

    On older releases this maps ``check_vma`` onto the experimental API's
    ``check_rep`` — same semantics (disable per-output replication/varying
    checking, required for interpreted Pallas paths that can't trace
    varying-axis values through a kernel call).
    """
    if HAS_NATIVE_SHARD_MAP:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )


def pvary(x: Any, axis_name: str) -> Any:
    """Mark ``x`` as varying over ``axis_name`` (new-API ``jax.lax.pcast`` /
    mid-API ``jax.lax.pvary``). On versions without varying-mesh-axis types
    this is the identity — the old ``check_rep`` tracker needs no cast."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, (axis_name,))
    return x


def shape_dtype_struct(shape: Any, dtype: Any, vma: Any = None) -> jax.ShapeDtypeStruct:
    """``jax.ShapeDtypeStruct`` accepting ``vma`` only where jax does."""
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


__all__ = ["HAS_NATIVE_SHARD_MAP", "pvary", "shard_map", "shape_dtype_struct"]
