"""mTLS certificate tooling.

Capability parity with the reference's ``p2pfl/certificates/gen-certs.sh``
(+ openssl.cnf / server_ext.cnf / client_ext.cnf): a self-signed CA that
signs one server and one client certificate, suitable for the gRPC
transport's mutual-TLS mode (``Settings.USE_SSL`` — grpc_protocol.py server
creds require client auth). Implemented in Python over ``cryptography`` so
federations can mint ephemeral certs programmatically (tests, CI,
single-command deployments) instead of shelling out to openssl.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from typing import Dict, Sequence

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID


def _key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _name(common_name: str) -> x509.Name:
    return x509.Name(
        [
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "p2pfl_tpu"),
            x509.NameAttribute(NameOID.COMMON_NAME, common_name),
        ]
    )


def _san(hostnames: Sequence[str]) -> x509.SubjectAlternativeName:
    alts: list[x509.GeneralName] = []
    for h in hostnames:
        try:
            alts.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            alts.append(x509.DNSName(h))
    return x509.SubjectAlternativeName(alts)


def _write_key(path: str, key: rsa.RSAPrivateKey) -> None:
    with open(path, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )


def _write_cert(path: str, cert: x509.Certificate) -> None:
    with open(path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))


def generate_certificates(
    out_dir: str,
    hostnames: Sequence[str] = ("localhost", "127.0.0.1", "::1"),
    days: int = 500,
) -> Dict[str, str]:
    """Mint a CA + CA-signed server and client certs (gen-certs.sh semantics).

    Returns a dict of paths keyed ``ca_crt, server_key, server_crt,
    client_key, client_crt`` — exactly the five ``Settings.SSL_*`` knobs the
    gRPC transport reads.
    """
    os.makedirs(out_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    not_after = now + datetime.timedelta(days=days)

    ca_key = _key()
    ca_name = _name("p2pfl_tpu-ca")
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    def issue(common_name: str) -> tuple[rsa.RSAPrivateKey, x509.Certificate]:
        key = _key()
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(common_name))
            .issuer_name(ca_name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(not_after)
            .add_extension(_san(hostnames), critical=False)
            .add_extension(
                x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                     x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]
                ),
                critical=False,
            )
            .sign(ca_key, hashes.SHA256())
        )
        return key, cert

    server_key, server_cert = issue("p2pfl_tpu-server")
    client_key, client_cert = issue("p2pfl_tpu-client")

    paths = {
        "ca_crt": os.path.join(out_dir, "ca.crt"),
        "server_key": os.path.join(out_dir, "server.key"),
        "server_crt": os.path.join(out_dir, "server.crt"),
        "client_key": os.path.join(out_dir, "client.key"),
        "client_crt": os.path.join(out_dir, "client.crt"),
    }
    _write_cert(paths["ca_crt"], ca_cert)
    _write_key(paths["server_key"], server_key)
    _write_cert(paths["server_crt"], server_cert)
    _write_key(paths["client_key"], client_key)
    _write_cert(paths["client_crt"], client_cert)
    return paths
