"""Experiment-control helpers.

Parity with reference p2pfl/utils/utils.py:24-145: shrink timeouts for tests,
wait for membership convergence, wait for training to finish, and compare
models across nodes.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Sequence

import numpy as np

from p2pfl_tpu.config import Settings

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.node import Node


def set_test_settings() -> None:
    """Shrink every timeout so multi-node tests run fast in one process.

    Mirrors reference utils/utils.py:24-40.
    """
    Settings.GRPC_TIMEOUT = 0.5
    Settings.HEARTBEAT_PERIOD = 0.25
    Settings.HEARTBEAT_TIMEOUT = 1.5
    Settings.WAIT_HEARTBEATS_CONVERGENCE = 0.3
    Settings.GOSSIP_PERIOD = 0.05
    Settings.TTL = 10
    Settings.GOSSIP_MESSAGES_PER_PERIOD = 100
    Settings.GOSSIP_MODELS_PERIOD = 0.1
    Settings.GOSSIP_MODELS_PER_ROUND = 4
    Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = 20
    Settings.GOSSIP_SEND_RETRIES = 2
    Settings.GOSSIP_SEND_BACKOFF = 0.05
    Settings.CHAOS_ENABLED = False  # chaos is opt-in per test/bench scope
    Settings.TRAIN_SET_SIZE = 4
    Settings.VOTE_TIMEOUT = 10.0
    Settings.AGGREGATION_TIMEOUT = 30.0
    # Well above clean-run fit variance (~1-2s fits), well below the timeout.
    Settings.AGGREGATION_STALL_PATIENCE = 8.0
    Settings.RESOURCE_MONITOR_PERIOD = 0.5
    Settings.LOG_LEVEL = "DEBUG"


def wait_convergence(
    nodes: Sequence["Node"],
    n_neis: int,
    *,
    only_direct: bool = False,
    wait: float = 5.0,
) -> None:
    """Block until every node sees ``n_neis`` neighbors (or raise)."""
    deadline = time.time() + wait
    while time.time() < deadline:
        if all(len(n.get_neighbors(only_direct=only_direct)) == n_neis for n in nodes):
            return
        time.sleep(0.05)
    counts = {n.addr: len(n.get_neighbors(only_direct=only_direct)) for n in nodes}
    raise TimeoutError(f"convergence not reached: {counts} (wanted {n_neis})")


def full_connection(node: "Node", others: Sequence["Node"]) -> None:
    """Connect ``node`` to every node in ``others``."""
    for other in others:
        node.connect(other.addr)


def wait_to_finish(nodes: Sequence["Node"], timeout: float = 3600.0) -> None:
    """Block until every node reports learning finished (or raise)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(not n.learning_in_progress() for n in nodes):
            return
        time.sleep(0.1)
    raise TimeoutError("learning did not finish in time")


def check_equal_models(nodes: Sequence["Node"], atol: float = 1e-1) -> None:
    """Assert all nodes hold (approximately) the same parameters.

    Mirrors reference utils/utils.py:119-145 (allclose, atol=1e-1).
    """
    ref_params = None
    for node in nodes:
        params = node.learner.get_model().get_parameters()
        if ref_params is None:
            ref_params = params
            continue
        assert len(params) == len(ref_params), "layer count mismatch"
        for a, b in zip(ref_params, params):
            assert a.shape == b.shape, f"shape mismatch {a.shape} vs {b.shape}"
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol)
