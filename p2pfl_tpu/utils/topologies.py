"""Network topology construction.

Parity with reference p2pfl/utils/topologies.py:30-93 (STAR / FULL / LINE /
RING adjacency + connect), extended with GRID and ERDOS_RENYI which are useful
for larger gossip simulations.
"""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING, List, Sequence

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.node import Node


class TopologyType(enum.Enum):
    STAR = "star"
    FULL = "full"
    LINE = "line"
    RING = "ring"
    GRID = "grid"
    ERDOS_RENYI = "erdos_renyi"


class TopologyFactory:
    """Build adjacency matrices and wire up nodes accordingly."""

    @staticmethod
    def generate_matrix(
        topology: TopologyType, n: int, *, p: float = 0.3, seed: int = 0
    ) -> np.ndarray:
        """Symmetric 0/1 adjacency matrix with empty diagonal."""
        adj = np.zeros((n, n), dtype=np.int8)
        if n <= 1:
            return adj
        if topology == TopologyType.STAR:
            adj[0, 1:] = 1
            adj[1:, 0] = 1
        elif topology == TopologyType.FULL:
            adj[:] = 1
            np.fill_diagonal(adj, 0)
        elif topology == TopologyType.LINE:
            idx = np.arange(n - 1)
            adj[idx, idx + 1] = 1
            adj[idx + 1, idx] = 1
        elif topology == TopologyType.RING:
            idx = np.arange(n)
            nxt = (idx + 1) % n
            adj[idx, nxt] = 1
            adj[nxt, idx] = 1
        elif topology == TopologyType.GRID:
            side = int(np.ceil(np.sqrt(n)))
            for i in range(n):
                r, c = divmod(i, side)
                for rr, cc in ((r + 1, c), (r, c + 1)):
                    j = rr * side + cc
                    if rr < side and cc < side and j < n:
                        adj[i, j] = adj[j, i] = 1
        elif topology == TopologyType.ERDOS_RENYI:
            rng = np.random.default_rng(seed)
            upper = rng.random((n, n)) < p
            adj = np.triu(upper, 1).astype(np.int8)
            adj = adj | adj.T
            # Guarantee connectivity with a ring backbone.
            idx = np.arange(n)
            nxt = (idx + 1) % n
            adj[idx, nxt] = 1
            adj[nxt, idx] = 1
        else:  # pragma: no cover
            raise ValueError(f"unknown topology {topology}")
        return adj

    @staticmethod
    def connect_nodes(matrix: np.ndarray, nodes: Sequence["Node"]) -> None:
        """Connect each pair (i<j) with matrix[i,j]==1 via node.connect."""
        n = len(nodes)
        for i in range(n):
            for j in range(i + 1, n):
                if matrix[i, j]:
                    nodes[i].connect(nodes[j].addr)

    @staticmethod
    def neighbors_of(matrix: np.ndarray, i: int) -> List[int]:
        return [int(j) for j in np.nonzero(matrix[i])[0]]
