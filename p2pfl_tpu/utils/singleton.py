"""Thread-safe singleton metaclass (reference: p2pfl/utils/singleton.py)."""

from __future__ import annotations

import threading
from typing import Any, Dict


class SingletonMeta(type):
    """Metaclass giving each class a single, lazily-created instance."""

    _instances: Dict[type, Any] = {}
    _lock = threading.Lock()

    def __call__(cls, *args: Any, **kwargs: Any) -> Any:
        if cls not in cls._instances:
            with SingletonMeta._lock:
                if cls not in cls._instances:
                    cls._instances[cls] = super().__call__(*args, **kwargs)
        return cls._instances[cls]

    @classmethod
    def reset(mcs, cls: type) -> None:
        """Drop the cached instance (tests)."""
        with mcs._lock:
            mcs._instances.pop(cls, None)
