"""Utility helpers (topologies, test helpers, singleton)."""
