"""Federated long-context LM fine-tuning example.

No reference analogue (the reference's models are MNIST-scale MLPs —
SURVEY.md §5 "long-context: absent"): N federated nodes fine-tune a
decoder-only transformer on their private token corpora through the mesh
simulation's causal-LM path (``MeshSimulation(task="lm")``), with the
attention kind selectable — ``blockwise`` (O(S)-memory online softmax),
``flash`` (Pallas TPU kernel), or ``dense``. For context lengths beyond
one chip's HBM, use ring attention over a sequence mesh axis via
``parallel.sequence.make_sequence_parallel_train_step`` (a separate
training path — it owns its own mesh axis, so it isn't a flag here).

The corpus is synthetic-but-learnable: arithmetic token progressions mod
the vocab, so next-token loss falls fast and the example is self-checking.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="p2pfl-tpu experiment run longcontext", description=__doc__
    )
    p.add_argument("--nodes", type=int, default=8)
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--embed-dim", type=int, default=64)
    p.add_argument("--seqs-per-node", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--train-set-size", type=int, default=4)
    p.add_argument(
        "--attention",
        choices=["blockwise", "flash", "dense"],
        default="blockwise",
        help="attention kind inside the federated LM",
    )
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument(
        "--dp-clip",
        type=float,
        default=0.0,
        help="DP-SGD per-sequence clip norm (> 0 enables private training)",
    )
    p.add_argument(
        "--dp-noise",
        type=float,
        default=0.0,
        help="DP-SGD Gaussian noise multiplier sigma",
    )
    p.add_argument("--measure-time", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--platform", choices=["default", "cpu", "tpu"], default="default"
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform != "default":
        import jax

        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from p2pfl_tpu.models import transformer_lm_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    rng = np.random.default_rng(args.seed)
    n, s, length = args.nodes, args.seqs_per_node, args.seq_len
    starts = rng.integers(0, args.vocab, size=(n, s, 1))
    x = ((starts + np.arange(length)[None, None, :]) % args.vocab).astype(np.int32)
    y = np.zeros((n, s), np.int32)  # unused for task="lm"
    mask = np.ones((n, s), np.float32)
    xt = (
        (rng.integers(0, args.vocab, size=(16, 1)) + np.arange(length)) % args.vocab
    ).astype(np.int32)

    model = transformer_lm_model(
        seed=args.seed,
        seq_len=length,
        vocab_size=args.vocab,
        num_layers=args.layers,
        num_heads=args.heads,
        embed_dim=args.embed_dim,
        attention_kind=args.attention,
    )
    sim = MeshSimulation(
        model,
        (x, y, mask),
        test_data=(xt, None),
        train_set_size=args.train_set_size,
        batch_size=args.batch_size,
        lr=args.lr,
        seed=args.seed,
        task="lm",
        dp_clip_norm=args.dp_clip,
        dp_noise_multiplier=args.dp_noise,
    )
    t0 = time.time()
    res = sim.run(rounds=args.rounds, epochs=args.epochs, warmup=True)
    result = {
        "seq_len": length,
        "attention": args.attention,
        "sec_per_round": round(res.seconds_per_round, 4),
        "first_token_loss": round(res.test_loss[0], 4),
        "final_token_loss": round(res.test_loss[-1], 4),
        "final_token_acc": round(res.test_acc[-1], 4),
    }
    if args.dp_clip > 0.0:
        result["dp_epsilon_at_1e-5"] = round(sim.privacy_spent()["epsilon"], 3)
    if args.measure_time:
        result["total_elapsed_s"] = round(time.time() - t0, 3)
    print(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
