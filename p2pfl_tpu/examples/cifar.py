"""Federated CIFAR-10 ResNet-18 (BASELINE.json configs #3 and #4).

Composes the pieces the baseline configs call for on the mesh-simulation
backend: GroupNorm ResNet-18 (:mod:`p2pfl_tpu.models.resnet`), Dirichlet
non-IID partitions, SCAFFOLD for client drift (config #3), and robust
aggregation (Multi-Krum / trimmed mean) against label-flipping Byzantine
nodes (config #4, ``--poison-frac``). The reference has no runnable
counterpart — its robust aggregators and CIFAR configs never meet in an
example or test.

Typical runs::

    # config #3 shape: 50 nodes, non-IID, SCAFFOLD
    python -m p2pfl_tpu.examples.cifar --aggregator scaffold

    # config #4 shape: 10% Byzantine label-flippers, Multi-Krum defense
    python -m p2pfl_tpu.examples.cifar --aggregator krum --poison-frac 0.1

    # same attack, no defense (shows the damage Krum prevents)
    python -m p2pfl_tpu.examples.cifar --aggregator fedavg --poison-frac 0.1
"""

from __future__ import annotations

import argparse
import math
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="p2pfl-tpu experiment run cifar", description=__doc__
    )
    p.add_argument("--nodes", type=int, default=50, help="population size")
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--epochs", type=int, default=1, help="local epochs per round")
    p.add_argument(
        "--aggregator",
        choices=["fedavg", "fedmedian", "scaffold", "krum", "trimmed_mean", "geomedian"],
        default="krum",
    )
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--train-set-size", type=int, default=8, help="committee size")
    p.add_argument("--samples-per-node", type=int, default=128)
    p.add_argument(
        "--rounds-per-call", type=int, default=1,
        help="rounds fused into one compiled call (amortizes dispatch)",
    )
    p.add_argument(
        "--eval-every", type=int, default=1,
        help="evaluate every k-th round (final round always evaluated)",
    )
    p.add_argument(
        "--poison-frac",
        type=float,
        default=0.0,
        help="fraction of Byzantine nodes (attack per --attack)",
    )
    p.add_argument(
        "--attack",
        choices=["labelflip", "signflip", "scaled"],
        default="labelflip",
        help="Byzantine mechanism: data poisoning (labelflip) or in-program "
        "model poisoning (signflip / 10x-scaled delta)",
    )
    p.add_argument(
        "--alpha",
        type=float,
        default=0.5,
        help="Dirichlet concentration for the non-IID partition",
    )
    p.add_argument(
        "--image-size",
        type=int,
        default=32,
        help="synthetic image side length (reduce for CPU smoke runs)",
    )
    p.add_argument("--lr", type=float, default=None, help="default: 0.05 scaffold, 1e-3 else")
    p.add_argument(
        "--clip-update-norm", type=float, default=0.0,
        help="norm-bounding defense: clip member deltas to this L2 norm "
        "before aggregation (0 = off; composes with any aggregator)",
    )
    p.add_argument(
        "--seed",
        type=int,
        default=None,
        help="pin the trainer RNG seed (unset: OS entropy; data stays "
        "deterministic either way)",
    )
    p.add_argument("--measure-time", action="store_true")
    p.add_argument(
        "--cost-analysis", action="store_true",
        help="report XLA's flops/bytes for the compiled round program "
        "(may AOT-recompile once; cheap under the persistent compile cache)",
    )
    p.add_argument(
        "--platform",
        choices=["default", "cpu", "tpu"],
        default="default",
        help="force a JAX platform before backend init (the env var alone "
        "cannot override a sitecustomize platform pin)",
    )
    return p


def run(args: argparse.Namespace) -> dict:
    if not 0.0 <= args.poison_frac < 1.0:
        raise SystemExit(f"--poison-frac must be in [0, 1), got {args.poison_frac}")
    if args.rounds_per_call < 1:
        raise SystemExit(f"--rounds-per-call must be >= 1, got {args.rounds_per_call}")
    if args.eval_every < 1:
        raise SystemExit(f"--eval-every must be >= 1, got {args.eval_every}")
    if args.aggregator == "scaffold" and args.clip_update_norm > 0:
        raise SystemExit(
            "--clip-update-norm composes with fedavg-style aggregators; "
            "scaffold's control variates assume unclipped deltas"
        )
    if args.aggregator == "scaffold" and args.attack != "labelflip" and args.poison_frac > 0:
        raise SystemExit(
            "model-poisoning attacks (--attack signflip/scaled) need a robust "
            "aggregator (krum/trimmed_mean/fedavg contrast); scaffold's server "
            "update has no robust variant"
        )
    from p2pfl_tpu.learning.dataset import (
        DirichletPartitionStrategy,
        poison_partitions,
        select_poisoned,
        synthetic_cifar10,
    )
    from p2pfl_tpu.models.resnet import resnet18_model
    from p2pfl_tpu.ops import aggregation as agg_ops
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    num_classes = 10
    data = synthetic_cifar10(
        n_train=args.nodes * args.samples_per_node,
        n_test=1024,
        num_classes=num_classes,
        image_size=args.image_size,
        seed=42,
    )
    parts = data.generate_partitions(
        args.nodes, DirichletPartitionStrategy, alpha=args.alpha,
        min_partition_size=max(2, args.samples_per_node // 8),
    )
    poisoned = []
    byzantine_mask = None
    if args.poison_frac > 0.0 and args.attack == "labelflip":
        parts, poisoned = poison_partitions(
            parts, args.poison_frac, num_classes, seed=7
        )
    elif args.poison_frac > 0.0:
        import numpy as np

        # Same selection as poison_partitions (shared helper): labelflip and
        # signflip/scaled runs at equal --poison-frac attack identical nodes.
        chosen = select_poisoned(args.nodes, args.poison_frac, seed=7)
        if len(chosen):  # a zero-count mask would compile the attack branch for nothing
            poisoned = chosen
            byzantine_mask = np.zeros(args.nodes, np.float32)
            byzantine_mask[poisoned] = 1.0

    # Byzantine budget for the robust rules: the expected number of poisoned
    # committee members, rounded up (Krum needs n - f - 2 >= 1 honest-majority
    # headroom; trimmed mean drops f from each tail).
    committee = args.train_set_size
    f = max(1, math.ceil(args.poison_frac * committee)) if len(poisoned) else 1
    f = min(f, max(1, (committee - 3) // 2))
    agg_fn = {
        "fedavg": agg_ops.fedavg,
        "fedmedian": lambda stacked, w: agg_ops.fedmedian(stacked),
        "krum": lambda stacked, w: agg_ops.krum(
            stacked, w, num_byzantine=f, num_selected=max(1, committee - f)
        )[0],
        "trimmed_mean": lambda stacked, w: agg_ops.trimmed_mean(stacked, trim=f),
        "geomedian": agg_ops.geometric_median,
    }.get(args.aggregator)
    algorithm = "scaffold" if args.aggregator == "scaffold" else "fedavg"
    lr = args.lr if args.lr is not None else (0.05 if algorithm == "scaffold" else 1e-3)

    # Context-managed: the jit cache pins every simulation that ran (static
    # `self`), so back-to-back runs in one process (the bench's
    # scaffold/krum/fedavg trio) must close() each or HBM fills with dead
    # populations.
    with MeshSimulation(
        resnet18_model(seed=0, input_shape=(args.image_size, args.image_size, 3)),
        parts,
        train_set_size=committee,
        batch_size=args.batch_size,
        seed=args.seed,
        aggregate_fn=agg_fn,
        algorithm=algorithm,
        lr=lr,
        byzantine_mask=byzantine_mask,
        byzantine_attack=args.attack,
        clip_update_norm=args.clip_update_norm,
    ) as sim:
        res = sim.run(
            rounds=args.rounds, epochs=args.epochs, warmup=True,
            rounds_per_call=args.rounds_per_call, eval_every=args.eval_every,
        )
        cost = (
            sim.round_cost_analysis(
                epochs=args.epochs, rounds_per_call=args.rounds_per_call,
                eval_every=args.eval_every,
            )
            if args.cost_analysis
            else None
        )
    return {
        "mode": "mesh",
        "model": "resnet18-groupnorm",
        "aggregator": args.aggregator,
        "attack": args.attack if len(poisoned) else None,
        "nodes": args.nodes,
        "poisoned_nodes": [int(i) for i in poisoned],
        "byzantine_budget": f if args.aggregator in ("krum", "trimmed_mean") else None,
        "sec_per_round": res.seconds_per_round,
        "test_acc": [round(a, 4) for a in res.test_acc],
        "final_test_acc": res.test_acc[-1] if res.test_acc else None,
        # XLA cost analysis of the exact compiled round program — the
        # bench's production-model MFU rows divide flops_per_round by the
        # measured sec_per_round (no hand-counted conv FLOPs).
        "cost_analysis": cost,
    }


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.platform != "default":
        import jax

        jax.config.update("jax_platforms", args.platform)

    import time

    t0 = time.monotonic()
    result = run(args)
    if args.measure_time:
        result["total_elapsed_s"] = round(time.monotonic() - t0, 3)
    print(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
