"""Two-process gRPC quickstart, process 1 (reference examples/node1.py).

Starts a gRPC node on 127.0.0.1:6666, waits for node2 to connect, runs a
2-round experiment, then shuts down. Run ``python -m p2pfl_tpu.examples.node2``
in another terminal.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="p2pfl-tpu experiment run node1", description=__doc__)
    p.add_argument("--addr", default="127.0.0.1:6666", help="bind address")
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--wait", type=float, default=600.0, help="peer-wait timeout (s)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from p2pfl_tpu.comm.grpc.grpc_protocol import GrpcCommunicationProtocol
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    data = synthetic_mnist(n_train=600, n_test=256)
    part = data.generate_partitions(2, RandomIIDPartitionStrategy)[0]
    node = Node(
        mlp_model(seed=0), part, addr=args.addr, protocol=GrpcCommunicationProtocol
    )
    node.start()
    print(f"node1 up at {node.addr}; waiting for a peer...", flush=True)
    try:
        deadline = time.time() + args.wait
        while not node.get_neighbors():
            if time.time() > deadline:
                print("no peer connected in time", file=sys.stderr)
                return 1
            time.sleep(0.5)
        print(f"peer connected; starting {args.rounds}-round experiment", flush=True)
        node.set_start_learning(rounds=args.rounds, epochs=1)
        node.wait_learning_finished(timeout=600)
        print("done:", node.learner.evaluate(), flush=True)
    finally:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
