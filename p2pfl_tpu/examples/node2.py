"""Two-process gRPC quickstart, process 2 (reference examples/node2.py).

Connects to node1 at 127.0.0.1:6666 and participates in the experiment it
starts. Run ``python -m p2pfl_tpu.examples.node1`` first.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="p2pfl-tpu experiment run node2", description=__doc__)
    p.add_argument("--peer", default="127.0.0.1:6666", help="node1's address")
    p.add_argument("--wait", type=float, default=600.0, help="start-of-learning timeout (s)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from p2pfl_tpu.comm.grpc.grpc_protocol import GrpcCommunicationProtocol
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    data = synthetic_mnist(n_train=600, n_test=256)
    part = data.generate_partitions(2, RandomIIDPartitionStrategy)[1]
    node = Node(
        mlp_model(seed=0), part, addr="127.0.0.1", protocol=GrpcCommunicationProtocol
    )
    node.start()
    if not node.connect(args.peer):
        print(f"could not connect to {args.peer}; is node1 running?", file=sys.stderr)
        node.stop()
        return 1
    print(f"node2 up at {node.addr}, connected to {args.peer}", flush=True)
    try:
        # Wait (bounded) for node1 to kick off learning, then for it to end.
        deadline = time.time() + args.wait
        while not node.learning_in_progress():
            if time.time() > deadline:
                print("node1 never started learning", file=sys.stderr)
                return 1
            time.sleep(0.5)
        node.wait_learning_finished(timeout=600)
        print("done:", node.learner.evaluate(), flush=True)
    finally:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
