"""MNIST federation example (reference p2pfl/examples/mnist.py:121-210).

Two execution modes (SURVEY.md §7 "hard parts"):

* ``--mode mesh`` (default): the TPU-native path — the whole population is a
  stacked pytree sharded over a device mesh and an experiment is one XLA
  program (:class:`~p2pfl_tpu.parallel.simulation.MeshSimulation`).
* ``--mode nodes``: capability-parity path — real :class:`~p2pfl_tpu.node.Node`
  objects running the async gossip protocol (in-memory or gRPC transport),
  exactly like the reference example.

Profiling goes through :mod:`p2pfl_tpu.management.profiler` (the reference
wires yappi, examples/mnist.py:264-297): ``--profiling`` writes a host
cProfile ``.pstat`` under ``profile/mnist/``; ``--trace DIR`` additionally
captures the on-device XLA timeline (TensorBoard/Perfetto-viewable).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="p2pfl-tpu experiment run mnist", description=__doc__
    )
    p.add_argument("--nodes", type=int, default=4, help="population size")
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--epochs", type=int, default=1, help="local epochs per round")
    p.add_argument(
        "--topology",
        choices=["line", "ring", "star", "full"],
        default="line",
        help="overlay topology (nodes mode)",
    )
    p.add_argument(
        "--protocol",
        choices=["memory", "grpc"],
        default="memory",
        help="transport (nodes mode)",
    )
    p.add_argument(
        "--aggregator",
        choices=["fedavg", "fedmedian", "scaffold", "krum", "trimmed_mean", "geomedian"],
        default="fedavg",
    )
    p.add_argument("--mode", choices=["mesh", "nodes"], default="mesh")
    p.add_argument(
        "--server-opt",
        choices=["none", "fedavgm", "fedadam", "fedyogi"],
        default="none",
        help="FedOpt server optimizer (mesh mode; Reddi et al. 2021)",
    )
    p.add_argument(
        "--server-lr", type=float, default=0.01,
        help="server step size for --server-opt (adaptive variants want "
        "~0.003-0.01; fedavgm ~1.0)",
    )
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--train-set-size", type=int, default=4, help="committee size")
    p.add_argument("--samples-per-node", type=int, default=300)
    p.add_argument("--measure-time", action="store_true")
    p.add_argument("--profiling", action="store_true", help="cProfile the run")
    p.add_argument(
        "--trace",
        metavar="DIR",
        default=None,
        help="write an on-device XLA profiler trace under DIR",
    )
    p.add_argument(
        "--dp-clip",
        type=float,
        default=0.0,
        help="DP-SGD per-example clip norm (> 0 enables private training)",
    )
    p.add_argument(
        "--dp-noise",
        type=float,
        default=0.0,
        help="DP-SGD Gaussian noise multiplier sigma",
    )
    p.add_argument(
        "--wire-compression",
        # Mirror config.py's validated choice set exactly ("topk" was
        # missing here — config/CLI drift of the kind C5 polices): the flag
        # writes Settings.WIRE_COMPRESSION, so the two sets must agree.
        choices=["none", "bf16", "int8", "topk"],
        default=None,
        help="codec for gossiped weight frames (nodes mode; mesh mode "
        "never puts weights on a wire). Unset: the "
        "P2PFL_TPU_WIRE_COMPRESSION env override (or 'none') applies.",
    )
    p.add_argument(
        "--seed",
        type=int,
        default=None,
        help="pin the trainer RNG seed (reproducible runs; voids the DP "
        "noise-unpredictability guarantee). Unset: OS entropy.",
    )
    p.add_argument(
        "--platform",
        choices=["default", "cpu", "tpu"],
        default="default",
        help="force a JAX platform before backend init (the env var alone "
        "cannot override a sitecustomize platform pin)",
    )
    return p


def _make_aggregator(name: str):
    from p2pfl_tpu.learning.aggregators import (
        FedAvg,
        FedMedian,
        GeometricMedian,
        Krum,
        Scaffold,
        TrimmedMean,
    )

    return {
        "fedavg": FedAvg,
        "fedmedian": FedMedian,
        "scaffold": Scaffold,
        "krum": Krum,
        "trimmed_mean": TrimmedMean,
        "geomedian": GeometricMedian,
    }[name]()


def run_mesh(args: argparse.Namespace) -> dict:
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.ops import aggregation as agg_ops
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    # 2*trim must stay below the committee size or the trimmed mean is empty
    trim = min(max(1, args.train_set_size // 4), (args.train_set_size - 1) // 2)
    agg_fn = {
        "fedavg": agg_ops.fedavg,
        "fedmedian": lambda stacked, w: agg_ops.fedmedian(stacked),
        "krum": lambda stacked, w: agg_ops.krum(stacked, w, num_byzantine=1)[0],
        "trimmed_mean": lambda stacked, w: agg_ops.trimmed_mean(stacked, trim=trim),
        "geomedian": agg_ops.geometric_median,
    }.get(args.aggregator)
    algorithm = "scaffold" if args.aggregator == "scaffold" else "fedavg"

    # Data stays deterministic either way — only the trainer seed (batch
    # order, committee draw, DP noise) goes entropy-derived when unset.
    data = synthetic_mnist(
        n_train=args.nodes * args.samples_per_node, n_test=1024,
        seed=42 if args.seed is None else args.seed,
    )
    parts = data.generate_partitions(args.nodes, RandomIIDPartitionStrategy)
    sim = MeshSimulation(
        mlp_model(seed=0),
        parts,
        train_set_size=args.train_set_size,
        batch_size=args.batch_size,
        seed=args.seed,
        aggregate_fn=agg_fn,
        algorithm=algorithm,
        lr=0.05 if algorithm == "scaffold" else 1e-3,
        dp_clip_norm=args.dp_clip,
        dp_noise_multiplier=args.dp_noise,
        server_optimizer=None if args.server_opt == "none" else args.server_opt,
        server_lr=args.server_lr,
    )
    res = sim.run(rounds=args.rounds, epochs=args.epochs, warmup=True)
    out = {
        "mode": "mesh",
        "sec_per_round": res.seconds_per_round,
        "final_test_acc": res.test_acc[-1] if res.test_acc else None,
    }
    if args.dp_clip > 0.0:
        out["dp_epsilon_at_1e-5"] = round(sim.privacy_spent()["epsilon"], 3)
    return out


def run_nodes(args: argparse.Namespace) -> dict:
    import numpy as np

    from p2pfl_tpu.config import Settings

    if args.wire_compression is not None:  # unset keeps the env override
        Settings.WIRE_COMPRESSION = args.wire_compression
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils.topologies import TopologyFactory, TopologyType
    from p2pfl_tpu.utils.utils import (
        check_equal_models,
        wait_convergence,
        wait_to_finish,
    )

    if args.protocol == "grpc":
        from p2pfl_tpu.comm.grpc.grpc_protocol import GrpcCommunicationProtocol

        protocol = GrpcCommunicationProtocol
        addr = lambda i: "127.0.0.1"  # noqa: E731 — random free port
    else:
        from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol

        protocol = InMemoryCommunicationProtocol
        addr = lambda i: None  # noqa: E731

    data = synthetic_mnist(
        n_train=args.nodes * args.samples_per_node, n_test=512,
        seed=42 if args.seed is None else args.seed,
    )
    parts = data.generate_partitions(args.nodes, RandomIIDPartitionStrategy)
    nodes = [
        Node(
            mlp_model(seed=0),
            parts[i],
            addr=addr(i),
            aggregator=_make_aggregator(args.aggregator),
            batch_size=args.batch_size,
            dp_clip_norm=args.dp_clip,
            dp_noise_multiplier=args.dp_noise,
        )
        for i in range(args.nodes)
    ]
    for n in nodes:
        n.start()
    try:
        matrix = TopologyFactory.generate_matrix(
            TopologyType(args.topology), args.nodes
        )
        TopologyFactory.connect_nodes(matrix, nodes)
        wait_convergence(nodes, args.nodes - 1, only_direct=False, wait=60)

        nodes[0].set_start_learning(rounds=args.rounds, epochs=args.epochs)
        wait_to_finish(nodes, timeout=3600)
        check_equal_models(nodes)

        accs = []
        for n in nodes:
            m = n.learner.evaluate()
            if "test_acc" in m:
                accs.append(m["test_acc"])
        out = {
            "mode": "nodes",
            "final_test_acc": float(np.mean(accs)) if accs else None,
        }
        if args.dp_clip > 0.0:
            # Privacy spend is a local claim of the node's own learner,
            # never read off the gossiped model (the executor decorator
            # delegates privacy_spent through its __getattr__).
            out["dp_epsilon_at_1e-5"] = round(
                nodes[0].learner.privacy_spent()["epsilon"], 3
            )
        return out
    finally:
        for n in nodes:
            n.stop()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.platform != "default":
        import jax

        jax.config.update("jax_platforms", args.platform)

    from p2pfl_tpu.management.profiler import profile_run

    with profile_run(
        host_dir="profile/mnist" if args.profiling else None,
        device_trace_dir=args.trace,
        label="mnist",
    ) as prof_info:
        result = run_mesh(args) if args.mode == "mesh" else run_nodes(args)

    if args.measure_time:
        result["total_elapsed_s"] = round(prof_info["elapsed_s"], 3)
    print(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
