"""Runnable example experiments (reference p2pfl/examples/).

Each entry maps a name to (module, description). The CLI's ``experiment``
subcommands (cli.py) discover examples from this registry, mirroring the
reference CLI's behavior of listing/running scripts from ``p2pfl/examples/``
(reference cli.py:138-230) — but via ``python -m`` module execution instead
of path-based subprocess scripts.
"""

from __future__ import annotations

EXAMPLES = {
    "mnist": (
        "p2pfl_tpu.examples.mnist",
        "N-node MNIST federation: --nodes/--rounds/--epochs/--topology/"
        "--protocol/--aggregator/--mode (mesh = one sharded XLA program, "
        "nodes = full async gossip protocol).",
    ),
    "cifar": (
        "p2pfl_tpu.examples.cifar",
        "Federated CIFAR-10 ResNet-18 (configs #3/#4): --aggregator "
        "{scaffold,krum,trimmed_mean,fedavg,fedmedian}/--poison-frac/"
        "--attack {labelflip,signflip,scaled}/--nodes/--alpha.",
    ),
    "longcontext": (
        "p2pfl_tpu.examples.longcontext",
        "Federated long-context LM fine-tuning over the mesh (task='lm'): "
        "--seq-len/--attention {blockwise,flash,dense}/--layers/--nodes.",
    ),
    "node1": (
        "p2pfl_tpu.examples.node1",
        "Two-process gRPC quickstart, process 1 (waits for node2, then trains).",
    ),
    "node2": (
        "p2pfl_tpu.examples.node2",
        "Two-process gRPC quickstart, process 2 (connects to node1).",
    ),
}

__all__ = ["EXAMPLES"]
