"""Attention kernels: dense reference, blockwise (online-softmax), and a
Pallas TPU flash-attention kernel.

The reference framework has no attention anywhere (models are MNIST MLPs,
flax_model.py:171-195) — long-context support is green-field TPU capability
for this framework. Design:

* ``dense_attention`` — O(S^2) memory reference implementation; ground truth
  for tests and fine for short sequences.
* ``blockwise_attention`` — FlashAttention-style online softmax as a pure JAX
  ``lax.scan`` over key/value blocks: O(S) memory, differentiable, XLA fuses
  the inner matmuls onto the MXU. Used as the per-chunk compute of ring
  attention (:mod:`p2pfl_tpu.ops.ring_attention`) and as the autodiff
  backward for the Pallas forward.
* ``flash_attention`` — Pallas kernel (grid over [batch, head, q-block,
  k-block] with online-softmax m/l/acc accumulators in VMEM scratch);
  forward on the MXU in the input dtype with float32 accumulation, emitting
  the per-row logsumexp. Backward is a pair of Pallas kernels
  (FlashAttention-2 style): a dq kernel accumulating over k blocks and a
  dk/dv kernel accumulating over q blocks, both recomputing probabilities
  from the saved logsumexp — O(block) VMEM, no S^2 residuals. Set
  ``bwd_kernel="remat"`` to fall back to differentiating the blockwise
  scan instead.

All functions take ``[batch, seq, heads, head_dim]`` ("BSHD") tensors and an
optional additive position offset pair so callers (ring attention) can apply
*global* causal masks to *local* sequence shards.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from p2pfl_tpu.utils.compat import shape_dtype_struct

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _causal_mask(
    scores: jax.Array, q_offset: jax.Array | int, kv_offset: jax.Array | int
) -> jax.Array:
    """Mask ``scores [..., Sq, Sk]`` where global q position < kv position."""
    sq, sk = scores.shape[-2], scores.shape[-1]
    q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return jnp.where(q_pos >= k_pos, scores, DEFAULT_MASK_VALUE)


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: jax.Array | int = 0,
    kv_offset: jax.Array | int = 0,
) -> jax.Array:
    """Materialized-softmax attention (reference implementation).

    Args:
        q: ``[B, Sq, H, D]``; k/v: ``[B, Sk, H, D]``.
        causal: apply a causal mask over *global* positions.
        q_offset / kv_offset: global position of the first row of q / k.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, q_offset, kv_offset)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(q.dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_k: int = 512,
    q_offset: jax.Array | int = 0,
    kv_offset: jax.Array | int = 0,
) -> jax.Array:
    """Online-softmax attention: ``lax.scan`` over key/value blocks.

    Never materializes the ``[Sq, Sk]`` score matrix for more than one key
    block, so activation memory is O(Sq * block_k). Fully differentiable
    (the scan's VJP rematerializes per-block).
    """
    m, l, acc = init_carry(q.shape)
    m, l, acc = blockwise_update(
        (m, l, acc), q, k, v, causal=causal, block_k=block_k,
        q_offset=q_offset, kv_offset=kv_offset,
    )
    return finalize_carry((m, l, acc), q.dtype)


def init_carry(q_shape: tuple) -> tuple:
    """Fresh online-softmax carry for queries of shape ``[B, Sq, H, D]``:
    running row max ``m [B, H, Sq]``, denominator ``l [B, H, Sq]``, and
    unnormalized output ``acc [B, Sq, H, D]`` (all float32)."""
    b, sq, h, d = q_shape
    m = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    acc = jnp.zeros((b, sq, h, d), jnp.float32)
    return m, l, acc


def finalize_carry(carry: tuple, dtype) -> jax.Array:
    """Normalize an online-softmax carry into the attention output."""
    m, l, acc = carry
    l_safe = jnp.einsum("bhq->bqh", jnp.maximum(l, 1e-30))[..., None]
    return (acc / l_safe).astype(dtype)


def blockwise_update(
    carry: tuple,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_k: int,
    q_offset: jax.Array | int,
    kv_offset: jax.Array | int,
) -> tuple:
    """Fold one key/value chunk into an online-softmax carry, blockwise.

    Ring attention chains this across rotating kv chunks (each with its own
    global ``kv_offset``); :func:`blockwise_attention` calls it once.
    """
    m, l, acc = carry
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    num_blocks = sk // block_k
    rem = sk - num_blocks * block_k
    scale = 1.0 / math.sqrt(d)

    qf = q.astype(jnp.float32) * scale

    def step(carry, inputs):
        m, l, acc = carry
        kb, vb, k_off = inputs
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
        if causal:
            s = _causal_mask(s, q_offset, k_off)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = corr * l + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        acc = jnp.einsum("bhq->bqh", corr)[..., None] * acc + pv
        return (m_new, l, acc), None

    if num_blocks:
        kb = k[:, : num_blocks * block_k].reshape(b, num_blocks, block_k, h, d)
        vb = v[:, : num_blocks * block_k].reshape(b, num_blocks, block_k, h, d)
        offs = kv_offset + jnp.arange(num_blocks, dtype=jnp.int32) * block_k
        (m, l, acc), _ = jax.lax.scan(
            step,
            (m, l, acc),
            (jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0), offs),
        )
    if rem:  # tail block (static shape — rem is a Python int)
        (m, l, acc), _ = step(
            (m, l, acc),
            (k[:, -rem:], v[:, -rem:], kv_offset + num_blocks * block_k),
        )
    return m, l, acc


# --- Pallas flash attention ---------------------------------------------------


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *, causal: bool
):
    """One (batch, head, q-block, k-block) program.

    The k-block axis is the innermost grid dimension — on TPU the grid runs
    sequentially, so the online-softmax statistics for the current q block
    persist in VMEM scratch across its k-block programs. Only one
    ``[block_q, d]`` q tile and one ``[block_k, d]`` k/v tile are resident
    at a time: VMEM stays O(block) regardless of sequence length.
    """
    q = q_ref[0, 0].astype(jnp.float32)  # [block_q, d]
    block_q, d = q.shape
    block_k = k_ref.shape[2]
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _fold():
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q * (1.0 / math.sqrt(d)), kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start)
        # m/l scratch carry the per-row stats broadcast across the 128-lane
        # minor dim (TPU-friendly tile shape); column 0 is authoritative.
        m = m_ref[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(
            corr * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
        )
        acc_ref[:] = corr * acc_ref[:] + jnp.dot(p, vb, preferred_element_type=jnp.float32)

    if causal:
        # Skip k blocks that lie entirely in this q block's future.
        pl.when(k_start < q_start + block_q)(_fold)
    else:
        _fold()

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
        ).astype(o_ref.dtype)
        # Per-row logsumexp (lane-broadcast like m/l): the backward kernels
        # recompute p = exp(s - lse) from it instead of storing S^2 probs.
        if lse_ref is not None:
            lse_ref[0, 0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _flash_kernel_no_lse(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, causal):
    """Forward-only variant: no lse output ref at all, so the pallas_call
    never materializes the ``[B,H,Sq,128]`` f32 lane-broadcast logsumexp in
    HBM — inference pays for the attention output only."""
    _flash_kernel(q_ref, k_ref, v_ref, o_ref, None, m_ref, l_ref, acc_ref, causal=causal)


def _pick_block(n: int, target: int) -> int:
    """Largest divisor of ``n`` that is <= ``target`` (so odd sequence
    lengths degrade gracefully instead of erroring)."""
    b = min(n, target)
    while n % b:
        b -= 1
    return b


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    with_lse: bool = True,
) -> tuple:
    """Returns ``(out [B,Sq,H,D], lse [B,H,Sq,128])`` — lse is lane-broadcast
    (column 0 authoritative) so the backward kernels read TPU-tiled blocks.

    ``with_lse=False`` (forward-only / inference path) dispatches the no-lse
    kernel variant and returns ``(out, None)``: the logsumexp exists only as
    VMEM scratch, never as an ``[B,H,Sq,128]`` f32 HBM output — a 128/d
    fraction of the output traffic saved (2x at d=64) when nothing will ever
    read it."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    # kernel layout [B, H, S, D]
    qt = jnp.moveaxis(q, 2, 1)
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    grid = (b, h, sq // block_q, sk // block_k)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
    ]
    o_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),  # running max m (lane-bcast)
        pltpu.VMEM((block_q, 128), jnp.float32),  # running denom l (lane-bcast)
        pltpu.VMEM((block_q, d), jnp.float32),  # unnormalized acc
    ]
    if not with_lse:
        out = pl.pallas_call(
            functools.partial(_flash_kernel_no_lse, causal=causal),
            out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            grid=grid,
            in_specs=in_specs,
            out_specs=o_spec,
            scratch_shapes=scratch,
            interpret=interpret,
        )(qt, kt, vt)
        return jnp.moveaxis(out, 1, 2), None
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 128), jnp.float32),
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            o_spec,
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.moveaxis(out, 1, 2), lse


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, dq_acc, *, causal: bool
):
    """dq for one (batch, head, q-block): accumulate over the k-block grid
    axis. Probabilities are recomputed from the forward's logsumexp."""
    q = q_ref[0, 0].astype(jnp.float32)
    block_q, d = q.shape
    block_k = k_ref.shape[2]
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    q_start = qi * block_q
    k_start = ki * block_k
    scale = 1.0 / math.sqrt(d)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _fold():
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        dd = dd_ref[0, 0][:, :1]
        s = jnp.dot(q * scale, kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp(s - lse)  # masked entries underflow to exactly 0
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dd)
        dq_acc[:] = dq_acc[:] + scale * jnp.dot(
            ds, kb, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(k_start < q_start + block_q)(_fold)
    else:
        _fold()

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, dd_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, causal: bool
):
    """dk/dv for one (batch, head, k-block): accumulate over the q-block
    grid axis (innermost), mirroring the dq kernel."""
    kb = k_ref[0, 0].astype(jnp.float32)
    block_k, d = kb.shape
    block_q = q_ref.shape[2]
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    k_start = ki * block_k
    q_start = qi * block_q
    scale = 1.0 / math.sqrt(d)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _fold():
        vb = v_ref[0, 0].astype(jnp.float32)
        qb = q_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        dd = dd_ref[0, 0][:, :1]
        s = jnp.dot(qb * scale, kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        dv_acc[:] = dv_acc[:] + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dd)
        dk_acc[:] = dk_acc[:] + scale * jnp.dot(
            ds.T, qb, preferred_element_type=jnp.float32
        )

    if causal:
        # A q block contributes iff its last row can see this k block.
        pl.when(q_start + block_q > k_start)(_fold)
    else:
        _fold()

    @pl.when(qi == pl.num_programs(3) - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, g, causal: bool, block_q: int, block_k: int, interpret: bool
):
    """FlashAttention-2-style backward: a dq kernel (k-block accumulation)
    and a dk/dv kernel (q-block accumulation), both O(block) VMEM."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    qt = jnp.moveaxis(q, 2, 1)
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    dot = jnp.moveaxis(g, 2, 1).astype(jnp.float32)
    ot = jnp.moveaxis(out, 2, 1).astype(jnp.float32)
    # D_i = sum_d dO * O per row (lane-broadcast for TPU-tiled reads); the
    # lse residual arrives compact [B,H,Sq,1] and is re-broadcast the same
    # way (XLA materializes these only for the kernel's lifetime).
    dd = jnp.broadcast_to(
        jnp.sum(dot * ot, axis=-1, keepdims=True), (b, h, sq, 128)
    )
    lse = jnp.broadcast_to(lse, (b, h, sq, 128))

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )
    k_spec = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        grid=(b, h, sq // block_q, sk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot.astype(q.dtype), lse, dd)

    # dkv grid: (b, h, k-block, q-block) — q innermost so dk/dv scratch
    # accumulates across it; index maps swap qi/ki roles accordingly.
    kv_spec = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0))
    qv_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    rowv_spec = pl.BlockSpec(
        (1, 1, block_q, 128), lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ),
        grid=(b, h, sk // block_k, sq // block_q),
        in_specs=[kv_spec, kv_spec, qv_spec, qv_spec, rowv_spec, rowv_spec],
        out_specs=(kv_spec, kv_spec),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(kt, vt, qt, dot.astype(q.dtype), lse, dd)
    return (
        jnp.moveaxis(dq, 1, 2),
        jnp.moveaxis(dk, 1, 2),
        jnp.moveaxis(dv, 1, 2),
    )


def _flash_carry_kernel(
    offs_ref, q_ref, k_ref, v_ref, m_in_ref, l_in_ref, acc_in_ref,
    m_out_ref, l_out_ref, acc_out_ref, *, causal: bool
):
    """Carry-in/carry-out flash fold of ONE kv chunk (ring attention's
    per-rotation step): like the forward kernel, but the online-softmax
    statistics START from the incoming carry and are emitted unnormalized
    (the ring finalizes after the last rotation). Global q/kv offsets
    arrive as scalar prefetch so the causal mask uses absolute positions.
    The out refs themselves accumulate across the k-block grid axis (same
    (bi, hi, qi) block for every ki program), so no scratch is needed.
    """
    q = q_ref[0, 0].astype(jnp.float32)
    block_q, d = q.shape
    block_k = k_ref.shape[2]
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    q_start = offs_ref[0] + qi * block_q
    k_start = offs_ref[1] + ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_out_ref[0, 0] = m_in_ref[0, 0]
        l_out_ref[0, 0] = l_in_ref[0, 0]
        acc_out_ref[0, 0] = acc_in_ref[0, 0]

    def _fold():
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q * (1.0 / math.sqrt(d)), kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start)
        m = m_out_ref[0, 0][:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        m_out_ref[0, 0] = jnp.broadcast_to(m_new, m_out_ref.shape[2:])
        l_out_ref[0, 0] = jnp.broadcast_to(
            corr * l_out_ref[0, 0][:, :1] + jnp.sum(p, axis=-1, keepdims=True),
            l_out_ref.shape[2:],
        )
        acc_out_ref[0, 0] = corr * acc_out_ref[0, 0] + jnp.dot(
            p, vb, preferred_element_type=jnp.float32
        )

    if causal:
        # Dynamic (offset-dependent) skip of chunks fully in this q block's
        # future; a skipped fold leaves the carry untouched, which is also
        # the mathematical contribution of an all-masked chunk.
        pl.when(k_start < q_start + block_q)(_fold)
    else:
        _fold()


def flash_chunk_update(
    carry: tuple,
    qt: jax.Array,
    kt: jax.Array,
    vt: jax.Array,
    q_offset: jax.Array,
    kv_offset: jax.Array,
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    vma: Optional[frozenset] = None,
) -> tuple:
    """Fold one kv chunk into a kernel-layout flash carry.

    Carry layout (all float32, kernel/"BHSD" convention): ``m [B,H,Sq,128]``
    lane-broadcast running max, ``l [B,H,Sq,128]`` denominator, ``acc
    [B,H,Sq,D]`` unnormalized output. Inputs ``qt/kt/vt`` are ``[B,H,S,D]``.
    This is :func:`blockwise_update`'s Pallas counterpart for ring
    attention's rotation step (2-3x faster forward at long S on TPU).
    ``vma``: when called inside ``shard_map`` (the ring), the mesh axes the
    outputs vary over — shard_map's vma checking requires it on pallas_call
    output shapes.
    """
    interpret = _resolve_interpret(interpret)
    m, l, acc = carry
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    offs = jnp.asarray(
        [jnp.int32(q_offset), jnp.int32(kv_offset)], dtype=jnp.int32
    )
    grid = (b, h, sq // block_q, sk // block_k)
    # NB: with num_scalar_prefetch, index maps receive the scalar ref AFTER
    # the grid indices.
    q_spec = pl.BlockSpec(
        (1, 1, block_q, d), lambda bi, hi, qi, ki, offs: (bi, hi, qi, 0)
    )
    k_spec = pl.BlockSpec(
        (1, 1, block_k, d), lambda bi, hi, qi, ki, offs: (bi, hi, ki, 0)
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 128), lambda bi, hi, qi, ki, offs: (bi, hi, qi, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[q_spec, k_spec, k_spec, row_spec, row_spec, q_spec],
        out_specs=[row_spec, row_spec, q_spec],
    )
    m, l, acc = pl.pallas_call(
        functools.partial(_flash_carry_kernel, causal=causal),
        grid_spec=grid_spec,
        out_shape=(
            shape_dtype_struct((b, h, sq, 128), jnp.float32, vma=vma),
            shape_dtype_struct((b, h, sq, 128), jnp.float32, vma=vma),
            shape_dtype_struct((b, h, sq, d), jnp.float32, vma=vma),
        ),
        interpret=interpret,
    )(offs, qt, kt, vt, m, l, acc)
    return m, l, acc


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    """Pallas interpret mode default: real kernels on TPU, interpreter
    elsewhere (the virtual CPU test mesh). One definition — forward and
    backward must never disagree."""
    return jax.default_backend() != "tpu" if interpret is None else interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    bwd_kernel: str = "pallas",
) -> jax.Array:
    """Pallas TPU flash attention over ``[B, S, H, D]`` tensors.

    On non-TPU backends (tests run on a virtual CPU mesh) the kernels run in
    Pallas interpret mode automatically. Backward is the FlashAttention-2
    Pallas kernel pair by default (probabilities recomputed from the saved
    logsumexp — O(block) VMEM); ``bwd_kernel="remat"`` differentiates the
    blockwise scan instead (kept as the independently-derived cross-check;
    ``tests/test_attention.py`` asserts both match dense gradients).

    The primal (not-under-``grad``) path runs the no-lse kernel variant:
    inference never reads the logsumexp, so it is not written to HBM at all
    (the ``custom_vjp`` forward rule below still emits it as the backward's
    residual when differentiating).
    """
    return _flash_forward(
        q, k, v, causal, block_q, block_k, _resolve_interpret(interpret),
        with_lse=False,
    )[0]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, bwd_kernel):
    if bwd_kernel not in ("pallas", "remat"):
        raise ValueError(f"bwd_kernel must be 'pallas' or 'remat', got {bwd_kernel!r}")
    out, lse = _flash_forward(
        q, k, v, causal, block_q, block_k, _resolve_interpret(interpret)
    )
    # The remat path recomputes everything from q/k/v — carrying out+lse
    # to the backward would inflate its activation memory for nothing. The
    # pallas path keeps only column 0 of the lane-broadcast lse (the
    # authoritative one): the saved residual is [B,H,S,1], not the 128x
    # kernel-layout tile; _flash_backward re-broadcasts it.
    if bwd_kernel == "pallas":
        return out, (q, k, v, out, lse[..., :1])
    return out, (q, k, v, None, None)


def _flash_bwd(causal, block_q, block_k, interpret, bwd_kernel, residuals, g):
    q, k, v, out, lse = residuals
    if bwd_kernel == "pallas":
        return _flash_backward(
            q, k, v, out, lse, g, causal, block_q, block_k,
            _resolve_interpret(interpret),
        )
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal, block_k=block_k),
        q,
        k,
        v,
    )
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
