"""Aggregation math as pure, jittable JAX kernels over stacked parameters.

The reference computes aggregation with per-layer numpy loops on host
(p2pfl/learning/aggregators/fedavg.py:41-77, fedmedian.py:24-65,
scaffold.py:29-140). Here every aggregation rule is a pure function over a
*stacked* parameter pytree — each leaf has a leading ``num_models`` axis — so:

* one ``jit`` covers every layer (XLA fuses the whole reduction),
* the same kernel runs on host-gathered models (federation mode) and on a
  mesh-sharded population (simulation mode): when the stacked axis is sharded
  over a mesh axis, XLA lowers the reductions below to ``reduce_scatter`` /
  ``all_reduce`` collectives over ICI — no hand-written NCCL-style calls,
* Byzantine-robust rules (median / trimmed-mean / Krum — BASELINE.json config
  #4) come almost for free as different reductions over the same stack.

All kernels take ``weights`` (per-model sample counts) where the rule uses
them and are wrapped in ``jax.jit`` at import; inputs may be numpy or jax
arrays.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def tree_stack(trees: list[Pytree]) -> Pytree:
    """Stack a list of identically-structured pytrees along a new axis 0."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(tree: Pytree, n: int) -> list[Pytree]:
    """Inverse of :func:`tree_stack`."""
    return [jax.tree.map(lambda x, i=i: x[i], tree) for i in range(n)]


@jax.jit
def fedavg(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Sample-weighted mean over the model axis.

    Semantics of reference fedavg.py:41-77: each model contributes
    proportionally to its ``num_samples``; supports partial aggregation (the
    caller passes whatever subset it currently holds).

    Args:
        stacked: pytree with leading axis ``num_models`` on every leaf.
        weights: ``[num_models]`` float weights (sample counts).
    """
    w = jnp.asarray(weights, dtype=jnp.float32)
    norm = w / jnp.maximum(w.sum(), 1e-12)

    def leaf(x: jax.Array) -> jax.Array:
        wn = norm.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.sum(x.astype(jnp.float32) * wn, axis=0).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


@jax.jit
def fedavg_masked(stacked: Pytree, weights: jax.Array, mask: jax.Array) -> Pytree:
    """FedAvg over a masked subset of the stack (static shapes, jit-friendly).

    Used by the mesh simulation where the per-round committee is a boolean
    mask over the population rather than a dynamic-length list (SURVEY.md §7
    "variable committee membership ... masked updates").
    """
    w = jnp.asarray(weights, dtype=jnp.float32) * jnp.asarray(mask, dtype=jnp.float32)
    return fedavg(stacked, w)


@jax.jit
def fedmedian(stacked: Pytree) -> Pytree:
    """Coordinate-wise median over the model axis.

    The reference declares FedMedian but raises NotImplementedError at the top
    of ``aggregate`` (fedmedian.py:41) — implemented for real here.
    """

    def leaf(x: jax.Array) -> jax.Array:
        return jnp.median(x.astype(jnp.float32), axis=0).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


@partial(jax.jit, static_argnames=("trim",))
def trimmed_mean(stacked: Pytree, trim: int) -> Pytree:
    """Coordinate-wise trimmed mean: drop the ``trim`` largest and smallest
    values per coordinate, then average. Byzantine-robust for up to ``trim``
    adversarial models (Yin et al. 2018)."""

    def leaf(x: jax.Array) -> jax.Array:
        n = x.shape[0]
        xs = jnp.sort(x.astype(jnp.float32), axis=0)
        sl = jax.lax.slice_in_dim(xs, trim, n - trim, axis=0)
        return jnp.mean(sl, axis=0).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


def _flatten_stack(stacked: Pytree) -> jax.Array:
    """[num_models, total_params] float32 matrix from a stacked pytree."""
    leaves = jax.tree.leaves(stacked)
    n = leaves[0].shape[0]
    return jnp.concatenate(
        [leaf.reshape(n, -1).astype(jnp.float32) for leaf in leaves], axis=1
    )


@partial(jax.jit, static_argnames=("num_byzantine", "num_selected"))
def krum_select(stacked: Pytree, num_byzantine: int, num_selected: int = 1) -> jax.Array:
    """(Multi-)Krum selection scores → indices of the selected models.

    Each model is scored by the sum of squared distances to its
    ``n - num_byzantine - 2`` nearest neighbors; the ``num_selected`` models
    with the lowest scores are selected (Blanchard et al. 2017). Returns the
    selected indices ``[num_selected]``.
    """
    x = _flatten_stack(stacked)
    n = x.shape[0]
    sq = jnp.sum(x * x, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (x @ x.T)  # pairwise squared dists (MXU)
    d2 = d2 + jnp.diag(jnp.full((n,), jnp.inf, dtype=d2.dtype))
    k = max(1, n - num_byzantine - 2)
    nearest = -jax.lax.top_k(-d2, k)[0]  # k smallest distances per row
    scores = jnp.sum(nearest, axis=1)
    _, idx = jax.lax.top_k(-scores, num_selected)
    return idx


@partial(jax.jit, static_argnames=("num_byzantine", "num_selected"))
def krum(
    stacked: Pytree, weights: jax.Array, num_byzantine: int, num_selected: int = 1
) -> tuple[Pytree, jax.Array]:
    """Multi-Krum aggregation: average the selected models (sample-weighted).

    Returns ``(aggregated, selected_indices)`` — callers need the indices
    for contributor provenance (only the selected models contributed)."""
    idx = krum_select(stacked, num_byzantine, num_selected)
    sel = jax.tree.map(lambda x: x[idx], stacked)
    return fedavg(sel, jnp.asarray(weights, dtype=jnp.float32)[idx]), idx


@partial(jax.jit, static_argnames=("iters",))
def geometric_median(
    stacked: Pytree, weights: jax.Array, iters: int = 8, eps: float = 1e-6
) -> Pytree:
    """Weighted geometric median over the model axis (Weiszfeld iterations).

    The strongest classic robust rule in the family here: unlike the
    coordinate-wise median/trimmed-mean it is rotation-invariant, and unlike
    Krum it does not have to commit to a discrete subset — RFA (Pillutla et
    al. 2019) shows it tolerates up to half the total weight being
    adversarial. No reference counterpart (its robust story is config #4's
    wish list); fixed ``iters`` keeps the loop jit-compilable and the whole
    solve runs as ``iters`` fused weighted means (one flattened [N, P]
    matrix — MXU-friendly, same layout Krum uses).
    """
    x = _flatten_stack(stacked)  # [N, P] float32
    w = jnp.asarray(weights, dtype=jnp.float32)
    w = w / jnp.maximum(w.sum(), 1e-12)

    def step(z, _):
        d = jnp.sqrt(jnp.maximum(jnp.sum((x - z) ** 2, axis=1), eps * eps))
        beta = w / d
        z = (beta @ x) / jnp.maximum(beta.sum(), 1e-12)
        return z, None

    z0 = w @ x  # start from the weighted mean
    z, _ = jax.lax.scan(step, z0, None, length=iters)

    # Unflatten back into the stacked pytree's structure/dtypes.
    out, offset = [], 0
    for leaf in jax.tree.leaves(stacked):
        size = math.prod(leaf.shape[1:])  # static shapes -> Python int
        out.append(
            z[offset : offset + size].reshape(leaf.shape[1:]).astype(leaf.dtype)
        )
        offset += size
    return jax.tree.unflatten(jax.tree.structure(stacked), out)


@jax.jit
def sparse_delta_apply(anchor_flat: jax.Array, idx: jax.Array, vals: jax.Array) -> jax.Array:
    """Merge a received sparse delta into a dense float32 base on-device:
    ``anchor_flat.at[idx].add(vals)`` — one fused XLA scatter-add per leaf,
    never a host loop over indices. This is the accumulation primitive of
    the sparse delta wire path (comm/delta.py): a gossiped top-k delta is
    reconstructed against the receiver's round anchor and lands directly in
    the float32 domain the aggregators already operate in."""
    return anchor_flat.at[idx].add(vals.astype(jnp.float32))


@jax.jit
def scaffold_update(
    global_params: Pytree,
    global_c: Pytree,
    delta_y_stack: Pytree,
    delta_c_stack: Pytree,
    global_lr: jax.Array,
    total_population: jax.Array,
) -> tuple[Pytree, Pytree]:
    """SCAFFOLD server update (Karimireddy et al. 2020).

    Reference semantics (scaffold.py:59-140): the server keeps a simulated
    global model and a global control variate ``c``; each round it applies the
    mean client model delta scaled by a global learning rate and moves ``c``
    by the mean control-variate delta scaled by ``num_clients / N``.

    Returns ``(new_global_params, new_global_c)``.
    """
    num_clients = jax.tree.leaves(delta_y_stack)[0].shape[0]

    new_params = jax.tree.map(
        lambda p, dy: (
            p.astype(jnp.float32) + global_lr * jnp.mean(dy.astype(jnp.float32), axis=0)
        ).astype(p.dtype),
        global_params,
        delta_y_stack,
    )
    frac = num_clients / jnp.maximum(total_population, 1.0)
    new_c = jax.tree.map(
        lambda c, dc: (
            c.astype(jnp.float32) + frac * jnp.mean(dc.astype(jnp.float32), axis=0)
        ).astype(c.dtype),
        global_c,
        delta_c_stack,
    )
    return new_params, new_c
