"""Wire compression for gossiped model weights.

The reference always gossips full-precision pickled float32 weights
(p2pfl/learning/frameworks/p2pfl_model.py:71-86); on a 1 GiB message cap
(grpc_server.py:64-71) that bounds model size and burns WAN bandwidth in
cross-host federations. This module adds lossy-but-bounded per-tensor
codecs applied *at the wire boundary only* — training and aggregation math
stay float32; only the bytes that ride the gossip protocol shrink:

* ``bf16`` — float32 leaves cast to bfloat16 (2x smaller, ~3 decimal
  digits kept; the same dtype the MXU computes in, so quantization noise
  is at compute-noise scale).
* ``int8`` — symmetric per-tensor linear quantization (4x smaller):
  ``q = round(a / scale)`` with ``scale = absmax / 127``; worst-case
  per-element error is ``scale / 2``.

Integer/bool leaves and empty tensors pass through unchanged. The codec
spec (per-tensor scheme + original dtype + scale) rides in the PFLT frame
metadata, so a receiver reconstructs float32 arrays transparently —
senders and receivers only need to agree on the frame format, not on a
compression setting (``Settings.WIRE_COMPRESSION`` is sender-local).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

SCHEMES = ("none", "bf16", "int8")

#: Reserved metadata key carrying the per-tensor codec spec in a PFLT frame.
CODEC_META_KEY = "__codec__"


def _bf16_dtype() -> np.dtype:
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def compress_arrays(
    arrays: Sequence[np.ndarray], scheme: str
) -> Tuple[List[np.ndarray], List[Dict[str, Any]]]:
    """Encode ``arrays`` under ``scheme``; returns (encoded, per-tensor spec).

    The spec list is msgpack-safe and positional (one entry per tensor).
    """
    if scheme not in SCHEMES:
        raise ValueError(f"unknown compression scheme {scheme!r}; known: {SCHEMES}")
    encoded: List[np.ndarray] = []
    spec: List[Dict[str, Any]] = []
    for a in arrays:
        a = np.asarray(a)
        if scheme == "none" or not np.issubdtype(a.dtype, np.floating) or a.size == 0:
            encoded.append(a)
            spec.append({"codec": "raw"})
        elif scheme == "bf16":
            encoded.append(a.astype(_bf16_dtype()))
            spec.append({"codec": "bf16", "dtype": a.dtype.str})
        else:  # int8
            absmax = float(np.max(np.abs(a)))
            if not np.isfinite(absmax):
                # int8 cannot represent NaN/inf; quantizing would launder a
                # diverged model into plausible finite weights. Ship the
                # tensor raw so receivers still see the divergence.
                encoded.append(a)
                spec.append({"codec": "raw"})
                continue
            scale = absmax / 127.0 if absmax > 0 else 1.0
            # float32 throughout: rint is exact over the +/-127 range, and a
            # float64 temporary would double transient memory on the gossip
            # encode path.
            q = np.clip(
                np.rint(a.astype(np.float32, copy=False) / np.float32(scale)),
                -127,
                127,
            )
            encoded.append(q.astype(np.int8))
            spec.append({"codec": "int8", "dtype": a.dtype.str, "scale": scale})
    return encoded, spec


def decompress_arrays(
    arrays: Sequence[np.ndarray], spec: Sequence[Dict[str, Any]]
) -> List[np.ndarray]:
    """Invert :func:`compress_arrays` given the frame's codec spec."""
    if len(arrays) != len(spec):
        raise ValueError(
            f"codec spec length {len(spec)} does not match tensor count {len(arrays)}"
        )
    out: List[np.ndarray] = []
    for a, s in zip(arrays, spec):
        codec = s.get("codec", "raw")
        if codec == "raw":
            out.append(np.asarray(a))
        elif codec == "bf16":
            out.append(np.asarray(a).astype(np.dtype(s["dtype"])))
        elif codec == "int8":
            out.append(
                (np.asarray(a, dtype=np.float32) * np.float32(s["scale"])).astype(
                    np.dtype(s["dtype"])
                )
            )
        else:
            raise ValueError(f"unknown tensor codec {codec!r}")
    return out
