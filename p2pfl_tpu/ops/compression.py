"""Wire compression for gossiped model weights.

The reference always gossips full-precision pickled float32 weights
(p2pfl/learning/frameworks/p2pfl_model.py:71-86); on a 1 GiB message cap
(grpc_server.py:64-71) that bounds model size and burns WAN bandwidth in
cross-host federations. This module adds lossy-but-bounded per-tensor
codecs applied *at the wire boundary only* — training and aggregation math
stay float32; only the bytes that ride the gossip protocol shrink:

* ``bf16`` — float32 leaves cast to bfloat16 (2x smaller, ~3 decimal
  digits kept; the same dtype the MXU computes in, so quantization noise
  is at compute-noise scale).
* ``int8`` — symmetric per-tensor linear quantization (4x smaller):
  ``q = round(a / scale)`` with ``scale = absmax / 127``; worst-case
  per-element error is ``scale / 2``.
* ``topk`` — magnitude top-k sparsification (~``4 / (ratio * (2 + 2))`` x
  smaller at bf16 values + gap-packed u16 indices, i.e. ~10x at ratio=0.1):
  only the k largest-|value| elements per tensor ship as an index+values
  pair; the rest decode to ZERO. Meant for round-anchored deltas with
  error feedback (Deep Gradient Compression, Lin et al. 2018; EF-SGD,
  Karimireddy et al. 2019) — see :mod:`p2pfl_tpu.comm.delta` for the
  stateful wire path that owns anchors and residuals. Selection runs
  on-device through a jitted ``jax.lax.top_k`` kernel.

Integer/bool leaves and empty tensors pass through unchanged. The codec
spec (per-tensor scheme + original dtype + scale) rides in the PFLT frame
metadata, so a receiver reconstructs float32 arrays transparently —
senders and receivers only need to agree on the frame format, not on a
compression setting (``Settings.WIRE_COMPRESSION`` is sender-local).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

SCHEMES = ("none", "bf16", "int8", "topk")

#: Reserved metadata key carrying the per-tensor codec spec in a PFLT frame.
CODEC_META_KEY = "__codec__"


def _bf16_dtype() -> np.dtype:
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# --- jitted top-k sparsification kernels --------------------------------------
#
# Selection runs on-device: ``jax.lax.top_k`` over |x| picks the k
# largest-magnitude elements of a flattened tensor, indices are sorted
# ascending (the wire layout gap-packs them, ops/serialization.py), and the
# gather/scatter pair stays one fused XLA program per (size, k) shape — no
# host loop ever walks elements. jax is imported lazily so the numpy-only
# codecs stay importable in jax-free tooling contexts.


def topk_select(flat: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k by magnitude over a flat float32 array.

    Returns ``(indices, values)`` with indices sorted ascending (int32) and
    values gathered in that index order (float32). Jitted per (size, k).
    """
    import jax

    idx, vals = _topk_select_kernel(jax.numpy.asarray(flat, jax.numpy.float32), k=k)
    return np.asarray(idx), np.asarray(vals)


def _topk_select_impl(flat, *, k: int):
    import jax
    import jax.numpy as jnp

    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    idx = jnp.sort(idx)
    return idx, flat[idx]


_topk_kernel_cache: Dict[str, Any] = {}


def _topk_select_kernel(flat, *, k: int):
    import jax

    fn = _topk_kernel_cache.get("select")
    if fn is None:
        fn = jax.jit(_topk_select_impl, static_argnames=("k",))
        _topk_kernel_cache["select"] = fn
    return fn(flat, k=k)


def scatter_dense(indices: np.ndarray, values: np.ndarray, size: int) -> np.ndarray:
    """Jitted inverse of :func:`topk_select`: dense float32 vector with
    ``values`` at ``indices`` and zeros elsewhere (disjoint indices)."""
    import jax
    import jax.numpy as jnp

    fn = _topk_kernel_cache.get("scatter")
    if fn is None:
        fn = jax.jit(
            lambda idx, vals, *, size: jnp.zeros((size,), jnp.float32)
            .at[idx]
            .set(vals),
            static_argnames=("size",),
        )
        _topk_kernel_cache["scatter"] = fn
    return np.asarray(
        fn(jnp.asarray(indices), jnp.asarray(values, jnp.float32), size=size)
    )


def topk_count(size: int, ratio: float) -> int:
    """Number of transmitted elements for a tensor of ``size`` at ``ratio``."""
    return max(1, min(size, math.ceil(size * ratio)))


def _ef_encode_impl(delta, residual, *, k: int, quantize_bf16: bool):
    import jax
    import jax.numpy as jnp

    acc = delta + residual  # error feedback: add back what was never sent
    _, idx = jax.lax.top_k(jnp.abs(acc), k)
    idx = jnp.sort(idx)
    vals = acc[idx]
    if quantize_bf16:
        wire = vals.astype(jnp.bfloat16)
        dequant = wire.astype(jnp.float32)
    else:
        wire = vals
        dequant = vals
    # Residual keeps EXACTLY what the receiver will not reconstruct: the
    # untransmitted tail plus (under bf16) the per-value quantization error.
    new_residual = acc.at[idx].add(-dequant)
    return idx, wire, new_residual


def _ef_quant_encode_impl(delta, residual, *, k: int, qmax: int):
    import jax
    import jax.numpy as jnp

    acc = delta + residual  # error feedback: add back what was never sent
    _, idx = jax.lax.top_k(jnp.abs(acc), k)
    idx = jnp.sort(idx)
    vals = acc[idx]
    # Symmetric per-tensor linear quantization of the SELECTED values only:
    # the grid is sized to the surviving top-k range, not the whole tensor,
    # so the worst-case per-value error is absmax(selected)/(2*qmax) — and
    # the EF residual absorbs exactly that error (returned residual holds
    # acc - dequant at transmitted positions, one f32 subtraction).
    absmax = jnp.max(jnp.abs(vals))
    scale = jnp.where(absmax > 0, absmax / qmax, jnp.float32(1.0))
    q = jnp.clip(jnp.round(vals / scale), -qmax, qmax).astype(jnp.int8)
    dequant = q.astype(jnp.float32) * scale
    new_residual = acc.at[idx].add(-dequant)
    return idx, q, scale, new_residual


def ef_topk_quant_encode(
    delta: "Any", residual: "Any", k: int, bits: int
) -> Tuple["Any", "Any", float, "Any"]:
    """Fused error-feedback top-k selection + integer value quantization.

    Like :func:`ef_topk_encode` but the wire values are symmetric linear
    int8 (``bits=8``, grid ±127) or int4 (``bits=4``, grid ±7, packed to
    nibbles by the caller via :func:`pack_nibbles`). Returns
    ``(indices, q_int8, scale, new_residual)``; the conservation contract is
    the bf16 one: ``new_residual[idx] == (delta+residual)[idx] - q*scale``
    element-exactly (one float32 subtraction per transmitted value), so the
    quantization error is never lost — it ships in a later round.
    """
    import jax
    import jax.numpy as jnp

    if bits not in (4, 8):
        raise ValueError(f"quantized top-k supports 4 or 8 bits, got {bits}")
    fn = _topk_kernel_cache.get("ef_quant")
    if fn is None:
        fn = jax.jit(_ef_quant_encode_impl, static_argnames=("k", "qmax"))
        _topk_kernel_cache["ef_quant"] = fn
    idx, q, scale, new_residual = fn(
        jnp.asarray(delta, jnp.float32),
        jnp.asarray(residual, jnp.float32),
        k=k,
        qmax=127 if bits == 8 else 7,
    )
    return idx, q, float(scale), new_residual


def pack_nibbles(q: np.ndarray) -> np.ndarray:
    """Pack int4-range values (each in [-7, 7]) two-per-byte (uint8).

    Wire form is unsigned: ``q + 8`` occupies [1, 15], reserving nibble 0 as
    an invalid sentinel so a hostile frame full of zero bytes fails the
    range check at decode. Odd tails are padded with the encoding of 0
    (``8``); the decoder slices to the spec's value count.
    """
    u = (np.asarray(q, np.int64) + 8).astype(np.uint8)
    if (u < 1).any() or (u > 15).any():
        raise ValueError("int4 value out of [-7, 7] range")
    if u.size % 2:
        u = np.concatenate([u, np.array([8], np.uint8)])
    return (u[0::2] | (u[1::2] << 4)).astype(np.uint8)


def unpack_nibbles(packed: np.ndarray, count: int) -> np.ndarray:
    """Invert :func:`pack_nibbles` back to ``count`` int8 values in [-7, 7].

    Raises ``ValueError`` on the reserved 0 nibble or a short buffer — the
    pre-dequantize sanity check for hostile int4 frames.
    """
    packed = np.asarray(packed, np.uint8).reshape(-1)
    if packed.size * 2 < count:
        raise ValueError("int4 plane shorter than the declared value count")
    u = np.empty(packed.size * 2, np.uint8)
    u[0::2] = packed & 0x0F
    u[1::2] = packed >> 4
    u = u[:count]
    if (u < 1).any() or (u > 15).any():
        raise ValueError("int4 nibble outside the [1, 15] wire range")
    return (u.astype(np.int16) - 8).astype(np.int8)


def ef_topk_encode(
    delta: "Any", residual: "Any", k: int, value_dtype: str = "bf16"
) -> Tuple["Any", "Any", "Any"]:
    """One fused error-feedback top-k selection step (jitted, on-device).

    Args:
        delta: flat float32 array (jax or numpy) — the new update to ship.
        residual: flat float32 array — the node's accumulated untransmitted
            remainder from previous encodes.
        k: number of elements to transmit.
        value_dtype: wire dtype of the values ("bf16" or "float32").

    Returns ``(indices, wire_values, new_residual)`` as jax arrays; indices
    sorted ascending. Conservation invariant (float32 values):
    ``scatter(indices, wire_values) + new_residual == delta + residual``
    element-exactly, because transmitted and untransmitted positions are
    disjoint.
    """
    import jax
    import jax.numpy as jnp

    fn = _topk_kernel_cache.get("ef_encode")
    if fn is None:
        fn = jax.jit(_ef_encode_impl, static_argnames=("k", "quantize_bf16"))
        _topk_kernel_cache["ef_encode"] = fn
    return fn(
        jnp.asarray(delta, jnp.float32),
        jnp.asarray(residual, jnp.float32),
        k=k,
        quantize_bf16=(value_dtype == "bf16"),
    )


def compress_arrays(
    arrays: Sequence[np.ndarray],
    scheme: str,
    ratio: Optional[float] = None,
    value_dtype: Optional[str] = None,
) -> Tuple[List[np.ndarray], List[Dict[str, Any]]]:
    """Encode ``arrays`` under ``scheme``; returns (encoded, per-tensor spec).

    The spec list is msgpack-safe and positional (one entry per LOGICAL
    tensor). A ``topk`` entry covers TWO consecutive encoded arrays (packed
    indices + values — the sparse layout of ops/serialization.py); every
    other codec maps 1:1. ``ratio``/``value_dtype`` apply to ``topk`` only
    and default to ``Settings.WIRE_TOPK_RATIO`` / ``Settings.WIRE_TOPK_VALUES``.

    ``topk`` is a *stateless* sparsifier: it keeps the k largest-magnitude
    elements per tensor and decodes the rest to ZERO. Callers are expected to
    feed it deltas (params - anchor, comm/delta.py) — sparsifying raw weights
    would discard most of the model.
    """
    if scheme not in SCHEMES:
        raise ValueError(f"unknown compression scheme {scheme!r}; known: {SCHEMES}")
    if scheme == "topk":
        from p2pfl_tpu.config import Settings

        ratio = Settings.WIRE_TOPK_RATIO if ratio is None else float(ratio)
        value_dtype = Settings.WIRE_TOPK_VALUES if value_dtype is None else value_dtype
        if not 0.0 < ratio <= 1.0:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")
        if value_dtype not in ("bf16", "float32"):
            raise ValueError(f"topk value_dtype must be 'bf16' or 'float32', got {value_dtype!r}")
    encoded: List[np.ndarray] = []
    spec: List[Dict[str, Any]] = []
    for a in arrays:
        a = np.asarray(a)
        if scheme == "none" or not np.issubdtype(a.dtype, np.floating) or a.size == 0:
            encoded.append(a)
            spec.append({"codec": "raw"})
        elif scheme == "topk":
            flat = np.ascontiguousarray(a, dtype=np.float32).reshape(-1)
            if not np.isfinite(flat).all():
                # like int8: never launder a diverged tensor into a plausible
                # sparse one — top_k over NaNs is undefined anyway
                encoded.append(a)
                spec.append({"codec": "raw"})
                continue
            from p2pfl_tpu.ops.serialization import encode_sparse_indices

            k = topk_count(flat.size, ratio)
            idx, vals = topk_select(flat, k)
            packed, index_codec = encode_sparse_indices(idx)
            if value_dtype == "bf16":
                vals = vals.astype(_bf16_dtype())
            encoded.append(packed)
            encoded.append(vals)
            spec.append(
                {
                    "codec": "topk",
                    "dtype": a.dtype.str,
                    "shape": list(a.shape),
                    "index_codec": index_codec,
                    "parts": 2,
                }
            )
        elif scheme == "bf16":
            encoded.append(a.astype(_bf16_dtype()))
            spec.append({"codec": "bf16", "dtype": a.dtype.str})
        else:  # int8
            absmax = float(np.max(np.abs(a)))
            if not np.isfinite(absmax):
                # int8 cannot represent NaN/inf; quantizing would launder a
                # diverged model into plausible finite weights. Ship the
                # tensor raw so receivers still see the divergence.
                encoded.append(a)
                spec.append({"codec": "raw"})
                continue
            scale = absmax / 127.0 if absmax > 0 else 1.0
            # float32 throughout: rint is exact over the +/-127 range, and a
            # float64 temporary would double transient memory on the gossip
            # encode path.
            q = np.clip(
                np.rint(a.astype(np.float32, copy=False) / np.float32(scale)),
                -127,
                127,
            )
            encoded.append(q.astype(np.int8))
            spec.append({"codec": "int8", "dtype": a.dtype.str, "scale": scale})
    return encoded, spec


def decompress_arrays(
    arrays: Sequence[np.ndarray], spec: Sequence[Dict[str, Any]]
) -> List[np.ndarray]:
    """Invert :func:`compress_arrays` given the frame's codec spec.

    ``topk`` entries consume two encoded arrays (packed indices + values) and
    densify through the jitted scatter kernel — untransmitted elements decode
    to zero (the delta wire path adds the round anchor back, comm/delta.py).
    """
    expected = sum(int(s.get("parts", 1)) for s in spec)
    if len(arrays) != expected:
        raise ValueError(
            f"codec spec length {len(spec)} ({expected} parts) does not match "
            f"tensor count {len(arrays)}"
        )
    out: List[np.ndarray] = []
    pos = 0
    for s in spec:
        codec = s.get("codec", "raw")
        if codec == "topk":
            from p2pfl_tpu.ops.serialization import decode_sparse_indices

            packed, vals = arrays[pos], arrays[pos + 1]
            pos += 2
            shape = tuple(s["shape"])
            size = int(np.prod(shape, dtype=np.int64))
            idx = decode_sparse_indices(np.asarray(packed), s["index_codec"])
            if idx.size != np.asarray(vals).size:
                raise ValueError("sparse index/values length mismatch")
            if idx.size and (idx[-1] >= size or idx[0] < 0):
                raise ValueError("sparse index out of tensor bounds")
            dense = scatter_dense(idx, np.asarray(vals, dtype=np.float32), size)
            out.append(dense.reshape(shape).astype(np.dtype(s["dtype"])))
            continue
        a = arrays[pos]
        pos += 1
        if codec == "raw":
            out.append(np.asarray(a))
        elif codec == "bf16":
            out.append(np.asarray(a).astype(np.dtype(s["dtype"])))
        elif codec == "int8":
            out.append(
                (np.asarray(a, dtype=np.float32) * np.float32(s["scale"])).astype(
                    np.dtype(s["dtype"])
                )
            )
        else:
            raise ValueError(f"unknown tensor codec {codec!r}")
    return out
