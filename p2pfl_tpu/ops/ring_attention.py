"""Ring attention: exact attention over sequences sharded across devices.

Long-context support is green-field for this framework (the reference has no
attention or sequence dimension anywhere — SURVEY.md §5 "Long-context ...
absent"); this is the TPU-native design: the sequence axis is sharded over a
mesh axis, each device holds a ``[B, S/n, H, D]`` shard of q/k/v, and key/value
chunks rotate around the ring with ``jax.lax.ppermute`` (which XLA lowers to
ICI neighbor exchanges) while each device folds the visiting chunk into its
queries' online-softmax carry (:func:`p2pfl_tpu.ops.attention.blockwise_update`).

After ``n`` steps every query has attended to every key — exact attention,
O(S/n) memory per device, with communication overlappable against the chunk
matmuls (XLA schedules the ppermute DMA concurrently with compute since the
next step's matmul doesn't depend on it until the fold).

Causal masking is *global*: chunk origins ride along the ring so each fold
masks by absolute positions. Fully-masked (future) chunks contribute exactly
zero to the carry (see the finite mask-value analysis in ops/attention.py).

The functions here are ``shard_map`` collectives — call them inside
``jax.shard_map`` with the sequence axis mapped (see
:func:`p2pfl_tpu.parallel.sequence.sequence_parallel_attention` for the
wrapped convenience form).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from p2pfl_tpu.utils.compat import HAS_NATIVE_SHARD_MAP, pvary
from p2pfl_tpu.ops.attention import (
    blockwise_update,
    finalize_carry,
    flash_chunk_update,
    init_carry,
)


def _ring_blockwise(q, k, v, axis_name, causal, block_k):
    """The lax.scan-over-blocks ring body (fully differentiable)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = idx * s_local

    # Each device sends its current kv chunk to its left neighbor, so chunk
    # origins visit in order idx, idx+1, ..., wrapping — the diagonal
    # (self) chunk is folded first, which keeps the online-softmax carry
    # well-conditioned under causal masking (every row sees a real key in
    # step 0).
    perm = [(i, (i - 1) % n) for i in range(n)]

    def step(carry, _):
        (m, l, acc), kc, vc, origin = carry
        m, l, acc = blockwise_update(
            (m, l, acc), q, kc, vc,
            causal=causal, block_k=block_k,
            q_offset=q_offset, kv_offset=origin * s_local,
        )
        kc, vc, origin = jax.lax.ppermute((kc, vc, origin), axis_name, perm)
        return ((m, l, acc), kc, vc, origin), None

    # The fresh carry is device-invariant; mark it varying over the ring axis
    # so the scan's carry types line up under shard_map's vma checking.
    carry0 = (
        jax.tree.map(
            lambda x: pvary(x, axis_name), init_carry(q.shape)
        ),
        k,
        v,
        idx,
    )
    (carry, _, _, _), _ = jax.lax.scan(step, carry0, None, length=n)
    return finalize_carry(carry, q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, block_k):
    """Ring forward with the Pallas flash-carry kernel per rotation (2-3x
    the blockwise fold's forward throughput at long S); backward
    rematerializes through the blockwise ring, whose scan VJP is the
    independently-tested gradient path."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    q_offset = idx * s_local
    perm = [(i, (i - 1) % n) for i in range(n)]

    # Kernel ("BHSD") layout once per call; kv chunks rotate pre-transposed.
    qt = jnp.moveaxis(q, 2, 1)
    var = lambda x: pvary(x, axis_name)  # noqa: E731
    m0 = var(jnp.full((b, h, s_local, 128), -jnp.inf, jnp.float32))
    l0 = var(jnp.zeros((b, h, s_local, 128), jnp.float32))
    acc0 = var(jnp.zeros((b, h, s_local, d), jnp.float32))

    def step(carry, _):
        (m, l, acc), kc, vc, origin = carry

        def fold(op):
            return flash_chunk_update(
                op, qt, kc, vc, q_offset, origin * s_local,
                causal=causal, block_k=block_k, vma=frozenset({axis_name}),
            )

        if causal:
            # A chunk with origin > idx is entirely in the local queries'
            # future: skip the kernel launch AND the m/l/acc HBM round-trip
            # it would spend copying the carry unchanged (n-1-idx of the n
            # rotations on device idx).
            m, l, acc = jax.lax.cond(
                origin > idx, lambda op: op, fold, (m, l, acc)
            )
        else:
            m, l, acc = fold((m, l, acc))
        kc, vc, origin = jax.lax.ppermute((kc, vc, origin), axis_name, perm)
        return ((m, l, acc), kc, vc, origin), None

    carry0 = ((m0, l0, acc0), jnp.moveaxis(k, 2, 1), jnp.moveaxis(v, 2, 1), idx)
    if HAS_NATIVE_SHARD_MAP:
        ((m, l, acc), _, _, _), _ = jax.lax.scan(step, carry0, None, length=n)
    else:
        # Old-jax fallback: an interpreted pallas_call inside lax.scan under
        # shard_map trips SPMD lowering (PartitionId is unimplemented for the
        # host partitioner). n is a trace-time constant, so unroll the ring.
        carry = carry0
        for _ in range(n):
            carry, _ = step(carry, None)
        (m, l, acc), _, _, _ = carry
    out = (acc / jnp.maximum(l[..., :1], 1e-30)).astype(q.dtype)
    return jnp.moveaxis(out, 1, 2)


def _ring_flash_fwd(q, k, v, axis_name, causal, block_k):
    return _ring_flash(q, k, v, axis_name, causal, block_k), (q, k, v)


def _ring_flash_bwd(axis_name, causal, block_k, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _ring_blockwise(q_, k_, v_, axis_name, causal, block_k),
        q, k, v,
    )
    return vjp(g)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    block_k: int = 512,
    impl: str = "blockwise",
) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name``.

    Must be called inside ``shard_map`` (or an equivalent SPMD context) with
    ``q/k/v`` of local shape ``[B, S_local, H, D]``, the global sequence laid
    out contiguously along the axis (device ``i`` holds positions
    ``[i*S_local, (i+1)*S_local)``).

    Args:
        axis_name: mesh axis the sequence is sharded over.
        causal: apply a global causal mask.
        block_k: key-block size of the per-chunk fold.
        impl: ``"blockwise"`` (lax.scan fold; default) or ``"flash"`` (the
            Pallas flash-carry kernel per rotation — faster forward on TPU;
            backward rematerializes through the blockwise ring). The flash
            impl needs the enclosing ``shard_map`` called with
            ``check_vma=False`` on CPU/interpret backends (the Pallas
            interpreter cannot trace varying-axis values through a kernel
            call); ``sequence_parallel_attention(impl="flash")`` sets it.

    Returns:
        Local output shard ``[B, S_local, H, D]``.
    """
    if impl == "flash":
        return _ring_flash(q, k, v, axis_name, causal, block_k)
    if impl != "blockwise":
        raise ValueError(f"impl must be 'blockwise' or 'flash', got {impl!r}")
    return _ring_blockwise(q, k, v, axis_name, causal, block_k)
