"""Safe, zero-copy-friendly wire format for model weights.

The reference pickles ``{"params": [np.ndarray, ...], "additional_info": {...}}``
and unpickles network payloads (p2pfl/learning/frameworks/p2pfl_model.py:71-101)
— an RCE risk called out in SURVEY.md §7. This module replaces pickle with a
flat self-describing buffer:

    magic "PFLT" | u16 version | u32 header_len | msgpack header | raw array bytes

The header carries dtype/shape per tensor plus a metadata dict (contributors,
num_samples, aggregator extra-info). Raw tensor bytes are laid out back to
back, 64-byte aligned, so deserialization is ``np.frombuffer`` views — no
copies, no code execution. Metadata is msgpack (no arbitrary objects); numpy
arrays inside metadata (e.g. SCAFFOLD control variates, scaffold.py:59-140 in
the reference) are encoded recursively with the same dtype/shape tagging.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Sequence, Tuple

import msgpack
import numpy as np

from p2pfl_tpu.exceptions import DecodingParamsError

_MAGIC = b"PFLT"
_VERSION = 1
_ALIGN = 64

# Sentinel key marking a msgpack map as an encoded ndarray.
_NDARRAY_KEY = "__pflt_ndarray__"


def _dtype_to_str(dt: np.dtype) -> str:
    """Portable dtype tag. ``dt.str`` is an opaque void ('|V2') for ml_dtypes
    types like bfloat16, so prefer the name when numpy can't round-trip it."""
    try:
        if np.dtype(dt.str) == dt:
            return dt.str
    except TypeError:
        pass
    return dt.name


def _str_to_dtype(s: str) -> np.dtype:
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))


def _encode_meta_value(v: Any) -> Any:
    """Recursively make a metadata value msgpack-safe (ndarrays tagged)."""
    if isinstance(v, np.ndarray):
        return {
            _NDARRAY_KEY: True,
            "dtype": _dtype_to_str(v.dtype),
            "shape": list(v.shape),
            "data": v.tobytes(),
        }
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, dict):
        return {str(k): _encode_meta_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_meta_value(x) for x in v]
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    raise TypeError(f"metadata value of type {type(v)!r} is not serializable")


def _decode_meta_value(v: Any) -> Any:
    if isinstance(v, dict):
        if v.get(_NDARRAY_KEY):
            arr = np.frombuffer(v["data"], dtype=_str_to_dtype(v["dtype"]))
            return arr.reshape(v["shape"]).copy()
        return {k: _decode_meta_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_meta_value(x) for x in v]
    return v


def _pad(n: int) -> int:
    return (-n) % _ALIGN


def serialize_arrays(
    arrays: Sequence[np.ndarray], metadata: Dict[str, Any] | None = None
) -> bytes:
    """Encode a flat list of arrays + metadata dict into one buffer."""
    # np.asarray(order="C") rather than ascontiguousarray: the latter promotes
    # 0-d arrays to 1-d (numpy >= 2.0), which would corrupt scalar leaves.
    np_arrays = [np.asarray(a, order="C") for a in arrays]
    header = {
        "tensors": [{"dtype": _dtype_to_str(a.dtype), "shape": list(a.shape)} for a in np_arrays],
        "meta": _encode_meta_value(metadata or {}),
    }
    header_bytes = msgpack.packb(header, use_bin_type=True)
    parts = [_MAGIC, struct.pack("<HI", _VERSION, len(header_bytes)), header_bytes]
    offset = len(_MAGIC) + 6 + len(header_bytes)
    parts.append(b"\0" * _pad(offset))
    offset += _pad(offset)
    for a in np_arrays:
        raw = a.tobytes()
        parts.append(raw)
        offset += len(raw)
        parts.append(b"\0" * _pad(offset))
        offset += _pad(offset)
    return b"".join(parts)


def deserialize_arrays(buf: bytes) -> Tuple[List[np.ndarray], Dict[str, Any]]:
    """Decode a buffer produced by :func:`serialize_arrays`.

    Returns (arrays, metadata). Arrays are zero-copy views into ``buf`` where
    alignment allows (always, by construction).
    """
    try:
        if buf[:4] != _MAGIC:
            raise DecodingParamsError("bad magic — not a p2pfl_tpu weights buffer")
        version, header_len = struct.unpack_from("<HI", buf, 4)
        if version != _VERSION:
            raise DecodingParamsError(f"unsupported wire version {version}")
        header_end = 10 + header_len
        header = msgpack.unpackb(buf[10:header_end], raw=False)
        offset = header_end + _pad(header_end)
        arrays: List[np.ndarray] = []
        for t in header["tensors"]:
            dtype = _str_to_dtype(t["dtype"])
            shape = tuple(t["shape"])
            count = int(np.prod(shape, dtype=np.int64))
            nbytes = dtype.itemsize * count
            arr = np.frombuffer(buf, dtype=dtype, count=count, offset=offset)
            arrays.append(arr.reshape(shape))
            offset += nbytes + _pad(offset + nbytes)
        meta = _decode_meta_value(header.get("meta", {}))
        return arrays, meta
    except DecodingParamsError:
        raise
    except Exception as exc:  # malformed input of any kind
        raise DecodingParamsError(f"could not decode weights payload: {exc}") from exc
