"""Safe, zero-copy-friendly wire format for model weights.

The reference pickles ``{"params": [np.ndarray, ...], "additional_info": {...}}``
and unpickles network payloads (p2pfl/learning/frameworks/p2pfl_model.py:71-101)
— an RCE risk called out in SURVEY.md §7. This module replaces pickle with a
flat self-describing buffer:

    "PFLT" | u16 version | u32 header_len | u32 crc32 | msgpack header
    | raw array bytes (each 64-byte aligned)

The header carries dtype/shape per tensor plus a metadata dict (contributors,
num_samples, aggregator extra-info). Raw tensor bytes are laid out back to
back, 64-byte aligned, so deserialization is ``np.frombuffer`` views — no
copies, no code execution. Metadata is msgpack (no arbitrary objects); numpy
arrays inside metadata (e.g. SCAFFOLD control variates, scaffold.py:59-140 in
the reference) are encoded recursively with the same dtype/shape tagging.
The crc32 (zlib polynomial) covers header bytes + raw tensor bytes, so both
metadata and weights corruption fail loudly; 0 means "not checked".

Frame assembly goes through the native C++ codec (:mod:`p2pfl_tpu.native`,
pflt_codec.cpp) when available, with a byte-identical pure-Python fallback.
"""

from __future__ import annotations

import ctypes
import struct
import zlib
from typing import Any, Dict, List, Sequence, Tuple

import msgpack
import numpy as np

from p2pfl_tpu import native
from p2pfl_tpu.exceptions import DecodingParamsError

_MAGIC = b"PFLT"
_VERSION = 2
_ALIGN = 64
_PREFIX = 14  # magic(4) + version(2) + header_len(4) + crc32(4)

# Sentinel key marking a msgpack map as an encoded ndarray.
_NDARRAY_KEY = "__pflt_ndarray__"


def _dtype_to_str(dt: np.dtype) -> str:
    """Portable dtype tag. ``dt.str`` is an opaque void ('|V2') for ml_dtypes
    types like bfloat16, so prefer the name when numpy can't round-trip it."""
    try:
        if np.dtype(dt.str) == dt:
            return dt.str
    except TypeError:
        pass
    return dt.name


def _str_to_dtype(s: str) -> np.dtype:
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))


def _encode_meta_value(v: Any) -> Any:
    """Recursively make a metadata value msgpack-safe (ndarrays tagged)."""
    if isinstance(v, np.ndarray):
        return {
            _NDARRAY_KEY: True,
            "dtype": _dtype_to_str(v.dtype),
            "shape": list(v.shape),
            "data": v.tobytes(),
        }
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, dict):
        return {str(k): _encode_meta_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_meta_value(x) for x in v]
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    raise TypeError(f"metadata value of type {type(v)!r} is not serializable")


def _decode_meta_value(v: Any) -> Any:
    if isinstance(v, dict):
        if v.get(_NDARRAY_KEY):
            arr = np.frombuffer(v["data"], dtype=_str_to_dtype(v["dtype"]))
            return arr.reshape(v["shape"]).copy()
        return {k: _decode_meta_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_meta_value(x) for x in v]
    return v


def _pad(n: int) -> int:
    return (-n) % _ALIGN


# --- sparse tensor layout (index + values per tensor) -------------------------
#
# A top-k-sparsified tensor rides the PFLT frame as TWO consecutive entries in
# the flat tensor list — a packed index array followed by a values array — with
# one ``__codec__`` spec entry describing both (ops/compression.py). Indices
# are sorted ascending and packed as either:
#
# * ``gap8`` — uint8 deltas between consecutive indices (first entry is the
#   absolute first index). At ~10% density the mean gap is ~10 and gaps above
#   255 are vanishingly rare, so 1 byte per index — and the byte stream
#   DEFLATEs close to its entropy inside a coalesced plane. Only emitted
#   into the coalesced (v2) frame layout (``allow_gap8``): the per-tensor
#   legacy layout stays byte-compatible with pre-gap8 decoders.
# * ``gap16`` — uint16 deltas, chosen whenever every gap (and the first
#   index) fits in 16 bits. The PR 1 default.
# * ``abs32`` — absolute uint32 indices (4 bytes) as the general fallback.
#
# All layouts are plain ndarrays, so they inherit the frame's 64-byte
# alignment, zero-copy decode, and CRC32 coverage — a corrupted index or
# values region fails the frame checksum exactly like dense weights.

SPARSE_INDEX_CODECS = ("gap8", "gap16", "abs32")


def encode_sparse_indices(
    idx: np.ndarray, allow_gap8: bool = False
) -> Tuple[np.ndarray, str]:
    """Pack sorted ascending flat indices; returns (packed, index_codec)."""
    idx = np.asarray(idx, dtype=np.int64)
    if idx.size == 0:
        return idx.astype(np.uint16), "gap16"
    gaps = np.diff(idx, prepend=0)
    if (gaps < 0).any():
        raise ValueError("sparse indices must be sorted ascending and unique")
    max_gap = int(gaps.max())
    if allow_gap8 and max_gap <= np.iinfo(np.uint8).max:
        return gaps.astype(np.uint8), "gap8"
    if max_gap <= np.iinfo(np.uint16).max:
        return gaps.astype(np.uint16), "gap16"
    if int(idx[-1]) > np.iinfo(np.uint32).max:
        raise ValueError("sparse index exceeds uint32 range")
    return idx.astype(np.uint32), "abs32"


def decode_sparse_indices(packed: np.ndarray, index_codec: str) -> np.ndarray:
    """Invert :func:`encode_sparse_indices` back to int64 flat indices."""
    if index_codec in ("gap8", "gap16"):
        return np.cumsum(np.asarray(packed, dtype=np.int64))
    if index_codec == "abs32":
        return np.asarray(packed, dtype=np.int64)
    raise ValueError(f"unknown sparse index codec {index_codec!r}")


def _frame_crc(header_bytes: bytes, np_arrays: Sequence[np.ndarray]) -> int:
    """Chained CRC32 (zlib polynomial) over header bytes + raw tensor bytes."""
    crc = zlib.crc32(header_bytes)
    for a in np_arrays:
        # uint8 view: ml_dtypes types (bfloat16 etc.) don't implement the
        # buffer protocol directly; 0-d arrays can't be viewed, so copy those.
        crc = zlib.crc32(a.view(np.uint8).data if a.ndim else a.tobytes(), crc)
    # reserve 0 as the "not checked" sentinel
    return crc if crc else 1


def serialize_arrays(
    arrays: Sequence[np.ndarray],
    metadata: Dict[str, Any] | None = None,
    checksum: bool = True,
) -> bytes:
    """Encode a flat list of arrays + metadata dict into one buffer.

    With ``checksum`` (default) the frame carries a CRC32 of header +
    tensor payload which :func:`deserialize_arrays` verifies — corruption of
    either weights or metadata in transit fails loudly instead of silently
    training on garbage.

    Returns bytes (Python path) or a ``bytearray`` (native path — single
    C++ pass into one buffer with no trailing copy; both satisfy the buffer
    protocol used by the transports).
    """
    # np.asarray(order="C") rather than ascontiguousarray: the latter promotes
    # 0-d arrays to 1-d (numpy >= 2.0), which would corrupt scalar leaves.
    np_arrays = [np.asarray(a, order="C") for a in arrays]
    header = {
        "tensors": [{"dtype": _dtype_to_str(a.dtype), "shape": list(a.shape)} for a in np_arrays],
        "meta": _encode_meta_value(metadata or {}),
    }
    header_bytes = msgpack.packb(header, use_bin_type=True)
    crc = _frame_crc(header_bytes, np_arrays) if checksum else 0

    lib = native.get_lib()
    if lib is not None:
        n = len(np_arrays)
        srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in np_arrays])
        sizes = (ctypes.c_size_t * n)(*[a.nbytes for a in np_arrays])
        total = lib.pflt_packed_size(sizes, n, len(header_bytes))
        buf = bytearray(total)
        written = lib.pflt_pack(
            (ctypes.c_char * total).from_buffer(buf), total, _VERSION, crc,
            header_bytes, len(header_bytes), srcs, sizes, n,
        )
        if written == total:
            return buf
        # fall through to the Python path on any native-side size mismatch

    parts = [_MAGIC, struct.pack("<HII", _VERSION, len(header_bytes), crc), header_bytes]
    offset = _PREFIX + len(header_bytes)
    parts.append(b"\0" * _pad(offset))
    offset += _pad(offset)
    for a in np_arrays:
        raw = a.tobytes()
        parts.append(raw)
        offset += len(raw)
        parts.append(b"\0" * _pad(offset))
        offset += _pad(offset)
    return b"".join(parts)


def deserialize_arrays(buf: bytes) -> Tuple[List[np.ndarray], Dict[str, Any]]:
    """Decode a buffer produced by :func:`serialize_arrays`.

    Returns (arrays, metadata). Arrays are zero-copy views into ``buf`` where
    alignment allows (always, by construction).
    """
    try:
        if bytes(buf[:4]) != _MAGIC:  # buf may be bytes, bytearray, memoryview
            raise DecodingParamsError("bad magic — not a p2pfl_tpu weights buffer")
        version, header_len, crc = struct.unpack_from("<HII", buf, 4)
        if version != _VERSION:
            raise DecodingParamsError(f"unsupported wire version {version}")
        header_end = _PREFIX + header_len
        header_bytes = buf[_PREFIX:header_end]
        header = msgpack.unpackb(header_bytes, raw=False)
        offset = header_end + _pad(header_end)
        arrays: List[np.ndarray] = []
        for t in header["tensors"]:
            dtype = _str_to_dtype(t["dtype"])
            shape = tuple(t["shape"])
            count = int(np.prod(shape, dtype=np.int64))
            nbytes = dtype.itemsize * count
            arr = np.frombuffer(buf, dtype=dtype, count=count, offset=offset)
            arrays.append(arr.reshape(shape))
            offset += nbytes + _pad(offset + nbytes)
        if crc and _frame_crc(header_bytes, arrays) != crc:
            raise DecodingParamsError("weights frame failed CRC32 integrity check")
        meta = _decode_meta_value(header.get("meta", {}))
        return arrays, meta
    except DecodingParamsError:
        raise
    except Exception as exc:  # malformed input of any kind
        raise DecodingParamsError(f"could not decode weights payload: {exc}") from exc
