"""Compute and wire-format primitives: serialization, aggregation kernels,
attention (blockwise / Pallas flash / ring)."""

from p2pfl_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    dense_attention,
    flash_attention,
)
from p2pfl_tpu.ops.ring_attention import ring_attention  # noqa: F401
