"""Compute and wire-format primitives: serialization, aggregation kernels."""
