"""p2pfl_tpu — a TPU-native decentralized federated learning framework.

Capability-equivalent to the reference p2pfl (peer-to-peer federated learning
over gossip; see /root/reference and SURVEY.md), re-designed TPU-first:

* local training is a jitted XLA computation (``lax.scan`` over batches) with
  parameters resident in HBM,
* aggregation math (FedAvg / median / trimmed-mean / Krum / SCAFFOLD) runs as
  jitted kernels over stacked parameter pytrees,
* large-scale simulation shards the federated population over a
  ``jax.sharding.Mesh`` (one slab of nodes per TPU device) instead of a Ray
  actor pool, keeping the whole multi-round loop on device,
* the host control plane (gossip, heartbeats, voting, commands) is a
  transport-agnostic protocol with in-memory and gRPC implementations, and a
  safe (no-pickle) flat-buffer wire format for weights.

Public API mirrors the reference's capabilities (reference: p2pfl/node.py:57):

    from p2pfl_tpu import Node
    node = Node(model, data, aggregator=FedAvg())
    node.start(); node.connect(addr)
    node.set_start_learning(rounds=3, epochs=1)
"""

__version__ = "0.1.0"

from p2pfl_tpu.config import Settings  # noqa: F401

__all__ = ["Settings", "Node", "__version__"]


def __getattr__(name):  # lazy import to keep `import p2pfl_tpu` light
    if name == "Node":
        from p2pfl_tpu.node import Node

        return Node
    raise AttributeError(f"module 'p2pfl_tpu' has no attribute {name!r}")
