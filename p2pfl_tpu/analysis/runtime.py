"""Runtime lock-order sentinel.

C1 approximates the acquisition graph lexically; this module records the
REAL one. While :meth:`LockOrderSentinel.patched` is active, every lock
built through ``threading.Lock`` / ``threading.RLock`` is wrapped in an
:class:`InstrumentedLock` that pushes/pops a thread-local held stack and
records a directed edge ``A -> B`` whenever B is acquired with A held.
After a multi-node chaos round, :meth:`assert_acyclic` proves no two code
paths ever disagreed on lock order — or names the cycle with the creation
sites of every lock in it.

Locks are grouped into lockdep-style CLASSES by creation site
(``file:lineno``): the three per-node ``Gossiper._pending_lock`` instances
of a 3-node federation are one class, so an A->B order on node 1 and B->A
on node 2 still forms a reportable cycle. Same-class nested acquisition is
treated as reentrant rather than an edge — instance-level self-deadlock of
a plain ``Lock`` is C1's (static) job, where instances are distinguishable.

Opt-in and test-scoped by design: the wrapper costs one dict update per
acquisition, and patching constructors process-wide also wraps library
locks (logging, executors, jax host callbacks) — which is exactly what you
want in a race hunt and never in production. ``make race-check`` runs a
3-node chaos round under the sentinel plus a deliberate-inversion negative
control.

The sentinel's own bookkeeping uses ``_thread.allocate_lock`` directly so
it is immune to its own patching (and can never deadlock with the locks it
watches).
"""

from __future__ import annotations

import _thread
import contextlib
import threading
from typing import Dict, Iterator, List, Optional, Tuple


def _creation_site(skip_module: str) -> str:
    """'relpath:lineno' of the first stack frame outside this module."""
    import sys

    frame = sys._getframe(2)
    while frame is not None and frame.f_code.co_filename.endswith(skip_module):
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    fname = frame.f_code.co_filename
    for marker in ("/p2pfl_tpu/", "/tests/", "/scripts/"):
        i = fname.rfind(marker)
        if i >= 0:
            fname = fname[i + 1:]
            break
    return f"{fname}:{frame.f_lineno}"


class LockOrderSentinel:
    """Process-wide acquisition-graph recorder (one instance: SENTINEL)."""

    def __init__(self) -> None:
        self._meta = _thread.allocate_lock()
        self._tls = threading.local()
        # (held, acquired) -> (count, held thread-site of first observation)
        self._edges: Dict[Tuple[str, str], int] = {}
        self._locks_seen = 0

    # --- recording (called by InstrumentedLock) ------------------------------

    def _held_stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def notify_created(self) -> None:
        with self._meta:
            self._locks_seen += 1

    def notify_acquired(self, name: str) -> None:
        stack = self._held_stack()
        if stack:
            with self._meta:
                for held in stack:
                    if held != name:
                        self._edges[(held, name)] = (
                            self._edges.get((held, name), 0) + 1
                        )
        stack.append(name)

    def notify_released(self, name: str) -> None:
        stack = self._held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # --- inspection ----------------------------------------------------------

    def reset(self) -> None:
        with self._meta:
            self._edges.clear()
            self._locks_seen = 0

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._meta:
            return dict(self._edges)

    def stats(self) -> Dict[str, int]:
        with self._meta:
            return {"locks": self._locks_seen, "edges": len(self._edges)}

    def find_cycle(self) -> Optional[List[str]]:
        """A lock-order cycle in the recorded graph, or None."""
        graph: Dict[str, List[str]] = {}
        for a, b in self.edges():
            graph.setdefault(a, []).append(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        parent: Dict[str, str] = {}

        def dfs(node: str) -> Optional[List[str]]:
            color[node] = GRAY
            for nxt in sorted(graph.get(node, ())):
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    cyc = [nxt, node]
                    cur = node
                    while cur != nxt:
                        cur = parent[cur]
                        cyc.append(cur)
                    cyc.reverse()
                    return cyc
                if c == WHITE:
                    parent[nxt] = node
                    got = dfs(nxt)
                    if got:
                        return got
            color[node] = BLACK
            return None

        for start in sorted(graph):
            if color.get(start, WHITE) == WHITE:
                got = dfs(start)
                if got:
                    return got
        return None

    def assert_acyclic(self) -> None:
        cyc = self.find_cycle()
        if cyc is not None:
            raise AssertionError(
                "lock-order cycle observed at runtime (potential deadlock): "
                + " -> ".join(cyc)
            )

    # --- instrumentation -----------------------------------------------------

    @contextlib.contextmanager
    def patched(self, reset: bool = True) -> Iterator["LockOrderSentinel"]:
        """Wrap ``threading.Lock``/``threading.RLock`` so every lock created
        in the block is instrumented. Locks outlive the block — recording
        continues until the process drops them — but constructor patching is
        strictly scoped."""
        if reset:
            self.reset()
        orig_lock, orig_rlock = threading.Lock, threading.RLock
        sentinel = self

        def make_lock() -> "InstrumentedLock":
            return InstrumentedLock(orig_lock(), sentinel, reentrant=False)

        def make_rlock() -> "InstrumentedLock":
            return InstrumentedLock(orig_rlock(), sentinel, reentrant=True)

        threading.Lock = make_lock  # type: ignore[assignment]
        threading.RLock = make_rlock  # type: ignore[assignment]
        try:
            yield self
        finally:
            threading.Lock = orig_lock  # type: ignore[assignment]
            threading.RLock = orig_rlock  # type: ignore[assignment]


class InstrumentedLock:
    """Lock wrapper feeding the sentinel. Duck-compatible with the stdlib
    lock protocol INCLUDING the private Condition hooks (``_is_owned`` /
    ``_release_save`` / ``_acquire_restore``), so ``threading.Condition``
    and ``threading.Event`` built on wrapped locks keep working — and the
    held-stack stays truthful across a ``Condition.wait`` (which releases
    the lock while blocked)."""

    __slots__ = ("_inner", "_sentinel", "_reentrant", "_name", "_depth")

    def __init__(
        self,
        inner,
        sentinel: LockOrderSentinel,
        reentrant: bool,
        name: Optional[str] = None,
    ) -> None:
        self._inner = inner
        self._sentinel = sentinel
        self._reentrant = reentrant
        self._name = name or _creation_site("analysis/runtime.py")
        self._depth = 0  # only meaningful for reentrant locks (owner-guarded)
        sentinel.notify_created()

    @property
    def name(self) -> str:
        return self._name

    # --- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._reentrant and self._depth > 0:
                self._depth += 1  # reentrant re-acquire: no new edge
            else:
                self._sentinel.notify_acquired(self._name)
                if self._reentrant:
                    self._depth = 1
        return got

    def release(self) -> None:
        if self._reentrant and self._depth > 1:
            self._depth -= 1
            self._inner.release()
            return
        self._depth = 0
        self._inner.release()
        self._sentinel.notify_released(self._name)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked() if hasattr(self._inner, "locked") else False

    # --- Condition integration ----------------------------------------------

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain lock: Condition's fallback probe
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        depth, self._depth = self._depth, 0
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        self._sentinel.notify_released(self._name)
        return (state, depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._sentinel.notify_acquired(self._name)
        self._depth = depth

    def _at_fork_reinit(self) -> None:
        if hasattr(self._inner, "_at_fork_reinit"):
            self._inner._at_fork_reinit()
        self._depth = 0

    def __repr__(self) -> str:
        return f"InstrumentedLock({self._name}, reentrant={self._reentrant})"


#: process-wide sentinel consumed by scripts/race_check.py and tests.
SENTINEL = LockOrderSentinel()
