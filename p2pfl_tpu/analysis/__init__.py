"""Correctness analysis plane.

Five interacting planes (gossip, chaos, Byzantine, observatory, async
windows) share 50+ threading primitives across the tree, and every past
concurrency bug (the PR 3 contributor-list race, the PR 4 post-aggregation
overwrite window) was found by hand after it bit a bench. Production FL
stacks (Papaya, arxiv 2111.04877) treat concurrency and wire-compat
invariants as machine-checked; this package is that check, wired into CI as
``make analyze``.

Static checkers (AST-based, :mod:`p2pfl_tpu.analysis.checkers`):

* **C1 lock-order** — the lock-acquisition-order graph from nested
  ``with <lock>`` scopes (plus one-hop call-under-lock resolution); a cycle
  is a potential deadlock, a ``with`` re-entry of a non-reentrant ``Lock``
  is a guaranteed one.
* **C2 blocking-under-lock** — transport sends, broadcasts, ``time.sleep``,
  thread joins, event waits and aggregation waits executed while a lock is
  held: the classic way one slow peer stalls every thread in the process.
* **C3 unguarded-shared-write** — attributes assigned from daemon-thread /
  command-handler entry points without a guarding lock (and without an
  explicit ``# unguarded-ok:`` annotation).
* **C4 jit-purity** — side-effecting calls (``time.*``, ``random``,
  ``np.random``, metrics, logging, ``print``) inside functions handed to
  ``jax.jit`` / ``pjit`` / ``shard_map``: they run at TRACE time only, so
  the metric/log silently freezes after compilation.
* **C5 drift** — ``P2PFL_TPU_*`` env reads that bypass ``config.py``'s
  validated fail-fast path, metric names used in code but absent from
  docs AND tests, and command names sent but never registered (or command
  classes defined but never wired into the dispatcher both transports
  share).

Runtime sentinel (:mod:`p2pfl_tpu.analysis.runtime`): an opt-in
instrumented-lock wrapper that records the ACTUAL acquisition graph during
multi-node chaos tests and asserts it acyclic (``make race-check``) — the
dynamic complement to C1's lexical approximation.

Suppressions live in ``analysis_baseline.json`` (every entry carries a
written reason); ``scripts/analyze.py`` exits 0 clean / 1 new finding /
2 stale suppression.
"""

from p2pfl_tpu.analysis.baseline import Baseline, compare
from p2pfl_tpu.analysis.checkers import ALL_CHECKERS, run_checkers
from p2pfl_tpu.analysis.core import Finding, ProjectIndex
from p2pfl_tpu.analysis.runtime import SENTINEL, LockOrderSentinel

__all__ = [
    "ALL_CHECKERS",
    "Baseline",
    "Finding",
    "LockOrderSentinel",
    "ProjectIndex",
    "SENTINEL",
    "compare",
    "run_checkers",
]
