"""Suppression baseline for the static checkers.

The committed ``analysis_baseline.json`` is the ONLY sanctioned way to ship
a known finding: every entry must carry a written reason, the CLI fails on
entries that no longer match anything (stale suppressions rot into lies),
and the acceptance bar keeps the file small — a baseline that grows is a
tree getting worse.

Exit-code contract (scripts/analyze.py):

* ``0`` — no new findings, no stale suppressions
* ``1`` — at least one NEW finding (not in the baseline)
* ``2`` — at least one STALE suppression (baseline entry matching nothing)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from p2pfl_tpu.analysis.core import Finding

BASELINE_VERSION = 1


@dataclass
class Suppression:
    checker: str
    key: str
    reason: str

    def to_json(self) -> Dict[str, str]:
        return {"checker": self.checker, "key": self.key, "reason": self.reason}


@dataclass
class Baseline:
    suppressions: List[Suppression] = field(default_factory=list)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        doc = json.loads(path.read_text())
        if doc.get("version") != BASELINE_VERSION:
            raise ValueError(
                f"baseline version {doc.get('version')!r} != {BASELINE_VERSION}"
            )
        sups = []
        for e in doc.get("suppressions", []):
            if not e.get("reason", "").strip():
                raise ValueError(
                    f"baseline entry {e.get('key')!r} has no reason — every "
                    "suppression must say WHY the finding is safe"
                )
            try:
                sups.append(Suppression(e["checker"], e["key"], e["reason"]))
            except KeyError as exc:
                raise ValueError(
                    f"baseline entry {e!r} is missing required field {exc}"
                ) from exc
        return cls(sups)

    def save(self, path: Path) -> None:
        doc = {
            "version": BASELINE_VERSION,
            "suppressions": [s.to_json() for s in self.suppressions],
        }
        path.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")


def compare(
    findings: Sequence[Finding], baseline: Baseline
) -> Tuple[List[Finding], List[Finding], List[Suppression]]:
    """(new findings, suppressed findings, stale suppressions)."""
    by_key = {s.key: s for s in baseline.suppressions}
    new: List[Finding] = []
    suppressed: List[Finding] = []
    matched: set = set()
    for f in findings:
        if f.key in by_key:
            suppressed.append(f)
            matched.add(f.key)
        else:
            new.append(f)
    stale = [s for s in baseline.suppressions if s.key not in matched]
    return new, suppressed, stale
