"""The five static checkers (C1-C5).

All of them are heuristic by design: they over-approximate (a flagged site
is *potentially* wrong) and the suppression channels — an inline
``# unguarded-ok: reason`` annotation for C3, the committed
``analysis_baseline.json`` for everything else — exist precisely so that a
human writes down WHY a finding is safe instead of the knowledge living in
one reviewer's head. A checker that finds nothing new on a clean tree and
flags the seeded-defect fixtures (tests/analysis_fixtures/) is doing its
job.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from p2pfl_tpu.analysis.core import (
    Finding,
    FuncInfo,
    Module,
    ProjectIndex,
    dotted_name,
    has_inline_waiver,
)

# ---------------------------------------------------------------------------
# shared: lexical with-lock scope walker
# ---------------------------------------------------------------------------


class _ScopeWalker:
    """Walk one function's statements tracking which locks are held
    lexically. Nested function definitions are NOT entered with held state
    (their bodies execute later, not under the lock); checkers that need
    them (C4) walk separately."""

    def __init__(self, index: ProjectIndex, mod: Module, info: FuncInfo) -> None:
        self.index = index
        self.mod = mod
        self.info = info
        self.held: List[Tuple[str, int]] = []  # (lock_id, acquire line)
        self.on_acquire: Optional[Callable[[str, int], None]] = None
        self.on_call: Optional[Callable[[ast.Call], None]] = None
        self.on_store: Optional[Callable[[ast.AST, int], None]] = None

    def walk(self) -> None:
        body = getattr(self.info.node, "body", [])
        for stmt in body:
            self._stmt(stmt)

    # --- statements ---------------------------------------------------------

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # executes later, not under the current lock scope
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = 0
            for item in node.items:
                lid = self.index.resolve_lock_expr(
                    item.context_expr, self.info.class_name, self.info.path
                )
                if lid:
                    if self.on_acquire:
                        self.on_acquire(lid, node.lineno)
                    self.held.append((lid, node.lineno))
                    acquired += 1
                else:
                    self._expr(item.context_expr)
            for stmt in node.body:
                self._stmt(stmt)
            for _ in range(acquired):
                self.held.pop()
            return
        # expressions nested in any other statement
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                self._expr(child)
        if isinstance(node, (ast.Assign, ast.AugAssign)) and self.on_store:
            self.on_store(node, node.lineno)

    def _expr(self, node: ast.AST) -> None:
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Call) and self.on_call:
                self.on_call(n)


# ---------------------------------------------------------------------------
# C1 — lock acquisition order
# ---------------------------------------------------------------------------


def check_lock_order(index: ProjectIndex, root: Path) -> List[Finding]:
    """Build the lock-order graph (lexical nesting + one-hop call-under-lock)
    and report cycles, plus guaranteed self-deadlocks: re-entering a
    non-reentrant ``Lock`` either lexically or through a same-class call."""
    findings: List[Finding] = []
    # edge: (A, B) -> (path, line, via) — acquire B while holding A
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    for info in index.funcs.values():
        mod = index.module_for(info.path)
        if mod is None:
            continue
        walker = _ScopeWalker(index, mod, info)

        def on_acquire(lid: str, line: int, info=info, walker=walker) -> None:
            for held_id, _ in walker.held:
                if held_id == lid:
                    if index.lock_kind(lid) == "Lock":
                        findings.append(
                            Finding(
                                "C1",
                                f"C1:self-deadlock:{info.qualname}:{lid}",
                                info.path,
                                line,
                                f"{info.qualname} re-enters non-reentrant "
                                f"{lid} it already holds — guaranteed deadlock",
                            )
                        )
                    continue
                edges.setdefault(
                    (held_id, lid), (info.path, line, info.qualname)
                )

        def on_call(call: ast.Call, info=info, walker=walker) -> None:
            if not walker.held:
                return
            for callee in index.resolve_callees(call, info.class_name, info.path):
                if callee.qualname == info.qualname:
                    continue
                for lid in callee.acquires:
                    for held_id, _ in walker.held:
                        if held_id == lid:
                            if index.lock_kind(lid) == "Lock":
                                findings.append(
                                    Finding(
                                        "C1",
                                        f"C1:self-deadlock:{info.qualname}:"
                                        f"{callee.name}:{lid}",
                                        info.path,
                                        call.lineno,
                                        f"{info.qualname} holds non-reentrant "
                                        f"{lid} and calls {callee.qualname} "
                                        "which re-acquires it — guaranteed "
                                        "deadlock",
                                    )
                                )
                            continue
                        edges.setdefault(
                            (held_id, lid),
                            (info.path, call.lineno, f"{info.qualname} -> {callee.name}"),
                        )

        walker.on_acquire = on_acquire
        walker.on_call = on_call
        walker.walk()

    findings.extend(_cycles_to_findings(edges))
    return findings


def _cycles_to_findings(
    edges: Dict[Tuple[str, str], Tuple[str, int, str]]
) -> List[Finding]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()
    findings: List[Finding] = []

    def dfs(node: str, stack: List[str], on_stack: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                core = tuple(cyc[:-1])
                k = min(range(len(core)), key=lambda i: core[i])
                canon = core[k:] + core[:k]
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                path, line, via = edges[(cyc[-2], cyc[-1])]
                findings.append(
                    Finding(
                        "C1",
                        "C1:cycle:" + "->".join(canon),
                        path,
                        line,
                        "lock-order cycle (potential deadlock): "
                        + " -> ".join(canon + (canon[0],))
                        + f" (closing edge via {via})",
                    )
                )
            elif nxt in graph and nxt not in visited_global:
                stack.append(nxt)
                on_stack.add(nxt)
                dfs(nxt, stack, on_stack)
                on_stack.discard(nxt)
                stack.pop()

    visited_global: Set[str] = set()
    for start in sorted(graph):
        dfs(start, [start], {start})
        visited_global.add(start)
    return findings


# ---------------------------------------------------------------------------
# C2 — blocking calls while a lock is held
# ---------------------------------------------------------------------------

#: leaf method names that block on the network / other threads when invoked
#: on the federation's objects.
_BLOCKING_LEAVES = {
    "send",
    "_safe_send",
    "_transport_send",
    "broadcast",
    "deliver",
    "gossip_weights",
    "wait_and_get_aggregation",
}
_BLOCKING_DOTTED = {"time.sleep", "subprocess.run", "subprocess.check_output", "subprocess.call"}


def _receiver_chain(call: ast.Call) -> str:
    name = dotted_name(call.func)
    return name or ""


def check_blocking_under_lock(index: ProjectIndex, root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for info in index.funcs.values():
        mod = index.module_for(info.path)
        if mod is None:
            continue
        walker = _ScopeWalker(index, mod, info)

        def on_call(call: ast.Call, info=info, walker=walker, mod=mod) -> None:
            if not walker.held:
                return
            label = _blocking_label(call, walker, index)
            if label is None:
                return
            if has_inline_waiver(mod, call.lineno, "blocking-ok:"):
                return
            lock_id = walker.held[-1][0]
            findings.append(
                Finding(
                    "C2",
                    f"C2:{info.qualname}:{label}:{lock_id}",
                    info.path,
                    call.lineno,
                    f"{info.qualname} calls blocking {label} while holding "
                    f"{lock_id} — every thread contending that lock stalls "
                    "behind the slow/network operation",
                )
            )

        walker.on_call = on_call
        walker.walk()
    return findings


def _blocking_label(
    call: ast.Call, walker: _ScopeWalker, index: ProjectIndex
) -> Optional[str]:
    chain = _receiver_chain(call)
    if chain in _BLOCKING_DOTTED:
        return chain
    if not isinstance(call.func, ast.Attribute):
        if isinstance(call.func, ast.Name) and call.func.id == "sleep":
            return "sleep"
        return None
    leaf = call.func.attr
    if leaf in _BLOCKING_LEAVES:
        return chain or leaf
    recv = dotted_name(call.func.value) or ""
    if leaf == "join":
        # str.join is everywhere; only thread-ish receivers block.
        low = recv.lower()
        if any(t in low for t in ("thread", "proc", "worker", "executor")):
            return f"{recv}.join"
        return None
    if leaf == "wait":
        # Condition.wait ON a held lock is the correct idiom (it releases);
        # waiting on anything ELSE while holding a lock is the bug.
        lid = index.resolve_lock_expr(call.func.value, walker.info.class_name, walker.info.path)
        if lid and index.lock_kind(lid) == "Condition" and any(
            h == lid for h, _ in walker.held
        ):
            return None
        return f"{recv}.wait"
    if leaf == "result":
        low = recv.lower()
        if "fut" in low:
            return f"{recv}.result"
    return None


# ---------------------------------------------------------------------------
# C3 — unguarded shared-attribute writes from thread entry points
# ---------------------------------------------------------------------------


def _thread_entry_funcs(index: ProjectIndex) -> Dict[str, str]:
    """qualname -> why it's an entry point. Covers ``Thread(target=...)``,
    ``executor.submit(fn, ...)``, and ``execute`` methods of Command
    subclasses (transport-thread command handlers)."""
    entries: Dict[str, str] = {}
    for info in index.funcs.values():
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            target: Optional[ast.AST] = None
            why = ""
            if fname.rsplit(".", 1)[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        target, why = kw.value, "Thread(target=...)"
            elif fname.endswith(".submit") and node.args:
                target, why = node.args[0], "executor.submit"
            if target is None:
                continue
            for callee in _resolve_func_ref(index, target, info):
                entries.setdefault(callee.qualname, why)
    for cls, methods in index.classes.items():
        bases = index.class_bases.get(cls, [])
        is_cmd = cls.endswith("Command") or any(
            b.rsplit(".", 1)[-1] == "Command" for b in bases
        )
        if is_cmd and "execute" in methods:
            entries.setdefault(
                methods["execute"].qualname, "command handler (transport thread)"
            )
    return entries


def _resolve_func_ref(
    index: ProjectIndex, ref: ast.AST, info: FuncInfo
) -> List[FuncInfo]:
    if isinstance(ref, ast.Attribute):
        name = ref.attr
        if isinstance(ref.value, ast.Name) and ref.value.id == "self" and info.class_name:
            own = index.classes.get(info.class_name, {}).get(name)
            if own:
                return [own]
        cands = [c for c in index.funcs_by_name.get(name, []) if c.class_name]
        return cands if len(cands) == 1 else []
    if isinstance(ref, ast.Name):
        cands = [
            c
            for c in index.funcs_by_name.get(ref.id, [])
            if c.path == info.path and c.class_name is None
        ]
        return cands if len(cands) == 1 else []
    return []


def check_unguarded_writes(index: ProjectIndex, root: Path) -> List[Finding]:
    # attr name -> count of functions that assign it (shared-state filter)
    writers: Dict[str, Set[str]] = {}
    for info in index.funcs.values():
        for node in ast.walk(info.node):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        writers.setdefault(t.attr, set()).add(info.qualname)

    entries = _thread_entry_funcs(index)
    findings: List[Finding] = []
    for qual, why in sorted(entries.items()):
        info = index.funcs.get(qual)
        if info is None:
            continue
        mod = index.module_for(info.path)
        if mod is None:
            continue
        # locals constructed fresh in this function are thread-private
        fresh: Set[str] = set()
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        fresh.add(t.id)
        walker = _ScopeWalker(index, mod, info)

        def on_store(
            node: ast.AST, line: int, info=info, walker=walker, mod=mod, fresh=fresh, why=why
        ) -> None:
            if walker.held:
                return
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]  # type: ignore[attr-defined]
            )
            for t in targets:
                if not isinstance(t, ast.Attribute):
                    continue
                root_name = t
                while isinstance(root_name, ast.Attribute):
                    root_name = root_name.value
                if not isinstance(root_name, ast.Name):
                    continue
                if root_name.id in fresh:
                    continue  # object constructed by this thread — private
                if len(writers.get(t.attr, ())) < 2:
                    continue  # not demonstrably shared state
                if has_inline_waiver(mod, line, "unguarded-ok:"):
                    continue
                findings.append(
                    Finding(
                        "C3",
                        f"C3:{info.qualname}:{t.attr}",
                        info.path,
                        line,
                        f"{info.qualname} ({why}) writes shared attribute "
                        f".{t.attr} without holding a lock — annotate "
                        "'# unguarded-ok: <reason>' if the write is safe",
                    )
                )

        walker.on_store = on_store
        walker.walk()
    return findings


# ---------------------------------------------------------------------------
# C4 — jit purity
# ---------------------------------------------------------------------------

_JIT_WRAPPERS = {"jit", "pjit", "shard_map"}
_IMPURE_ROOTS = {"time", "random", "logging", "log", "logger", "REGISTRY", "SKETCHES", "TRACER"}
_IMPURE_PREFIXES = ("np.random.", "numpy.random.")
_IMPURE_LEAVES = {"inc", "observe"}  # metric mutations via .labels(...).inc()


def _is_jit_wrapper(expr: ast.AST) -> bool:
    """True for jax.jit / jit / pjit / shard_map, and partial(jax.jit, ...)."""
    name = dotted_name(expr)
    if name and name.rsplit(".", 1)[-1] in _JIT_WRAPPERS:
        return True
    if isinstance(expr, ast.Call):
        cname = dotted_name(expr.func)
        if cname and cname.rsplit(".", 1)[-1] == "partial" and expr.args:
            return _is_jit_wrapper(expr.args[0])
        # jax.jit(fn, static_argnames=...) used as decorator factory value
        return _is_jit_wrapper(expr.func)
    return False


def _jitted_funcs(index: ProjectIndex) -> Dict[str, str]:
    """qualname -> how it gets jitted."""
    out: Dict[str, str] = {}
    for info in index.funcs.values():
        for dec in getattr(info.node, "decorator_list", []):
            if _is_jit_wrapper(dec):
                out[info.qualname] = "decorator"
    # call sites: jax.jit(F) / pjit(F) / shard_map(F, ...) anywhere
    for mod in index.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = dotted_name(node.func)
            if not cname or cname.rsplit(".", 1)[-1] not in _JIT_WRAPPERS:
                continue
            if not node.args:
                continue
            ref = node.args[0]
            refname = None
            if isinstance(ref, ast.Name):
                refname = ref.id
            elif isinstance(ref, ast.Attribute):
                refname = ref.attr
            if refname is None:
                continue
            for cand in index.funcs_by_name.get(refname, []):
                if cand.path == mod.rel:
                    out.setdefault(cand.qualname, f"passed to {cname}")
    return out


def check_jit_purity(index: ProjectIndex, root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for qual, how in sorted(_jitted_funcs(index).items()):
        info = index.funcs[qual]
        mod = index.module_for(info.path)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            label = _impure_label(node)
            if label is None:
                continue
            if mod is not None and has_inline_waiver(mod, node.lineno, "jit-impure-ok:"):
                continue
            findings.append(
                Finding(
                    "C4",
                    f"C4:{info.qualname}:{label}",
                    info.path,
                    node.lineno,
                    f"{info.qualname} (jitted via {how}) calls side-effecting "
                    f"{label}: it executes at TRACE time only and silently "
                    "freezes after compilation",
                )
            )
    return findings


def _impure_label(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name) and call.func.id == "print":
        return "print"
    chain = dotted_name(call.func)
    if chain:
        root = chain.split(".", 1)[0]
        if root in _IMPURE_ROOTS:
            return chain
        if chain.startswith(_IMPURE_PREFIXES):
            return chain
    if isinstance(call.func, ast.Attribute) and call.func.attr in _IMPURE_LEAVES:
        # _METRIC.labels(...).inc() — receiver is a Call, chain is None
        if isinstance(call.func.value, ast.Call):
            inner = dotted_name(call.func.value.func) or ""
            if inner.endswith(".labels") or inner == "labels":
                return f"{inner}().{call.func.attr}"
    return None


# ---------------------------------------------------------------------------
# C5 — drift: env reads, metric names, command registration
# ---------------------------------------------------------------------------


def check_drift(index: ProjectIndex, root: Path) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(_drift_env_reads(index))
    findings.extend(_drift_metrics(index, root))
    findings.extend(_drift_commands(index))
    return findings


def _drift_env_reads(index: ProjectIndex) -> List[Finding]:
    """P2PFL_TPU_* env reads outside config.py bypass the validated
    fail-fast path — a typo'd value then explodes mid-round on a transport
    thread instead of at import."""
    out: List[Finding] = []
    for mod in index.modules:
        if mod.rel.endswith("config.py"):
            continue
        for node in ast.walk(mod.tree):
            var: Optional[str] = None
            if isinstance(node, ast.Subscript):
                base = dotted_name(node.value)
                if base in ("os.environ",) and isinstance(node.slice, ast.Constant):
                    v = node.slice.value
                    if isinstance(v, str) and v.startswith("P2PFL_TPU_"):
                        var = v
            elif isinstance(node, ast.Call):
                cname = dotted_name(node.func)
                if cname in ("os.environ.get", "os.getenv", "environ.get", "getenv"):
                    if node.args and isinstance(node.args[0], ast.Constant):
                        v = node.args[0].value
                        if isinstance(v, str) and v.startswith("P2PFL_TPU_"):
                            var = v
            if var is not None:
                out.append(
                    Finding(
                        "C5",
                        f"C5:env:{mod.rel}:{var}",
                        mod.rel,
                        node.lineno,
                        f"direct read of {var} bypasses config.py's validated "
                        "fail-fast env layer — add a Settings field instead",
                    )
                )
    return out


_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _drift_metrics(index: ProjectIndex, root: Path) -> List[Finding]:
    """Metric names emitted in code must appear in docs OR tests — an
    undocumented, untested series silently renames/vanishes on refactor and
    every dashboard watching it flatlines."""
    names: Dict[str, Tuple[str, int]] = {}
    for mod in index.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = dotted_name(node.func) or ""
            if cname.rsplit(".", 1)[-1] not in _METRIC_FACTORIES:
                continue
            if not cname.startswith(("REGISTRY.", "registry.")):
                continue
            if node.args and isinstance(node.args[0], ast.Constant):
                v = node.args[0].value
                if isinstance(v, str) and v.startswith("p2pfl_"):
                    names.setdefault(v, (mod.rel, node.lineno))
    if not names:
        return []
    corpus = _reference_corpus(root)
    out: List[Finding] = []
    for name, (rel, line) in sorted(names.items()):
        if name in corpus:
            continue
        out.append(
            Finding(
                "C5",
                f"C5:metric:{name}",
                rel,
                line,
                f"metric {name} is emitted but appears in neither docs/ nor "
                "tests/ — document it (docs/components/) or assert it in a "
                "test before a refactor silently drops the series",
            )
        )
    return out


def _reference_corpus(root: Path) -> str:
    """Concatenated docs + tests text used for metric-name presence."""
    chunks: List[str] = []
    for pattern, base in (("*.md", root), ("**/*.md", root / "docs"), ("**/*.py", root / "tests")):
        if not base.exists():
            continue
        for p in sorted(base.glob(pattern)):
            if "analysis_fixtures" in p.parts:
                continue  # seeded-defect fixtures must not self-document
            try:
                chunks.append(p.read_text(encoding="utf-8", errors="replace"))
            except OSError:
                continue
    return "\n".join(chunks)


def _drift_commands(index: ProjectIndex) -> List[Finding]:
    """Command names sent must be handled and vice versa. Dispatch is shared
    by both transports (CommandDispatcher behind CommunicationProtocol), so
    one registration covers gRPC and in-memory — but a command class that is
    never instantiated, or a name sent with no definition, is dead wire
    surface either way."""
    # class name -> (cmd name, rel, line); includes nested classes
    defined: Dict[str, Tuple[str, str, int]] = {}
    consts: Dict[Tuple[str, str], str] = {}
    for mod in index.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
                v = node.value.value
                if isinstance(v, str):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            consts[(mod.rel, t.id)] = v
    for mod in index.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = [dotted_name(b) or "" for b in node.bases]
            if not (
                node.name.endswith("Command")
                or any(b.rsplit(".", 1)[-1] == "Command" for b in bases)
            ):
                continue
            for item in node.body:
                if (
                    isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and item.name == "get_name"
                ):
                    for stmt in ast.walk(item):
                        if isinstance(stmt, ast.Return) and stmt.value is not None:
                            if isinstance(stmt.value, ast.Constant) and isinstance(
                                stmt.value.value, str
                            ):
                                defined[node.name] = (
                                    stmt.value.value, mod.rel, node.lineno,
                                )
                            elif isinstance(stmt.value, ast.Name):
                                v = consts.get((mod.rel, stmt.value.id))
                                if v:
                                    defined[node.name] = (v, mod.rel, node.lineno)
    defined_names = {v[0] for v in defined.values()}

    instantiated: Set[str] = set()
    sent: Dict[str, Tuple[str, int]] = {}
    for mod in index.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = dotted_name(node.func) or ""
            leaf = cname.rsplit(".", 1)[-1]
            if leaf in defined:
                instantiated.add(leaf)
            if leaf in ("build_msg", "build_weights") and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    sent.setdefault(first.value, (mod.rel, node.lineno))
                elif isinstance(first, ast.Call):
                    gname = dotted_name(first.func) or ""
                    if gname.endswith(".get_name"):
                        cls = gname.rsplit(".", 2)[-2]
                        if cls in defined:
                            sent.setdefault(defined[cls][0], (mod.rel, node.lineno))

    out: List[Finding] = []
    for cls, (cmd, rel, line) in sorted(defined.items()):
        if cls not in instantiated:
            out.append(
                Finding(
                    "C5",
                    f"C5:cmd-unregistered:{cmd}",
                    rel,
                    line,
                    f"command class {cls} (name {cmd!r}) is defined but never "
                    "instantiated/registered on the dispatcher — inbound "
                    f"{cmd!r} frames would be dropped as unknown on both "
                    "transports",
                )
            )
    for cmd, (rel, line) in sorted(sent.items()):
        if cmd not in defined_names:
            out.append(
                Finding(
                    "C5",
                    f"C5:cmd-unhandled:{cmd}",
                    rel,
                    line,
                    f"command {cmd!r} is sent (build_msg/build_weights) but no "
                    "Command class defines it — receivers on either transport "
                    "drop it as unknown",
                )
            )
    return out


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

ALL_CHECKERS: Dict[str, Callable[[ProjectIndex, Path], List[Finding]]] = {
    "C1": check_lock_order,
    "C2": check_blocking_under_lock,
    "C3": check_unguarded_writes,
    "C4": check_jit_purity,
    "C5": check_drift,
}


def run_checkers(
    root: Path,
    subdirs: Sequence[str] = ("p2pfl_tpu",),
    checks: Optional[Sequence[str]] = None,
) -> List[Finding]:
    index = ProjectIndex(root, subdirs)
    findings: List[Finding] = []
    for name in checks or sorted(ALL_CHECKERS):
        findings.extend(ALL_CHECKERS[name](index, root))
    findings.sort(key=lambda f: (f.path, f.line, f.key))
    return findings
