"""Shared AST machinery for the static checkers.

The interesting problem is LOCK IDENTITY: ``with self._lock:`` appears in a
dozen classes and must not conflate ``Gossiper._pending_lock`` with
``Aggregator._lock``. A definition pass collects every attribute/name
assigned from a ``threading.Lock()`` / ``RLock()`` / ``Condition()`` /
``Semaphore()`` call, keyed by the defining class (or module); acquisition
sites then resolve ``self.X`` against the enclosing class first, fall back
to a unique cross-class match, and keep honestly-ambiguous names as ``?.X``
so a checker can choose to skip them.

Everything here is pure stdlib ``ast`` — the analysis must run in CI without
importing the package under analysis (imports pull in jax)."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

#: threading factories whose result is a lock-like primitive worth ordering.
LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

#: directories never scanned (generated code, caches).
SKIP_PARTS = {"__pycache__", ".git"}
SKIP_FILES = {"node_pb2.py"}  # generated protobuf


@dataclass(frozen=True)
class Finding:
    """One checker hit.

    ``key`` is the stable suppression identity: checker + file + scope +
    detail, deliberately WITHOUT line numbers so refactors that move code
    don't churn the baseline."""

    checker: str  # "C1".."C5"
    key: str
    path: str  # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"[{self.checker}] {self.path}:{self.line}: {self.message}"


@dataclass
class LockDef:
    """One lock primitive definition site."""

    lock_id: str  # "ClassName.attr" or "module:<relpath>.NAME"
    kind: str  # Lock | RLock | Condition | Semaphore | BoundedSemaphore
    path: str
    line: int


@dataclass
class FuncInfo:
    """One function/method with what the lock checkers need."""

    qualname: str  # "relpath::Class.method" or "relpath::func"
    name: str
    class_name: Optional[str]
    path: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    #: lock ids acquired lexically anywhere in the body (with-statements).
    acquires: Set[str] = field(default_factory=set)


class Module:
    def __init__(self, path: Path, rel: str) -> None:
        self.path = path
        self.rel = rel
        self.source = path.read_text(encoding="utf-8", errors="replace")
        self.tree = ast.parse(self.source, filename=rel)
        self.lines = self.source.splitlines()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def iter_py_files(root: Path, subdirs: Sequence[str]) -> Iterator[Tuple[Path, str]]:
    for sub in subdirs:
        base = root / sub
        if base.is_file():
            yield base, str(base.relative_to(root))
            continue
        for p in sorted(base.rglob("*.py")):
            if any(part in SKIP_PARTS for part in p.parts) or p.name in SKIP_FILES:
                continue
            yield p, str(p.relative_to(root))


def _call_name(node: ast.AST) -> Optional[str]:
    """'threading.Lock' for threading.Lock(...), 'Lock' for Lock(...)."""
    if not isinstance(node, ast.Call):
        return None
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for an attribute chain of plain names; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lock_factory_kind(call: ast.AST) -> Optional[str]:
    name = _call_name(call)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    if leaf in LOCK_FACTORIES and (
        "." not in name or name.startswith("threading.") or name.endswith(f".{leaf}")
    ):
        # Accept threading.Lock(), Lock(), mp.RLock() — anything whose leaf
        # is a known factory. InstrumentedLock etc. (analysis.runtime) is
        # deliberately excluded: wrapping is a runtime concern.
        return leaf
    return None


class ProjectIndex:
    """Cross-module index: lock definitions, classes, functions, Thread
    entry points, Command classes — built once, consumed by every checker."""

    def __init__(self, root: Path, subdirs: Sequence[str] = ("p2pfl_tpu",)) -> None:
        self.root = root
        self.modules: List[Module] = []
        for path, rel in iter_py_files(root, subdirs):
            try:
                self.modules.append(Module(path, rel))
            except (SyntaxError, OSError):
                continue
        # lock attr name -> [LockDef] (across classes; for unique-match fallback)
        self.locks_by_attr: Dict[str, List[LockDef]] = {}
        # (class_name, attr) -> LockDef
        self.locks_by_class: Dict[Tuple[str, str], LockDef] = {}
        # module-level: (rel, name) -> LockDef
        self.locks_module: Dict[Tuple[str, str], LockDef] = {}
        # lock_id -> LockDef
        self.lock_defs: Dict[str, LockDef] = {}
        # method/function name -> [FuncInfo]
        self.funcs_by_name: Dict[str, List[FuncInfo]] = {}
        # qualname -> FuncInfo
        self.funcs: Dict[str, FuncInfo] = {}
        # class name -> {method name -> FuncInfo}
        self.classes: Dict[str, Dict[str, FuncInfo]] = {}
        # class name -> list of base-class dotted names
        self.class_bases: Dict[str, List[str]] = {}
        self._build()

    # --- construction -------------------------------------------------------

    def _build(self) -> None:
        for mod in self.modules:
            self._index_module(mod)
        for info in self.funcs.values():
            info.acquires = self._lexical_acquires(info)

    def _index_module(self, mod: Module) -> None:
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self._index_class(mod, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(mod, node, None)
            elif isinstance(node, ast.Assign):
                kind = _lock_factory_kind(node.value)
                if kind:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            d = LockDef(
                                f"module:{mod.rel}.{tgt.id}", kind, mod.rel, node.lineno
                            )
                            self.locks_module[(mod.rel, tgt.id)] = d
                            self.lock_defs[d.lock_id] = d
                            self.locks_by_attr.setdefault(tgt.id, []).append(d)

    def _index_class(self, mod: Module, cls: ast.ClassDef) -> None:
        methods = self.classes.setdefault(cls.name, {})
        self.class_bases[cls.name] = [
            b for b in (dotted_name(base) for base in cls.bases) if b
        ]
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = self._add_func(mod, node, cls.name)
                methods[node.name] = info
                # lock definitions: self.X = threading.Lock() anywhere in a
                # method (typically __init__)
                for stmt in ast.walk(node):
                    if isinstance(stmt, ast.Assign):
                        kind = _lock_factory_kind(stmt.value)
                        if not kind:
                            continue
                        for tgt in stmt.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                d = LockDef(
                                    f"{cls.name}.{tgt.attr}", kind, mod.rel, stmt.lineno
                                )
                                self.locks_by_class[(cls.name, tgt.attr)] = d
                                self.lock_defs[d.lock_id] = d
                                self.locks_by_attr.setdefault(tgt.attr, []).append(d)

    def _add_func(
        self, mod: Module, node: ast.AST, class_name: Optional[str]
    ) -> FuncInfo:
        name = node.name  # type: ignore[attr-defined]
        qual = f"{mod.rel}::{class_name + '.' if class_name else ''}{name}"
        info = FuncInfo(qual, name, class_name, mod.rel, node)
        self.funcs[qual] = info
        self.funcs_by_name.setdefault(name, []).append(info)
        return info

    # --- lock resolution ----------------------------------------------------

    def resolve_lock_expr(
        self, expr: ast.AST, class_name: Optional[str], rel: str
    ) -> Optional[str]:
        """Lock id for a with-item context expression, or None if it isn't a
        lock. ``with foo():`` (Call) is never a lock acquisition — context
        managers like ``Settings.overridden()`` / tracer spans pass through
        here constantly."""
        if isinstance(expr, ast.Call):
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" and class_name:
                hit = self.locks_by_class.get((class_name, attr))
                if hit:
                    return hit.lock_id
                # inherited lock: single definition anywhere wins
            defs = self.locks_by_attr.get(attr, [])
            if len(defs) == 1:
                return defs[0].lock_id
            if defs:
                return f"?.{attr}"  # ambiguous: same attr name, many classes
            if "lock" in attr.lower():
                return f"?.{attr}"  # looks like a lock we never saw defined
            return None
        if isinstance(expr, ast.Name):
            hit = self.locks_module.get((rel, expr.id))
            if hit:
                return hit.lock_id
            defs = self.locks_by_attr.get(expr.id, [])
            if len(defs) == 1:
                return defs[0].lock_id
            if "lock" in expr.id.lower():
                return f"?.{expr.id}"
            return None
        return None

    def lock_kind(self, lock_id: str) -> Optional[str]:
        d = self.lock_defs.get(lock_id)
        return d.kind if d else None

    def _lexical_acquires(self, info: FuncInfo) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(info.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self.resolve_lock_expr(
                        item.context_expr, info.class_name, info.path
                    )
                    if lid:
                        out.add(lid)
        return out

    # --- callee resolution (one hop) ----------------------------------------

    def resolve_callees(
        self, call: ast.Call, class_name: Optional[str], rel: str
    ) -> List[FuncInfo]:
        """Best-effort in-tree targets of a call: ``self.m()`` prefers the
        enclosing class (then a unique cross-class match), ``obj.m()`` needs
        a unique cross-class match, bare ``f()`` a same-module function."""
        fn = call.func
        if isinstance(fn, ast.Attribute):
            name = fn.attr
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" and class_name:
                own = self.classes.get(class_name, {}).get(name)
                if own:
                    return [own]
                # may be inherited — unique global match is good enough
            candidates = self.funcs_by_name.get(name, [])
            methods = [c for c in candidates if c.class_name]
            if len(methods) == 1:
                return methods
            return []
        if isinstance(fn, ast.Name):
            candidates = [
                c
                for c in self.funcs_by_name.get(fn.id, [])
                if c.path == rel and c.class_name is None
            ]
            return candidates if len(candidates) == 1 else []
        return []

    def module_for(self, rel: str) -> Optional[Module]:
        for m in self.modules:
            if m.rel == rel:
                return m
        return None


def has_inline_waiver(mod: Module, lineno: int, tag: str) -> bool:
    """True when the source line (or the line above) carries an explicit
    ``# <tag>: reason`` annotation — the in-code suppression channel for
    findings that are understood and safe (the baseline file is for the
    rest)."""
    for ln in (lineno, lineno - 1):
        if tag in mod.line_text(ln):
            return True
    return False
