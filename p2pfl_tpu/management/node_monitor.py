"""Resource monitor thread (reference management/node_monitor.py:31-86):
psutil cpu%, ram%, net MBps reported each RESOURCE_MONITOR_PERIOD.

Without psutil the monitor is inert: ``available`` is False so callers and
tests can tell monitoring is off, and the first ``start()`` logs a one-time
warning instead of silently doing nothing."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

try:
    import psutil
except ImportError:  # pragma: no cover
    psutil = None

from p2pfl_tpu.config import Settings

log = logging.getLogger("p2pfl_tpu")


class NodeMonitor:
    #: False when psutil is missing — no system metrics will be reported.
    available: bool = psutil is not None

    _warned_unavailable = False  # process-wide: warn once, not per node

    def __init__(self, node_addr: str, report_fn: Callable[[str, str, float], None]) -> None:
        self._node = node_addr
        self._report = report_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if psutil is None:
            if not NodeMonitor._warned_unavailable:
                NodeMonitor._warned_unavailable = True
                log.warning(
                    "psutil is not installed — system resource monitoring "
                    "(cpu/ram/net gauges) is disabled for this process"
                )
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"monitor-{self._node}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    def _run(self) -> None:
        last_net = psutil.net_io_counters()
        last_t = time.time()
        while not self._stop.wait(Settings.RESOURCE_MONITOR_PERIOD):
            try:
                self._report(self._node, "cpu_percent", psutil.cpu_percent(interval=None))
                self._report(self._node, "ram_percent", psutil.virtual_memory().percent)
                net = psutil.net_io_counters()
                now = time.time()
                dt = max(now - last_t, 1e-6)
                self._report(
                    self._node, "net_in_mbps", (net.bytes_recv - last_net.bytes_recv) / dt / 1e6
                )
                self._report(
                    self._node, "net_out_mbps", (net.bytes_sent - last_net.bytes_sent) / dt / 1e6
                )
                last_net, last_t = net, now
            except Exception:
                pass
