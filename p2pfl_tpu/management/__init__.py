"""Management: logging, metrics, monitoring, telemetry."""
