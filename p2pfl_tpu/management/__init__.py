"""Management: logging, metrics, monitoring, telemetry, checkpointing."""

__all__ = [
    "FLCheckpointer",
    "NodeJournal",
    "attach_node_checkpointing",
    "attach_node_journal",
]


def __getattr__(name: str):
    # Lazy: checkpoint.py imports orbax, which must not become an
    # import-time dependency of the logger/Node/CLI paths.
    if name in __all__:
        from p2pfl_tpu.management import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
