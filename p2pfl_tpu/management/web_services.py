"""REST telemetry client.

Parity with reference management/p2pfl_web_services.py:58-268 (POST /node,
/node-log, /node-metric/local, /node-metric/global, /node-metric/system).
Uses stdlib urllib (no extra deps); failures are swallowed after marking the
sink broken, so telemetry can never take a node down.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Any, Dict


class WebServices:
    def __init__(self, url: str, key: str, timeout: float = 5.0) -> None:
        self._url = url.rstrip("/")
        self._key = key
        self._timeout = timeout
        self._broken = False

    def _post(self, path: str, body: Dict[str, Any]) -> None:
        if self._broken:
            return
        try:
            req = urllib.request.Request(
                self._url + path,
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json", "x-api-key": self._key},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self._timeout):
                pass
        except Exception as exc:
            self._broken = True
            logging.getLogger("p2pfl_tpu").warning("web telemetry disabled: %s", exc)

    def register_node(self, node: str) -> None:
        self._post("/node", {"address": node})

    def unregister_node(self, node: str) -> None:
        self._post("/node-remove", {"address": node})

    def send_log(self, node: str, level: str, message: str) -> None:
        self._post("/node-log", {"address": node, "level": level, "message": message})

    def send_local_metric(
        self, node: str, exp: str, metric: str, value: float, round: int, step: int
    ) -> None:
        self._post(
            "/node-metric/local",
            {"address": node, "experiment": exp, "metric": metric, "value": value,
             "round": round, "step": step},
        )

    def send_global_metric(
        self, node: str, exp: str, metric: str, value: float, round: int
    ) -> None:
        self._post(
            "/node-metric/global",
            {"address": node, "experiment": exp, "metric": metric, "value": value, "round": round},
        )

    def send_system_metric(self, node: str, metric: str, value: float) -> None:
        self._post("/node-metric/system", {"address": node, "metric": metric, "value": value})
