"""REST telemetry client.

Parity with reference management/p2pfl_web_services.py:58-268 (POST /node,
/node-log, /node-metric/local, /node-metric/global, /node-metric/system).
Uses stdlib urllib (no extra deps); failures are swallowed — telemetry can
never take a node down.

Failure handling: a *recoverable* breaker, not the old permanently-sticky
``_broken`` flag (one transient POST failure used to disable web telemetry
for the process lifetime). After ``fail_threshold`` consecutive failures the
breaker opens for an exponentially growing window (``backoff_base`` up to
``backoff_max``); once the window expires the next call re-probes, and a
single re-probe failure re-opens the window doubled. Every suppressed or
failed POST is counted in the telemetry registry
(``p2pfl_web_telemetry_drops_total``), so operators can see how much web
telemetry was lost and why.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Any, Dict

from p2pfl_tpu.telemetry import REGISTRY

log = logging.getLogger("p2pfl_tpu")

_DROPS = REGISTRY.counter(
    "p2pfl_web_telemetry_drops_total",
    "Web telemetry POSTs lost, by reason (post_failed | breaker_open)",
    labels=("reason",),
)


class WebServices:
    def __init__(
        self,
        url: str,
        key: str,
        timeout: float = 5.0,
        fail_threshold: int = 3,
        backoff_base: float = 1.0,
        backoff_max: float = 300.0,
    ) -> None:
        self._url = url.rstrip("/")
        self._key = key
        self._timeout = timeout
        self._fail_threshold = max(1, int(fail_threshold))
        self._backoff_base = float(backoff_base)
        self._backoff_max = float(backoff_max)
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._breaker_trips = 0  # consecutive open->reprobe->fail cycles
        self._blocked_until = 0.0  # monotonic deadline of the open window

    @property
    def broken(self) -> bool:
        """True while the breaker window is open (calls are dropped)."""
        with self._lock:
            return time.monotonic() < self._blocked_until

    def _post(self, path: str, body: Dict[str, Any]) -> None:
        with self._lock:
            if time.monotonic() < self._blocked_until:
                _DROPS.labels("breaker_open").inc()
                return
        try:
            req = urllib.request.Request(
                self._url + path,
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json", "x-api-key": self._key},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self._timeout):
                pass
        except Exception as exc:
            self._record_failure(exc)
        else:
            with self._lock:
                self._consecutive_failures = 0
                self._breaker_trips = 0

    def _record_failure(self, exc: Exception) -> None:
        _DROPS.labels("post_failed").inc()
        with self._lock:
            self._consecutive_failures += 1
            # After the first trip a single failed re-probe re-opens the
            # window — re-probing is one attempt, not a fresh threshold.
            threshold = 1 if self._breaker_trips else self._fail_threshold
            if self._consecutive_failures < threshold:
                return
            self._breaker_trips += 1
            self._consecutive_failures = 0
            backoff = min(
                self._backoff_base * (2 ** (self._breaker_trips - 1)),
                self._backoff_max,
            )
            self._blocked_until = time.monotonic() + backoff
        log.warning(
            "web telemetry paused for %.1fs after failure: %s", backoff, exc
        )

    def register_node(self, node: str) -> None:
        self._post("/node", {"address": node})

    def unregister_node(self, node: str) -> None:
        self._post("/node-remove", {"address": node})

    def send_log(self, node: str, level: str, message: str) -> None:
        self._post("/node-log", {"address": node, "level": level, "message": message})

    def send_local_metric(
        self, node: str, exp: str, metric: str, value: float, round: int, step: int
    ) -> None:
        self._post(
            "/node-metric/local",
            {"address": node, "experiment": exp, "metric": metric, "value": value,
             "round": round, "step": step},
        )

    def send_global_metric(
        self, node: str, exp: str, metric: str, value: float, round: int
    ) -> None:
        self._post(
            "/node-metric/global",
            {"address": node, "experiment": exp, "metric": metric, "value": value, "round": round},
        )

    def send_system_metric(self, node: str, metric: str, value: float) -> None:
        self._post("/node-metric/system", {"address": node, "metric": metric, "value": value})
