"""Two-level metric storage.

Parity with reference management/metric_storage.py:30-251:
* local (step-wise) metrics: exp -> round -> node -> metric -> [(step, value)]
* global (round-wise) metrics: exp -> node -> metric -> [(round, value)]
Both lock-guarded.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

LocalMetrics = Dict[str, Dict[int, Dict[str, Dict[str, List[Tuple[int, float]]]]]]
GlobalMetrics = Dict[str, Dict[str, Dict[str, List[Tuple[int, float]]]]]


class LocalMetricStorage:
    """exp -> round -> node -> metric -> [(step, value)]"""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._store: LocalMetrics = {}

    def add(self, exp: str, round: int, node: str, metric: str, value: float, step: int = 0) -> None:
        with self._lock:
            self._store.setdefault(exp, {}).setdefault(round, {}).setdefault(node, {}).setdefault(
                metric, []
            ).append((step, float(value)))

    def get_all(self) -> LocalMetrics:
        with self._lock:
            return {
                e: {r: {n: {m: list(v) for m, v in ms.items()} for n, ms in ns.items()} for r, ns in rs.items()}
                for e, rs in self._store.items()
            }

    def get(self, exp: str) -> Dict:
        return self.get_all().get(exp, {})


class GlobalMetricStorage:
    """exp -> node -> metric -> [(round, value)]"""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._store: GlobalMetrics = {}

    def add(self, exp: str, node: str, metric: str, value: float, round: int) -> None:
        with self._lock:
            self._store.setdefault(exp, {}).setdefault(node, {}).setdefault(metric, []).append(
                (round, float(value))
            )

    def get_all(self) -> GlobalMetrics:
        with self._lock:
            return {
                e: {n: {m: list(v) for m, v in ms.items()} for n, ms in ns.items()}
                for e, ns in self._store.items()
            }

    def get(self, exp: str) -> Dict:
        return self.get_all().get(exp, {})
