"""Checkpoint / resume subsystem (orbax-backed).

The reference has NO checkpointing — Lightning checkpoints are explicitly
disabled (reference lightning_learner.py:66) and model state only survives
inside the gossip protocol (SURVEY.md §5). This module is the TPU build's
upgrade: async orbax snapshots of

* a single :class:`~p2pfl_tpu.models.model_handle.ModelHandle` (federation
  mode — one node's model + contributor metadata per round), and
* an entire :class:`~p2pfl_tpu.parallel.simulation.MeshSimulation` population
  (stacked params + optimizer state + round counter), restored with the
  original shardings so a resumed run stays on-mesh.

Orbax writes from device memory (no host staging of the whole tree at once)
and keeps the last ``max_to_keep`` steps.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

Pytree = Any


class FLCheckpointer:
    """Round-indexed checkpoint store.

    Args:
        directory: checkpoint root (created if missing; made absolute —
            orbax requires absolute paths).
        max_to_keep: retained snapshots (oldest pruned).
        save_interval: only save when ``round % save_interval == 0``.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval: int = 1,
    ) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.save_interval = max(1, int(save_interval))
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=True,
            ),
        )

    # --- generic pytree + metadata ------------------------------------------

    def save(self, step: int, state: Pytree, meta: Optional[Dict[str, Any]] = None) -> bool:
        """Save ``state`` (pytree of arrays) + JSON-able ``meta`` at ``step``.

        Returns False (and skips) when the step is off the save interval.
        """
        if step % self.save_interval != 0:
            return False
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta or {}),
            ),
        )
        return True

    def restore(self, template: Pytree, step: Optional[int] = None):
        """Restore (state, meta) at ``step`` (default: latest).

        ``template`` supplies structure/shapes/shardings: device arrays in it
        are restored onto their existing shardings (a resumed mesh run stays
        sharded over the same mesh).
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                meta=ocp.args.JsonRestore(),
            ),
        )

        # Orbax restores some leaves (e.g. replicated scalars) onto a single
        # device; re-place every array onto its template sharding so a
        # resumed mesh computation sees consistent placements.
        def replace(t, r):
            if isinstance(t, jax.Array) and isinstance(r, (jax.Array, np.ndarray)):
                return jax.device_put(r, t.sharding)
            return r

        state = jax.tree.map(replace, template, restored["state"])
        return state, dict(restored["meta"] or {})

    def restore_meta(self, step: Optional[int] = None) -> dict:
        """Restore ONLY the JSON meta record at ``step`` (default: latest).

        Lets callers validate configuration pins (optimizer rule, DP
        parameters) BEFORE committing to the heavy structural restore — a
        mismatched template would otherwise surface as an opaque pytree
        structure error instead of the pin's explanatory ValueError.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return dict(restored["meta"] or {})

    # --- ModelHandle convenience --------------------------------------------

    def save_model(self, step: int, model) -> bool:
        """Snapshot a ModelHandle: params + federation metadata."""
        meta = {
            "contributors": list(model.contributors),
            "num_samples": int(model.num_samples),
            "additional_info": _jsonable(model.additional_info),
        }
        return self.save(step, model.params, meta)

    def restore_model(self, template_model, step: Optional[int] = None):
        """Restore into a copy of ``template_model`` (same apply_fn/def)."""
        params, meta = self.restore(template_model.params, step)
        out = template_model.build_copy(params=params)
        out.contributors = list(meta.get("contributors", []))
        out.num_samples = int(meta.get("num_samples", 1))
        out.additional_info = dict(meta.get("additional_info", {}))
        return out

    # --- bookkeeping ---------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> List[int]:
        return list(self._mngr.all_steps())

    def wait(self) -> None:
        """Block until in-flight async saves land."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self) -> "FLCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def attach_node_checkpointing(node, checkpointer: FLCheckpointer) -> None:
    """Federation mode: snapshot the node's model at every round end.

    Hooks the node's ``round_end_hooks`` (fired by RoundFinishedStage via
    ``log_round_finished``); the saved step is the just-finished round.
    """

    def hook(n) -> None:
        r = n.state.round
        finished = (r - 1) if r is not None else 0
        checkpointer.save_model(max(finished, 0), n.learner.get_model())

    node.round_end_hooks.append(hook)


def _jsonable(d: Dict[str, Any]) -> Dict[str, Any]:
    """Drop/convert values JSON can't carry (arrays -> lists)."""
    out: Dict[str, Any] = {}
    for k, v in d.items():
        if isinstance(v, np.generic):  # np.float32(..) etc. — not a Python float
            out[k] = v.item()
        elif isinstance(v, (np.ndarray, jax.Array)):
            out[k] = np.asarray(v).tolist()
        elif isinstance(v, (str, int, float, bool, list, dict, type(None))):
            out[k] = v
    return out
