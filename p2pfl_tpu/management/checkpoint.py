"""Checkpoint / resume subsystem (orbax-backed).

The reference has NO checkpointing — Lightning checkpoints are explicitly
disabled (reference lightning_learner.py:66) and model state only survives
inside the gossip protocol (SURVEY.md §5). This module is the TPU build's
upgrade: async orbax snapshots of

* a single :class:`~p2pfl_tpu.models.model_handle.ModelHandle` (federation
  mode — one node's model + contributor metadata per round), and
* an entire :class:`~p2pfl_tpu.parallel.simulation.MeshSimulation` population
  (stacked params + optimizer state + round counter), restored with the
  original shardings so a resumed run stays on-mesh.

Orbax writes from device memory (no host staging of the whole tree at once)
and keeps the last ``max_to_keep`` steps.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

log = logging.getLogger("p2pfl_tpu")

Pytree = Any

from p2pfl_tpu.telemetry import REGISTRY  # noqa: E402  (after orbax guard docs)

_JOURNAL_SAVES = REGISTRY.counter(
    "p2pfl_recovery_journal_saves_total",
    "Write-ahead recovery-journal snapshots committed to disk",
    labels=("node",),
)

#: Orbax's per-step commit marker: written as the final act of a save (the
#: step directory itself lands via write-to-temp + atomic rename). A step
#: directory without it is TORN — a crash interrupted the save — and must be
#: invisible to ``latest_step``/``restore`` instead of poisoning recovery.
_COMMIT_MARKER = "_CHECKPOINT_METADATA"


def _fsync_dir(path: str) -> None:
    """fsync a directory fd so a completed atomic rename survives power loss
    (the rename itself is atomic but not durable until the directory entry
    is flushed). Best-effort: not every filesystem supports dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class FLCheckpointer:
    """Round-indexed checkpoint store.

    Args:
        directory: checkpoint root (created if missing; made absolute —
            orbax requires absolute paths).
        max_to_keep: retained snapshots (oldest pruned).
        save_interval: only save when ``round % save_interval == 0``.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval: int = 1,
    ) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.save_interval = max(1, int(save_interval))
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=True,
                # A crash mid-save leaves a tmp-staged step; sweep stale tmp
                # directories at (re)open so a restarted process never
                # accumulates them.
                cleanup_tmp_directories=True,
            ),
        )

    # --- crash safety --------------------------------------------------------

    def _step_complete(self, step: int) -> bool:
        """A step is trustworthy only once its commit marker exists. Orbax
        stages every save in a temp directory and atomically renames it into
        place (write-to-temp + rename), writing the marker as the final act
        — so a torn/partial step directory (crash mid-save, or a bare
        directory a crashed rename left behind) is detectable and must be
        SKIPPED, never restored from."""
        d = os.path.join(self.directory, str(step))
        return os.path.isdir(d) and os.path.exists(os.path.join(d, _COMMIT_MARKER))

    # --- generic pytree + metadata ------------------------------------------

    def save(self, step: int, state: Pytree, meta: Optional[Dict[str, Any]] = None) -> bool:
        """Save ``state`` (pytree of arrays) + JSON-able ``meta`` at ``step``.

        Crash-safe: the save is staged in a temp directory and atomically
        renamed into place with a trailing commit marker; :meth:`wait`
        additionally fsyncs the directory entries so the rename is durable.
        A crash at ANY point mid-save leaves either no step directory or a
        torn one — and torn steps are skipped by ``restore``/``latest_step``
        instead of raising, so a crash mid-save can never poison recovery.

        Returns False (and skips) when the step is off the save interval.
        """
        if step % self.save_interval != 0:
            return False
        self._drain_finalize()
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta or {}),
            ),
        )
        return True

    def _drain_finalize(self) -> None:
        """Join any in-flight async save before issuing the next one.

        Orbax clears its finalize-thread handle only when ``wait`` is called
        from the THREAD that requested the save — but a crash-restarted node
        journals from a fresh workflow thread, and the handle the dead
        thread left behind trips ``save``'s internal assertion forever.
        After the join returns, clear the dead handle ourselves (guarded,
        best-effort: private attrs of the pinned orbax version)."""
        try:
            self._mngr.wait_until_finished()
            lock = getattr(self._mngr, "_finalize_thread_lock", None)
            if lock is None:
                return
            with lock:
                ft = getattr(self._mngr, "_finalize_thread", None)
                if ft is not None and not ft.is_alive():
                    self._mngr._finalize_thread = None
        except Exception:  # noqa: BLE001 — degrade to orbax's own behavior
            log.debug("checkpoint finalize drain failed", exc_info=True)

    def restore(self, template: Pytree, step: Optional[int] = None):
        """Restore (state, meta) at ``step`` (default: newest restorable).

        ``template`` supplies structure/shapes/shardings: device arrays in it
        are restored onto their existing shardings (a resumed mesh run stays
        sharded over the same mesh).

        With ``step=None``, torn or unreadable snapshots are skipped: the
        restore walks complete steps newest-first and returns the first one
        that loads, raising :class:`FileNotFoundError` only when none does.
        """
        if step is None:
            candidates = sorted(self.all_steps(), reverse=True)
            if not candidates:
                raise FileNotFoundError(f"no checkpoints under {self.directory}")
            last_exc: Optional[Exception] = None
            for s in candidates:
                try:
                    return self._restore_step(template, s)
                except Exception as exc:  # noqa: BLE001 — torn step: try older
                    last_exc = exc
                    log.warning(
                        "checkpoint step %s under %s unreadable (%s) — "
                        "falling back to the previous snapshot",
                        s, self.directory, exc,
                    )
            raise FileNotFoundError(
                f"no restorable checkpoint under {self.directory} "
                f"(last error: {last_exc})"
            )
        if not self._step_complete(step):
            raise FileNotFoundError(
                f"checkpoint step {step} under {self.directory} is torn/absent"
            )
        return self._restore_step(template, step)

    def _restore_step(self, template: Pytree, step: int):
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                meta=ocp.args.JsonRestore(),
            ),
        )

        # Orbax restores some leaves (e.g. replicated scalars) onto a single
        # device; re-place every array onto its template sharding so a
        # resumed mesh computation sees consistent placements.
        def replace(t, r):
            if isinstance(t, jax.Array) and isinstance(r, (jax.Array, np.ndarray)):
                return jax.device_put(r, t.sharding)
            return r

        state = jax.tree.map(replace, template, restored["state"])
        return state, dict(restored["meta"] or {})

    def restore_meta(self, step: Optional[int] = None) -> dict:
        """Restore ONLY the JSON meta record at ``step`` (default: newest
        restorable — torn steps are skipped like :meth:`restore` does).

        Lets callers validate configuration pins (optimizer rule, DP
        parameters) BEFORE committing to the heavy structural restore — a
        mismatched template would otherwise surface as an opaque pytree
        structure error instead of the pin's explanatory ValueError.
        """
        if step is None:
            for s in sorted(self.all_steps(), reverse=True):
                try:
                    restored = self._mngr.restore(
                        s, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
                    )
                    return dict(restored["meta"] or {})
                except Exception as exc:  # noqa: BLE001 — torn step: try older
                    log.warning(
                        "checkpoint meta at step %s under %s unreadable (%s) "
                        "— falling back", s, self.directory, exc,
                    )
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if not self._step_complete(step):
            raise FileNotFoundError(
                f"checkpoint step {step} under {self.directory} is torn/absent"
            )
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return dict(restored["meta"] or {})

    def restore_coherent(
        self,
        template: Pytree,
        step: Optional[int] = None,
        check_meta=None,
    ):
        """Restore ``(state, meta)`` with BOTH drawn from the SAME step.

        :meth:`restore` and :meth:`restore_meta` each walk complete steps
        newest-first INDEPENDENTLY — a step whose small JSON meta record
        survives while its state files are torn (a kill mid-``save_to`` can
        leave exactly that) would hand a caller meta from step A and state
        from step B: a poisoned resume whose cursor and weights disagree.
        This walk tries meta THEN state for one step and falls back to the
        next-older step on ANY read failure, so engines resume coherently
        or not at all.

        ``check_meta(meta)``, when given, runs between the meta and state
        reads of each candidate step; exceptions it raises PROPAGATE —
        configuration-pin mismatches are a caller error, never a torn
        snapshot to skip.
        """
        if step is not None:
            meta = self.restore_meta(step)
            if check_meta is not None:
                check_meta(meta)
            state, _ = self.restore(template, step)
            return state, meta
        candidates = sorted(self.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        last_exc: Optional[Exception] = None
        for s in candidates:
            try:
                meta = self.restore_meta(s)
            except Exception as exc:  # noqa: BLE001 — torn meta: try older
                last_exc = exc
                log.warning(
                    "checkpoint meta at step %s under %s unreadable (%s) — "
                    "falling back to the previous snapshot",
                    s, self.directory, exc,
                )
                continue
            if check_meta is not None:
                check_meta(meta)
            try:
                state, _ = self.restore(template, s)
            except Exception as exc:  # noqa: BLE001 — torn state: try older
                last_exc = exc
                log.warning(
                    "checkpoint state at step %s under %s unreadable (%s) — "
                    "falling back to the previous snapshot",
                    s, self.directory, exc,
                )
                continue
            return state, meta
        raise FileNotFoundError(
            f"no coherently restorable checkpoint under {self.directory} "
            f"(last error: {last_exc})"
        )

    # --- ModelHandle convenience --------------------------------------------

    def save_model(self, step: int, model) -> bool:
        """Snapshot a ModelHandle: params + federation metadata."""
        meta = {
            "contributors": list(model.contributors),
            "num_samples": int(model.num_samples),
            "additional_info": _jsonable(model.additional_info),
        }
        return self.save(step, model.params, meta)

    def restore_model(self, template_model, step: Optional[int] = None):
        """Restore into a copy of ``template_model`` (same apply_fn/def)."""
        params, meta = self.restore(template_model.params, step)
        out = template_model.build_copy(params=params)
        out.contributors = list(meta.get("contributors", []))
        out.num_samples = int(meta.get("num_samples", 1))
        out.additional_info = dict(meta.get("additional_info", {}))
        return out

    # --- bookkeeping ---------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def all_steps(self) -> List[int]:
        """Complete (committed) steps only — torn directories a crash left
        behind are invisible here, so they can never be selected as the
        resume point."""
        return [s for s in self._mngr.all_steps() if self._step_complete(s)]

    def wait(self) -> None:
        """Block until in-flight async saves land, then fsync the committed
        step directories' entries (the atomic rename is durable only once
        the parent directory is flushed)."""
        self._mngr.wait_until_finished()
        _fsync_dir(self.directory)
        latest = self.latest_step()
        if latest is not None:
            _fsync_dir(os.path.join(self.directory, str(latest)))

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self) -> "FLCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NodeJournal:
    """Write-ahead node-state journal: the durable-recovery closure of one
    federated node, snapshotted atomically per round/window.

    Where :func:`attach_node_checkpointing` snapshots only the MODEL, the
    journal captures everything :meth:`p2pfl_tpu.node.Node.resume` needs to
    bring a crashed node back *as itself* mid-experiment (Papaya treats
    restarts as the normal operating condition; APPFL makes restartability a
    framework capability):

    * model params + contributor metadata,
    * the sparse-delta wire state — round anchor AND error-feedback
      residuals (``comm/delta.py``), restored bit-exact so sparse frames for
      the journaled round keep decoding and no transmitted mass is lost,
    * round/window position, scheduler mode, epochs, total rounds,
    * known membership + per-peer round status, so the resumed node can
      reconnect and re-enter the stage machine where it left off.

    Steps are indexed by round; saves ride :class:`FLCheckpointer`'s
    crash-safe path (temp-staged, atomically renamed, commit-marked), so a
    crash mid-journal leaves the previous snapshot restorable.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: Optional[int] = None,
        every: Optional[int] = None,
    ) -> None:
        from p2pfl_tpu.config import Settings

        self._ck = FLCheckpointer(
            directory,
            max_to_keep=max_to_keep or Settings.RECOVERY_JOURNAL_KEEP,
            save_interval=1,
        )
        self.every = max(1, int(every or Settings.RECOVERY_JOURNAL_EVERY))

    @property
    def directory(self) -> str:
        return self._ck.directory

    # --- write side ----------------------------------------------------------

    def snapshot(self, node) -> bool:
        """Journal ``node``'s full recovery closure at its current round.
        No-op (False) outside an experiment or when this round is already
        journaled."""
        state = node.state
        r = state.round
        if state.experiment is None or r is None:
            return False
        if r in self._ck.all_steps():
            return False  # this position is already durable
        model = node.learner.get_model()
        wire_st = state.wire.export_state()
        tree: Dict[str, Any] = {
            "params": [np.asarray(p) for p in model.get_parameters()]
        }
        if wire_st["anchor"] is not None:
            tree["anchor"] = wire_st["anchor"]
        if wire_st["residual"] is not None:
            tree["residual"] = wire_st["residual"]
        try:
            membership = list(node.protocol.get_neighbors(only_direct=False))
        except Exception:  # noqa: BLE001 — protocol stopping; journal anyway
            membership = []
        meta = {
            "journal_version": 1,
            "addr": node.addr,
            "round": int(r),
            "total_rounds": int(state.total_rounds or 0),
            "epochs": int(state.epochs),
            "fed_mode": state.fed_mode,
            "exp_name": state.experiment.exp_name,
            "anchor_round": int(wire_st["anchor_round"]),
            "anchor_crc": int(wire_st["anchor_crc"]),
            "anchor_shapes": [list(s) for s in (wire_st["shapes"] or [])],
            "has_anchor": wire_st["anchor"] is not None,
            "has_residual": wire_st["residual"] is not None,
            "membership": membership,
            "nei_status": {k: int(v) for k, v in state.nei_status.items()},
            "contributors": list(model.contributors),
            "num_samples": int(model.get_num_samples()),
            # Privacy plane: the session DH keypair + learned peer keys.
            # A crash-restarted masker MUST come back with the same pair
            # secrets — its re-sent masked frame then cancels exactly like
            # the lost one would have, instead of poisoning the lattice sum
            # with a fresh unmatched mask. Plaintext on disk, the same trust
            # the journal already extends to model params (threat model:
            # docs/components/privacy.md).
            "privacy": state.privacy.export_state(),
        }
        saved = self._ck.save(int(r), tree, meta)
        if saved:
            _JOURNAL_SAVES.labels(node.addr).inc()
            try:
                node.protocol.flight_recorder.record(
                    "journal", round=int(r), steps=len(self._ck.all_steps())
                )
            except Exception:  # noqa: BLE001 — observability must not raise
                pass
        return saved

    # --- read side -----------------------------------------------------------

    def latest_meta(self) -> Dict[str, Any]:
        """Newest restorable snapshot's metadata (raises FileNotFoundError
        when the journal is empty; torn steps are skipped)."""
        return self._ck.restore_meta()

    def restore_into(self, node) -> Dict[str, Any]:
        """Load the newest restorable snapshot into ``node``: model params +
        contribution, delta anchor + EF residuals (bit-exact), and per-peer
        round status. Walks older snapshots when the newest is torn. Returns
        the snapshot metadata (also stashed as ``node._resume_meta`` for
        :meth:`p2pfl_tpu.node.Node.resume_learning`)."""
        steps = sorted(self._ck.all_steps(), reverse=True)
        last_exc: Optional[Exception] = None
        for step in steps:
            try:
                meta = self._ck.restore_meta(step)
                model = node.learner.get_model()
                tree_t: Dict[str, Any] = {
                    "params": [np.asarray(p) for p in model.get_parameters()]
                }
                flat_sizes = [
                    int(np.prod(s, dtype=np.int64)) if s else 1
                    for s in meta.get("anchor_shapes") or []
                ]
                if meta.get("has_anchor"):
                    tree_t["anchor"] = [np.zeros((n,), np.float32) for n in flat_sizes]
                if meta.get("has_residual"):
                    tree_t["residual"] = [np.zeros((n,), np.float32) for n in flat_sizes]
                tree, _ = self._ck.restore(tree_t, step)
                model.set_parameters([np.asarray(p) for p in tree["params"]])
                model.set_contribution(
                    list(meta.get("contributors") or [node.addr]),
                    int(meta.get("num_samples", 1)),
                )
                shapes = [tuple(s) for s in meta.get("anchor_shapes") or []]
                node.state.wire.import_state(
                    {
                        "anchor": tree.get("anchor"),
                        "shapes": shapes or None,
                        "anchor_round": meta.get("anchor_round", -1),
                        "anchor_crc": meta.get("anchor_crc", 0),
                        "residual": tree.get("residual"),
                    }
                )
                node.state.nei_status.update(
                    {k: int(v) for k, v in (meta.get("nei_status") or {}).items()}
                )
                # Masked-round continuity: restore the journaled privacy key
                # material (pair secrets re-derive bit-identically).
                node.state.privacy.import_state(meta.get("privacy") or {})
                node._resume_meta = dict(meta)
                return dict(meta)
            except Exception as exc:  # noqa: BLE001 — torn step: fall back
                last_exc = exc
                log.warning(
                    "journal step %s under %s unrestorable (%s) — trying the "
                    "previous snapshot", step, self.directory, exc,
                )
        raise FileNotFoundError(
            f"no restorable journal under {self.directory} "
            f"(last error: {last_exc})"
        )

    # --- bookkeeping ---------------------------------------------------------

    def all_steps(self) -> List[int]:
        return self._ck.all_steps()

    def wait(self) -> None:
        self._ck.wait()

    def close(self) -> None:
        self._ck.close()

    def __enter__(self) -> "NodeJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def attach_node_journal(node, journal: NodeJournal) -> None:
    """Durable recovery: journal the node's full recovery closure at every
    ``journal.every``-th round end (and expose the journal on the node so
    quorum parking can snapshot on demand — ``Node.journal_now``)."""
    node.recovery_journal = journal

    def hook(n) -> None:
        r = n.state.round
        if r is None:
            return
        total = n.state.total_rounds or 0
        if r % journal.every == 0 or r >= total:
            journal.snapshot(n)

    node.round_end_hooks.append(hook)


def attach_node_checkpointing(node, checkpointer: FLCheckpointer) -> None:
    """Federation mode: snapshot the node's model at every round end.

    Hooks the node's ``round_end_hooks`` (fired by RoundFinishedStage via
    ``log_round_finished``); the saved step is the just-finished round.
    """

    def hook(n) -> None:
        r = n.state.round
        finished = (r - 1) if r is not None else 0
        checkpointer.save_model(max(finished, 0), n.learner.get_model())

    node.round_end_hooks.append(hook)


def _jsonable(d: Dict[str, Any]) -> Dict[str, Any]:
    """Drop/convert values JSON can't carry (arrays -> lists)."""
    out: Dict[str, Any] = {}
    for k, v in d.items():
        if isinstance(v, np.generic):  # np.float32(..) etc. — not a Python float
            out[k] = v.item()
        elif isinstance(v, (np.ndarray, jax.Array)):
            out[k] = np.asarray(v).tolist()
        elif isinstance(v, (str, int, float, bool, list, dict, type(None))):
            out[k] = v
    return out
