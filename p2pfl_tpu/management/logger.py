"""Framework logger: python-logging + metric routing, async by default.

Capability parity with the reference logger stack (management/logger/
logger.py:87-454 and the decorator chain in logger/__init__.py:28-35,
including AsyncLogger, decorators/async_logger.py:29-70). Instead of a
decorator tower, one logger object owns pluggable sinks: stdout/file
handlers, the two-level metric store, an optional web telemetry pusher, and
per-node resource monitors. A process-wide singleton instance is exposed as
``logger``.

Async: hot-path log calls (gossip ticks, heartbeats, stage transitions)
only enqueue a record into a ``QueueHandler``; a ``QueueListener`` thread
runs the real handlers, so the gossip/heartbeat threads never block on
stdout or file IO. ``flush()`` drains the queue (registered atexit).
"""

from __future__ import annotations

import atexit
import datetime
import logging
import logging.handlers
import os
import queue
import threading
from typing import Dict, Optional

from p2pfl_tpu.config import Settings
from p2pfl_tpu.experiment import Experiment
from p2pfl_tpu.management.metric_storage import GlobalMetricStorage, LocalMetricStorage
from p2pfl_tpu.utils.singleton import SingletonMeta


class P2pflTpuLogger(metaclass=SingletonMeta):
    def __init__(self) -> None:
        self._log = logging.getLogger("p2pfl_tpu")
        self._log.setLevel(getattr(logging, Settings.LOG_LEVEL, logging.INFO))
        # Async sink: the logger carries ONE QueueHandler; the listener
        # thread owns the real handlers (reference async_logger.py:29-70).
        for h in list(self._log.handlers):
            self._log.removeHandler(h)
        self._queue: "queue.SimpleQueue[logging.LogRecord]" = queue.SimpleQueue()
        self._log.addHandler(logging.handlers.QueueHandler(self._queue))
        stream = logging.StreamHandler()
        stream.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] %(message)s", "%H:%M:%S")
        )
        self._listener = logging.handlers.QueueListener(
            self._queue, stream, respect_handler_level=True
        )
        self._listener.start()
        atexit.register(self.flush)
        self._file_handler: Optional[logging.Handler] = None
        self.local_metrics = LocalMetricStorage()
        self.global_metrics = GlobalMetricStorage()
        self._nodes: Dict[str, Optional[Experiment]] = {}
        self._lock = threading.Lock()
        self._web_services = None
        self._monitors: Dict[str, object] = {}

    # --- plain logging ------------------------------------------------------

    def set_level(self, level: str) -> None:
        self._log.setLevel(getattr(logging, level, logging.INFO))

    def enable_file_logging(self, log_dir: Optional[str] = None) -> str:
        """Per-run log file under Settings.LOG_DIR (reference
        decorators/file_logger.py:30-56). The file handler joins the async
        listener, not the logger — writes never block the hot path."""
        log_dir = log_dir or Settings.LOG_DIR
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(
            log_dir, f"p2pfl_tpu-{datetime.datetime.now():%Y%m%d-%H%M%S}.log"
        )
        handlers = [h for h in self._listener.handlers if h is not self._file_handler]
        if self._file_handler is not None:
            self._file_handler.close()
        self._file_handler = logging.FileHandler(path)
        self._file_handler.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] %(message)s")
        )
        self._listener.handlers = tuple(handlers) + (self._file_handler,)
        return path

    def flush(self) -> None:
        """Drain the async queue so every enqueued record has been handled
        (stop processes the backlog, then the listener is restarted)."""
        listener = getattr(self, "_listener", None)
        if listener is None or listener._thread is None:
            return
        listener.stop()
        listener.start()

    def debug(self, node: str, msg: str) -> None:
        self._log.debug("(%s) %s", node, msg)

    def info(self, node: str, msg: str) -> None:
        self._log.info("(%s) %s", node, msg)

    def warning(self, node: str, msg: str) -> None:
        self._log.warning("(%s) %s", node, msg)

    def error(self, node: str, msg: str) -> None:
        self._log.error("(%s) %s", node, msg)

    # --- telemetry sinks ----------------------------------------------------

    def connect_web(self, url: str, key: str) -> None:
        """Attach the REST telemetry sink (reference decorators/
        web_logger.py:93-196)."""
        from p2pfl_tpu.management.web_services import WebServices

        self._web_services = WebServices(url, key)

    # --- node lifecycle (reference logger.py:306-454) -----------------------

    def register_node(self, node: str, simulation: bool = False) -> None:
        with self._lock:
            self._nodes[node] = None
        if self._web_services is not None:
            self._web_services.register_node(node)
        if Settings.RESOURCE_MONITOR_PERIOD > 0:
            from p2pfl_tpu.management.node_monitor import NodeMonitor

            mon = NodeMonitor(node, self.log_system_metric)
            self._monitors[node] = mon
            mon.start()

    def unregister_node(self, node: str) -> None:
        with self._lock:
            self._nodes.pop(node, None)
        mon = self._monitors.pop(node, None)
        if mon is not None:
            mon.stop()  # type: ignore[attr-defined]

    def experiment_started(self, node: str, experiment: Experiment) -> None:
        with self._lock:
            self._nodes[node] = experiment
        self.info(node, f"experiment started: {experiment}")

    def experiment_finished(self, node: str) -> None:
        with self._lock:
            self._nodes[node] = None
        self.info(node, "experiment finished")

    def round_finished_info(self, node: str, round: int) -> None:
        self.info(node, f"round {round} finished")

    # --- metrics (reference logger.py:266-305 routing) ----------------------

    def log_metric(
        self,
        node: str,
        metric: str,
        value: float,
        step: Optional[int] = None,
        round: Optional[int] = None,
    ) -> None:
        with self._lock:
            exp = self._nodes.get(node)
        exp_name = exp.exp_name if exp is not None else "default"
        if round is None:
            round = exp.round if exp is not None else 0
        if step is None:
            # round-wise -> global storage
            self.global_metrics.add(exp_name, node, metric, value, round or 0)
            if self._web_services is not None:
                self._web_services.send_global_metric(node, exp_name, metric, value, round or 0)
        else:
            self.local_metrics.add(exp_name, round or 0, node, metric, value, step)
            if self._web_services is not None:
                self._web_services.send_local_metric(
                    node, exp_name, metric, value, round or 0, step
                )

    def log_system_metric(self, node: str, metric: str, value: float) -> None:
        if self._web_services is not None:
            self._web_services.send_system_metric(node, metric, value)

    def get_local_logs(self):
        return self.local_metrics.get_all()

    def get_global_logs(self):
        return self.global_metrics.get_all()

    @classmethod
    def reset(cls) -> None:
        """Drop the singleton (tests)."""
        inst = SingletonMeta._instances.get(cls)
        if inst is not None:
            for mon in list(inst._monitors.values()):
                try:
                    mon.stop()  # type: ignore[attr-defined]
                except Exception:
                    pass
            try:
                inst._listener.stop()
                atexit.unregister(inst.flush)
            except Exception:
                pass
        SingletonMeta.reset(cls)


def get_logger() -> P2pflTpuLogger:
    return P2pflTpuLogger()


logger = get_logger()
