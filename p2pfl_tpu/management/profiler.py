"""Profiling: host cProfile plus on-device XLA traces.

Capability parity with the reference's profiling hook (yappi around the
example run, p2pfl/examples/mnist.py:264-297 — host-side Python stacks
saved as .pstat files). TPU-first upgrade: in this framework the entire
round loop is ONE jitted XLA program, so host profiles show a single
opaque ``execute`` call; :func:`profile_run` therefore also captures the
device timeline with ``jax.profiler.trace`` (per-op XLA execution, fusion
boundaries, HBM traffic), viewable in TensorBoard / Perfetto.
"""

from __future__ import annotations

import contextlib
import cProfile
import pathlib
import sys
import time
import uuid
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_run(
    host_dir: Optional[str] = None,
    device_trace_dir: Optional[str] = None,
    label: str = "run",
) -> Iterator[dict]:
    """Profile the enclosed block.

    Args:
        host_dir: if set, write a cProfile ``.pstat`` of the host Python
            under this directory (the reference's capability).
        device_trace_dir: if set, wrap the block in ``jax.profiler.trace``
            writing an XLA device trace under this directory.
        label: filename stem for the host profile.

    Yields a dict filled in on exit: ``elapsed_s`` plus the artifact paths
    that were written (``host_profile``, ``device_trace``).
    """
    info: dict = {}
    prof = None
    if host_dir is not None:
        prof = cProfile.Profile()

    stack = contextlib.ExitStack()
    if device_trace_dir is not None:
        import jax

        pathlib.Path(device_trace_dir).mkdir(parents=True, exist_ok=True)
        stack.enter_context(jax.profiler.trace(device_trace_dir))
        info["device_trace"] = device_trace_dir

    t0 = time.monotonic()
    if prof is not None:
        prof.enable()
    try:
        with stack:
            try:
                yield info
            finally:
                # Stamp + stop the host profiler before the trace context
                # exits: serializing the xplane files can take seconds and
                # is neither run time nor hot-path frames.
                info["elapsed_s"] = round(time.monotonic() - t0, 4)
                if prof is not None:
                    prof.disable()
    finally:
        if prof is not None:
            out = pathlib.Path(host_dir)
            out.mkdir(parents=True, exist_ok=True)
            path = out / f"{label}-{uuid.uuid4().hex}.pstat"
            prof.dump_stats(str(path))
            info["host_profile"] = str(path)
            print(f"host profile written to {path}", file=sys.stderr)
