"""Profiling: host cProfile, on-device XLA traces, and the continuous
performance-profiling plane.

Capability parity with the reference's profiling hook (yappi around the
example run, p2pfl/examples/mnist.py:264-297 — host-side Python stacks
saved as .pstat files). TPU-first upgrade: in this framework the entire
round loop is ONE jitted XLA program, so host profiles show a single
opaque ``execute`` call; :func:`profile_run` therefore also captures the
device timeline with ``jax.profiler.trace`` (per-op XLA execution, fusion
boundaries, HBM traffic), viewable in TensorBoard / Perfetto.

Continuous profiling (this PR's addition): instead of a one-shot wrapper
the operator opts into, the running system captures its own evidence —

* :func:`device_trace_window` — a bounded, never-raising
  ``jax.profiler.trace`` window any subsystem can wrap around one unit of
  work; ``capture_once`` labels make it safe to leave enabled (the stage
  machine wraps ONE fit per process when ``Settings.PERF_TRACE_DIR`` is
  set, ``MeshSimulation.run(profile_dir=...)`` wraps its first timed
  chunk).
* :func:`perf_section` — the structured ``perf`` block every bench JSON
  embeds: compile events (first-compile seconds, recompile counts — the
  retrace storms ``p2pfl_learner_jit_compile_seconds`` alone cannot see),
  steady-state step timings, XLA ``cost_analysis`` FLOPs/bytes, and the
  device-trace paths captured this process. ``scripts/perf_diff.py``
  diffs two of these with noise-aware thresholds.
"""

from __future__ import annotations

import contextlib
import cProfile
import logging
import pathlib
import sys
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

log = logging.getLogger("p2pfl_tpu")

#: Schema version stamped into every perf section; perf_diff refuses to
#: compare sections with different versions.
PERF_SCHEMA_VERSION = 1

# Device-trace windows captured by THIS process (paths), surfaced by
# perf_section so bench JSONs can point at their own evidence.
_captured_traces: List[str] = []
_captured_labels: set = set()
_capture_lock = threading.Lock()


@contextlib.contextmanager
def profile_run(
    host_dir: Optional[str] = None,
    device_trace_dir: Optional[str] = None,
    label: str = "run",
) -> Iterator[dict]:
    """Profile the enclosed block.

    Args:
        host_dir: if set, write a cProfile ``.pstat`` of the host Python
            under this directory (the reference's capability).
        device_trace_dir: if set, wrap the block in ``jax.profiler.trace``
            writing an XLA device trace under this directory.
        label: filename stem for the host profile.

    Yields a dict filled in on exit: ``elapsed_s`` plus the artifact paths
    that were written (``host_profile``, ``device_trace``).
    """
    info: dict = {}
    prof = None
    if host_dir is not None:
        prof = cProfile.Profile()

    stack = contextlib.ExitStack()
    if device_trace_dir is not None:
        import jax

        pathlib.Path(device_trace_dir).mkdir(parents=True, exist_ok=True)
        stack.enter_context(jax.profiler.trace(device_trace_dir))
        info["device_trace"] = device_trace_dir

    t0 = time.monotonic()
    if prof is not None:
        prof.enable()
    try:
        with stack:
            try:
                yield info
            finally:
                # Stamp + stop the host profiler before the trace context
                # exits: serializing the xplane files can take seconds and
                # is neither run time nor hot-path frames.
                info["elapsed_s"] = round(time.monotonic() - t0, 4)
                if prof is not None:
                    prof.disable()
    finally:
        if prof is not None:
            out = pathlib.Path(host_dir)
            out.mkdir(parents=True, exist_ok=True)
            path = out / f"{label}-{uuid.uuid4().hex}.pstat"
            prof.dump_stats(str(path))
            info["host_profile"] = str(path)
            print(f"host profile written to {path}", file=sys.stderr)


# --- continuous profiling -----------------------------------------------------


@contextlib.contextmanager
def device_trace_window(
    trace_dir: Optional[str],
    label: str = "window",
    capture_once: bool = True,
) -> Iterator[Optional[str]]:
    """Capture a windowed ``jax.profiler`` device trace around the block.

    Built to be LEFT ENABLED in production paths: a falsy ``trace_dir``
    makes it a no-op, ``capture_once`` (default) captures only the first
    window per ``label`` per process (a fit wrapped every round costs one
    trace, not hundreds), and any profiler failure is logged and swallowed
    — a broken trace backend must never break the round it was observing.

    Yields the trace directory when capturing, else ``None``.
    """
    if not trace_dir:
        yield None
        return
    with _capture_lock:
        if capture_once and label in _captured_labels:
            yield None
            return
        _captured_labels.add(label)
    out = str(pathlib.Path(trace_dir) / label)
    started = False
    try:
        import jax

        pathlib.Path(out).mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(out)
        started = True
    except Exception:  # noqa: BLE001 — observation must not break the work
        log.exception("device trace window %r failed to start", label)
        yield None
        return
    try:
        yield out
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
                with _capture_lock:
                    _captured_traces.append(out)
            except Exception:  # noqa: BLE001
                log.exception("device trace window %r failed to stop", label)


def captured_device_traces() -> List[str]:
    """Paths of device-trace windows captured by this process so far."""
    with _capture_lock:
        return list(_captured_traces)


# (monotonic stamp, byte sum) of the last live-array sweep; None = never.
_live_sum_cache: Optional[tuple] = None


def live_arrays_bytes(ttl_s: Optional[float] = None) -> float:
    """Sum of live jax array buffer bytes, cached for
    ``Settings.DEVOBS_MEM_TTL_S`` (override with ``ttl_s``; 0 = resweep).

    The sweep is O(live arrays) — a 100k-vnode population holds thousands
    of buffers, and the digest beat used to pay that walk on EVERY beat.
    All beat-path callers now share one sweep per TTL. Never raises.
    """
    global _live_sum_cache
    try:
        if ttl_s is None:
            from p2pfl_tpu.config import Settings

            ttl_s = float(Settings.DEVOBS_MEM_TTL_S)
        now = time.monotonic()
        cached = _live_sum_cache
        if cached is not None and ttl_s > 0 and now - cached[0] <= ttl_s:
            return cached[1]
        import jax

        val = float(sum(int(a.nbytes) for a in jax.live_arrays()))
        _live_sum_cache = (now, val)
        return val
    except Exception:  # noqa: BLE001 — observation must not raise
        return 0.0


def device_memory_watermark() -> Dict[str, float]:
    """``{"bytes_in_use", "peak_bytes_in_use"}`` of device 0, best effort.

    Backend ``memory_stats()`` when the platform exposes them (TPU/GPU
    report a true allocator peak), else the TTL-cached live-array sum (CPU:
    in-use only — the peak then equals in-use). Never raises; all-zero when
    JAX is absent. The device observatory stamps this around every timed
    chunk (flight-recorder chunk events, bench ``devobs`` perf block)."""
    try:
        import jax

        stats = None
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — CPU backend has no memory_stats
            stats = None
        if stats and stats.get("bytes_in_use"):
            in_use = float(stats.get("bytes_in_use", 0.0) or 0.0)
            peak = float(stats.get("peak_bytes_in_use", 0.0) or 0.0)
            return {
                "bytes_in_use": in_use,
                "peak_bytes_in_use": max(in_use, peak),
            }
        live = live_arrays_bytes()
        return {"bytes_in_use": live, "peak_bytes_in_use": live}
    except Exception:  # noqa: BLE001
        return {"bytes_in_use": 0.0, "peak_bytes_in_use": 0.0}


def _gauge_by_node(registry: Any, name: str) -> Dict[str, float]:
    """Counter/gauge family -> {node label: value} (empty when absent)."""
    fam = registry.get(name)
    out: Dict[str, float] = {}
    if fam is None:
        return out
    for labels, child in fam.samples():
        out[labels.get("node", "")] = float(child.value)
    return out


def perf_section(
    registry: Any = None,
    cost: Optional[Dict[str, float]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The structured ``perf`` block a bench JSON embeds.

    Pulls compile/step telemetry out of the metrics registry (per-node
    first-compile seconds, recompile counts, steady-state step time /
    steps-per-second), attaches the caller's XLA ``cost_analysis`` result
    (``flops``/``bytes_accessed`` — computed since PR 1 in
    ``MeshSimulation.round_cost_analysis`` and ``JaxLearner.cost_analysis``
    but never exported until now) and the device-trace windows captured by
    this process. ``scripts/perf_diff.py`` compares two of these blocks
    with noise-aware thresholds and exit-code semantics.
    """
    if registry is None:
        from p2pfl_tpu.telemetry import REGISTRY as registry  # noqa: N811

    compile_s = _gauge_by_node(registry, "p2pfl_learner_jit_compile_seconds")
    recompiles = _gauge_by_node(registry, "p2pfl_learner_recompiles_total")
    recompile_s = _gauge_by_node(registry, "p2pfl_learner_recompile_seconds")
    step_s = _gauge_by_node(registry, "p2pfl_learner_step_seconds")
    steps_per_s = _gauge_by_node(registry, "p2pfl_learner_steps_per_second")
    section: Dict[str, Any] = {
        "schema_version": PERF_SCHEMA_VERSION,
        "compile": {
            "first_compile_s": {k: round(v, 4) for k, v in compile_s.items()},
            "recompiles_total": {k: int(v) for k, v in recompiles.items()},
            "last_recompile_s": {k: round(v, 4) for k, v in recompile_s.items()},
        },
        "steady_state": {
            "step_s": {k: round(v, 6) for k, v in step_s.items()},
            "steps_per_s": {k: round(v, 2) for k, v in steps_per_s.items()},
        },
        "xla_cost": cost,
        "device_traces": captured_device_traces(),
    }
    if extra:
        section.update(extra)
    return section
