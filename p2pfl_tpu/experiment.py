"""Experiment descriptor: name + round bookkeeping.

Parity with reference p2pfl/experiment.py:4-74.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Experiment:
    """A named multi-round learning session.

    Attributes:
        exp_name: Unique experiment identifier (used to key metric storage).
        total_rounds: Planned number of federated rounds.
        round: Current round index (0-based); ``None`` disallowed — start at 0.
    """

    exp_name: str
    total_rounds: int
    round: int = field(default=0)

    def increase_round(self) -> None:
        """Advance to the next round (reference: experiment.py:28)."""
        if self.round is None:
            raise ValueError("round not initialized")
        self.round += 1

    def self_update(self, other: "Experiment") -> None:
        """Adopt another experiment descriptor's fields."""
        self.exp_name = other.exp_name
        self.total_rounds = other.total_rounds
        self.round = other.round

    def __str__(self) -> str:
        return (
            f"Experiment(exp_name={self.exp_name}, total_rounds={self.total_rounds}, "
            f"round={self.round})"
        )
