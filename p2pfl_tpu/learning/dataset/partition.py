"""Dataset partitioning strategies.

Capability parity with reference
p2pfl/learning/dataset/partition_strategies.py:29-436 — and completion of it:
the reference leaves ``LabelSkewedPartitionStrategy`` raising
NotImplementedError (:107-146) and ``PercentageBasedNonIIDPartitionStrategy``
as an empty stub (:433-436); both are implemented for real here.

Every strategy maps a label vector to ``n`` lists of row indices; the dataset
wrapper turns those into per-node sub-datasets. All strategies are
deterministic given ``seed``.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


class PartitionStrategy:
    """Interface: labels -> per-partition index lists."""

    @staticmethod
    def generate(labels: Sequence[int], n: int, seed: int = 0, **kwargs) -> List[np.ndarray]:
        raise NotImplementedError


class RandomIIDPartitionStrategy(PartitionStrategy):
    """Uniform shuffle + near-equal split (reference :60-105)."""

    @staticmethod
    def generate(labels: Sequence[int], n: int, seed: int = 0, **kwargs) -> List[np.ndarray]:
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(labels))
        return [np.sort(part) for part in np.array_split(idx, n)]


class LabelSkewedPartitionStrategy(PartitionStrategy):
    """Each partition draws from a limited set of classes.

    ``classes_per_partition`` classes are assigned round-robin over a shuffled
    class order; samples of each class are split evenly among the partitions
    that own the class. (The reference declares this strategy but raises
    NotImplementedError, :107-146.)
    """

    @staticmethod
    def generate(
        labels: Sequence[int],
        n: int,
        seed: int = 0,
        classes_per_partition: int = 2,
        **kwargs,
    ) -> List[np.ndarray]:
        labels = np.asarray(labels)
        rng = np.random.default_rng(seed)
        classes = np.unique(labels)
        class_pos = {c: i for i, c in enumerate(classes)}
        # Deal class slots from a shuffled round-robin deck so every partition
        # gets exactly `classes_per_partition` distinct-ish classes and class
        # ownership stays balanced across partitions.
        deck_len = n * classes_per_partition
        deck = np.tile(rng.permutation(classes), -(-deck_len // len(classes)))[:deck_len]
        owners: List[List[int]] = [[] for _ in classes]
        for p in range(n):
            for c in deck[p * classes_per_partition : (p + 1) * classes_per_partition]:
                owners[class_pos[c]].append(p)
        parts: List[List[int]] = [[] for _ in range(n)]
        for c in classes:
            own = owners[class_pos[c]]
            if not own:  # orphan class: give it to a random partition
                own = [int(rng.integers(n))]
            c_idx = rng.permutation(np.nonzero(labels == c)[0])
            for i, chunk in enumerate(np.array_split(c_idx, len(own))):
                parts[own[i]].extend(chunk.tolist())
        return [np.sort(np.asarray(p, dtype=np.int64)) for p in parts]


class DirichletPartitionStrategy(PartitionStrategy):
    """Per-class Dirichlet(alpha) proportions with min-size re-balancing.

    Semantics of reference :161-431: for each class, draw partition
    proportions ~ Dir(alpha); resample until every partition ends up with at
    least ``min_partition_size`` rows (bounded retries, then top up from the
    largest partitions).
    """

    @staticmethod
    def generate(
        labels: Sequence[int],
        n: int,
        seed: int = 0,
        alpha: float = 0.5,
        min_partition_size: int = 2,
        max_retries: int = 50,
        **kwargs,
    ) -> List[np.ndarray]:
        labels = np.asarray(labels)
        rng = np.random.default_rng(seed)
        classes = np.unique(labels)
        for _ in range(max_retries):
            parts: List[List[int]] = [[] for _ in range(n)]
            for c in classes:
                c_idx = rng.permutation(np.nonzero(labels == c)[0])
                props = rng.dirichlet(np.full(n, alpha))
                cuts = (np.cumsum(props) * len(c_idx)).astype(int)[:-1]
                for p, chunk in enumerate(np.split(c_idx, cuts)):
                    parts[p].extend(chunk.tolist())
            if min(len(p) for p in parts) >= min_partition_size:
                break
        else:
            # Top up starving partitions from the largest ones.
            sizes = [len(p) for p in parts]
            for p in range(n):
                while len(parts[p]) < min_partition_size:
                    donor = int(np.argmax([len(q) for q in parts]))
                    parts[p].append(parts[donor].pop())
        return [np.sort(np.asarray(p, dtype=np.int64)) for p in parts]


class PercentageBasedNonIIDPartitionStrategy(PartitionStrategy):
    """Each partition keeps ``percentage`` of its rows from one "home" class
    and fills the rest IID from all classes. (Empty stub in the reference,
    :433-436.)"""

    @staticmethod
    def generate(
        labels: Sequence[int],
        n: int,
        seed: int = 0,
        percentage: float = 0.8,
        **kwargs,
    ) -> List[np.ndarray]:
        labels = np.asarray(labels)
        rng = np.random.default_rng(seed)
        classes = np.unique(labels)
        total = len(labels)
        per_part = total // n
        home_budget = int(per_part * percentage)

        by_class = {c: list(rng.permutation(np.nonzero(labels == c)[0])) for c in classes}
        pool: List[int] = []
        parts: List[List[int]] = [[] for _ in range(n)]
        # Deal home classes round-robin; a partition keeps drawing home
        # classes until its home budget is met (a single class may be smaller
        # than the budget).
        home_order = list(rng.permutation(classes))
        next_home = 0
        for p in range(n):
            need = home_budget
            while need > 0 and any(by_class[c] for c in classes):
                home = home_order[next_home % len(home_order)]
                next_home += 1
                take = by_class[home][:need]
                by_class[home] = by_class[home][need:]
                parts[p].extend(int(i) for i in take)
                need -= len(take)
        for c in classes:  # leftover rows form the IID pool
            pool.extend(int(i) for i in by_class[c])
        pool = list(rng.permutation(pool))
        for p in range(n):
            need = per_part - len(parts[p])
            parts[p].extend(pool[:need])
            pool = pool[need:]
        return [np.sort(np.asarray(p, dtype=np.int64)) for p in parts]
