"""HF-datasets wrapper + jax-native export.

Capability parity with the reference ``P2PFLDataset``
(p2pfl/learning/dataset/p2pfl_dataset.py:55-342): construction from
csv/json/parquet/HF-hub/pandas/generator, train/test split, partition
generation, and export. The export path fixes the reference's inefficiency of
driving flax through a torch DataLoader with batch_size=1
(flax/flax_learner.py:40-173, flax_dataset.py:29-67): here export produces
dense, padded, fixed-shape numpy arrays that a jitted ``lax.scan`` epoch can
consume directly.

Also ships :func:`synthetic_mnist` — a deterministic, learnable MNIST-shaped
dataset (random class templates + noise) so tests and benches run with zero
network egress (the reference downloads ``p2pfl/MNIST`` from the HF hub,
test/node_test.py:79-135).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from p2pfl_tpu.learning.dataset.partition import PartitionStrategy

try:  # HF datasets is available in the image; keep a soft dependency anyway.
    import datasets as hf_datasets
except ImportError:  # pragma: no cover
    hf_datasets = None


class FederatedDataset:
    """A train/test pair of HF datasets with partition + export helpers.

    Args:
        data: HF ``Dataset`` (split lazily) or ``DatasetDict`` with
            ``train``/``test`` keys.
        x_key / y_key: column names for inputs and labels.
    """

    def __init__(
        self,
        data: Any,
        x_key: str = "image",
        y_key: str = "label",
        train_split: str = "train",
        test_split: str = "test",
    ) -> None:
        self._data = data
        self.x_key = x_key
        self.y_key = y_key
        self.train_split = train_split
        self.test_split = test_split

    # --- constructors (reference p2pfl_dataset.py:187-223) ------------------

    @classmethod
    def from_huggingface(cls, dataset_id: str, **kwargs) -> "FederatedDataset":
        return cls(hf_datasets.load_dataset(dataset_id), **kwargs)

    @classmethod
    def from_csv(cls, path: str, **kwargs) -> "FederatedDataset":
        return cls(hf_datasets.load_dataset("csv", data_files=path), **kwargs)

    @classmethod
    def from_json(cls, path: str, **kwargs) -> "FederatedDataset":
        return cls(hf_datasets.load_dataset("json", data_files=path), **kwargs)

    @classmethod
    def from_parquet(cls, path: str, **kwargs) -> "FederatedDataset":
        return cls(hf_datasets.load_dataset("parquet", data_files=path), **kwargs)

    @classmethod
    def from_pandas(cls, df: Any, **kwargs) -> "FederatedDataset":
        return cls(hf_datasets.Dataset.from_pandas(df), **kwargs)

    @classmethod
    def from_generator(cls, gen: Callable, **kwargs) -> "FederatedDataset":
        return cls(hf_datasets.Dataset.from_generator(gen), **kwargs)

    @classmethod
    def from_arrays(
        cls,
        x_train: np.ndarray,
        y_train: np.ndarray,
        x_test: Optional[np.ndarray] = None,
        y_test: Optional[np.ndarray] = None,
        x_key: str = "x",
        y_key: str = "y",
    ) -> "FederatedDataset":
        """Build directly from numpy arrays (no HF machinery in the hot path)."""
        d: Dict[str, Any] = {
            "train": _ArraySplit(np.asarray(x_train), np.asarray(y_train)),
        }
        if x_test is not None:
            d["test"] = _ArraySplit(np.asarray(x_test), np.asarray(y_test))
        return cls(d, x_key=x_key, y_key=y_key)

    # --- splits -------------------------------------------------------------

    def _split(self, train: bool) -> Any:
        key = self.train_split if train else self.test_split
        if isinstance(self._data, dict):
            return self._data[key]
        if hf_datasets is not None and isinstance(self._data, hf_datasets.DatasetDict):
            return self._data[key]
        if train:
            return self._data
        raise KeyError("dataset has no test split — call generate_train_test_split first")

    def generate_train_test_split(self, test_size: float = 0.2, seed: int = 0) -> None:
        """Split an unsplit dataset into train/test in place."""
        if isinstance(self._data, dict):
            train = self._data["train"]
            if isinstance(train, _ArraySplit):
                a, b = train.train_test_split(test_size, seed)
            else:  # HF Dataset: keyword args (2nd positional is train_size!)
                dd = train.train_test_split(test_size=test_size, seed=seed)
                a, b = dd["train"], dd["test"]
            self._data = {"train": a, "test": b}
        elif hf_datasets is not None and isinstance(self._data, hf_datasets.Dataset):
            self._data = self._data.train_test_split(test_size=test_size, seed=seed)
        else:
            raise TypeError("dataset is already split")

    def get_num_samples(self, train: bool = True) -> int:
        return len(self._split(train))

    # --- partitioning (reference p2pfl_dataset.py:203-223) ------------------

    def generate_partitions(
        self,
        num_partitions: int,
        strategy: Union[PartitionStrategy, type],
        seed: int = 0,
        **kwargs,
    ) -> List["FederatedDataset"]:
        """Partition the train split; every partition shares the full test
        split (standard FL evaluation protocol, as in the reference)."""
        train = self._split(True)
        labels = np.asarray(train[self.y_key]) if not isinstance(train, _ArraySplit) else train.y
        index_lists = strategy.generate(labels, num_partitions, seed=seed, **kwargs)
        out = []
        try:
            test = self._split(False)
        except KeyError:
            test = None
        for idx in index_lists:
            sub_train = train.select(idx) if hasattr(train, "select") else train.take(idx)
            d = {"train": sub_train}
            if test is not None:
                d["test"] = test
            out.append(
                FederatedDataset(
                    d,
                    x_key=self.x_key,
                    y_key=self.y_key,
                    train_split="train",
                    test_split="test",
                )
            )
        return out

    # --- export -------------------------------------------------------------

    def export_arrays(self, train: bool = True) -> Tuple[np.ndarray, np.ndarray]:
        """Dense ``(x, y)`` numpy arrays for the requested split."""
        split = self._split(train)
        if isinstance(split, _ArraySplit):
            return split.x, split.y
        x = np.asarray(split[self.x_key], dtype=np.float32)
        y = np.asarray(split[self.y_key], dtype=np.int32)
        if x.dtype == np.uint8 or x.max() > 2.0:
            x = x.astype(np.float32) / 255.0
        return x, y

    def export_batches(
        self,
        batch_size: int,
        train: bool = True,
        seed: "int | Tuple[int, ...]" = 0,
        drop_remainder: bool = False,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Fixed-shape batched arrays for a jitted ``lax.scan`` epoch.

        Returns ``(xb, yb, wb)`` with shapes ``[steps, B, ...]``,
        ``[steps, B]``, ``[steps, B]``; ``wb`` is a 0/1 validity mask covering
        the padding of the final partial batch (so jitted loss math can ignore
        padded rows while shapes stay static).

        ``seed`` may be an int or a tuple of ints — tuples feed numpy's
        ``SeedSequence`` hash, giving collision-free streams for structured
        coordinates like ``(base_seed, fit, epoch)``.
        """
        from p2pfl_tpu.learning.dataset.export_strategies import (
            BatchedArraysExportStrategy,
        )

        return self.export(
            BatchedArraysExportStrategy,
            train=train,
            batch_size=batch_size,
            seed=seed,
            drop_remainder=drop_remainder,
        )

    def export(
        self,
        strategy: type,
        train: bool = True,
        batch_size: int = 64,
        seed: "int | Tuple[int, ...]" = 0,
        **kwargs,
    ) -> Any:
        """Export the split through a framework-native strategy (reference
        ``P2PFLDataset.export``, p2pfl_dataset.py:224-248).

        ``strategy`` is an :class:`~p2pfl_tpu.learning.dataset.
        export_strategies.ExportStrategy` subclass — e.g.
        ``TorchExportStrategy`` (a ``DataLoader``),
        ``TensorFlowExportStrategy`` (a ``tf.data.Dataset``), or
        ``BatchedArraysExportStrategy`` (the TPU ``lax.scan`` layout).
        """
        x, y = self.export_arrays(train)
        return strategy.export(
            x, y, train=train, batch_size=batch_size, seed=seed, **kwargs
        )


class _ArraySplit:
    """Minimal split backed by dense numpy arrays (no HF overhead)."""

    def __init__(self, x: np.ndarray, y: np.ndarray) -> None:
        assert len(x) == len(y)
        self.x = x
        self.y = y

    def __len__(self) -> int:
        return len(self.y)

    def take(self, idx: np.ndarray) -> "_ArraySplit":
        return _ArraySplit(self.x[idx], self.y[idx])

    def train_test_split(self, test_size: float, seed: int) -> Tuple["_ArraySplit", "_ArraySplit"]:
        n = len(self.y)
        rng = np.random.default_rng(seed)
        order = rng.permutation(n)
        cut = int(n * (1 - test_size))
        return self.take(order[:cut]), self.take(order[cut:])


def synthetic_mnist(
    n_train: int = 4096,
    n_test: int = 1024,
    num_classes: int = 10,
    seed: int = 42,
    noise: float = 0.35,
) -> FederatedDataset:
    """Deterministic MNIST-shaped dataset a small MLP can learn.

    Each class has a fixed random 28x28 template; samples are
    ``template + gaussian noise`` clipped to [0, 1]. Linearly separable in
    expectation, so accuracy > 0.5 after one epoch (the reference's e2e
    assertion, test/node_test.py:126-132) is achievable without downloads.
    """
    rng = np.random.default_rng(seed)
    templates = rng.uniform(0.0, 1.0, size=(num_classes, 28, 28)).astype(np.float32)

    def make(n: int, rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray]:
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        x = templates[y] + rng.normal(0.0, noise, size=(n, 28, 28)).astype(np.float32)
        return np.clip(x, 0.0, 1.0), y

    x_train, y_train = make(n_train, np.random.default_rng(seed + 1))
    x_test, y_test = make(n_test, np.random.default_rng(seed + 2))
    return FederatedDataset.from_arrays(x_train, y_train, x_test, y_test)


def synthetic_cifar10(
    n_train: int = 8192,
    n_test: int = 1024,
    num_classes: int = 10,
    image_size: int = 32,
    seed: int = 42,
    noise: float = 0.25,
) -> FederatedDataset:
    """Deterministic CIFAR-shaped dataset ``[N, H, W, 3]`` a convnet can learn
    (BASELINE.json configs #3/#4 shape, no downloads).

    Each class has a fixed low-frequency color template (random coarse grid
    upsampled to ``image_size``); samples are ``template + gaussian noise``
    clipped to [0, 1]. The coarse structure rewards spatial feature
    extraction — a conv stem separates the classes quickly while the task
    stays nontrivial under per-pixel noise.
    """
    rng = np.random.default_rng(seed)
    coarse = rng.uniform(0.0, 1.0, size=(num_classes, 4, 4, 3)).astype(np.float32)
    reps = -(-image_size // 4)
    templates = np.repeat(np.repeat(coarse, reps, axis=1), reps, axis=2)[
        :, :image_size, :image_size, :
    ]

    def make(n: int, rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray]:
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        x = templates[y] + rng.normal(0.0, noise, size=(n, image_size, image_size, 3)).astype(
            np.float32
        )
        return np.clip(x, 0.0, 1.0), y

    x_train, y_train = make(n_train, np.random.default_rng(seed + 1))
    x_test, y_test = make(n_test, np.random.default_rng(seed + 2))
    return FederatedDataset.from_arrays(x_train, y_train, x_test, y_test)


def cifar10(fallback_synthetic: bool = True) -> FederatedDataset:
    """Real CIFAR-10 from the HF hub if reachable, else the synthetic stand-in."""
    try:
        return FederatedDataset.from_huggingface("uoft-cs/cifar10", y_key="label")
    except Exception:
        if not fallback_synthetic:
            raise
        return synthetic_cifar10()


def mnist(fallback_synthetic: bool = True) -> FederatedDataset:
    """Real MNIST from the HF hub if reachable, else the synthetic stand-in."""
    try:
        return FederatedDataset.from_huggingface("ylecun/mnist")
    except Exception:
        if not fallback_synthetic:
            raise
        return synthetic_mnist()
