"""Dataset wrapper, partition strategies, and jax export."""

from p2pfl_tpu.learning.dataset.dataset import FederatedDataset, synthetic_mnist  # noqa: F401
from p2pfl_tpu.learning.dataset.partition import (  # noqa: F401
    DirichletPartitionStrategy,
    LabelSkewedPartitionStrategy,
    PercentageBasedNonIIDPartitionStrategy,
    RandomIIDPartitionStrategy,
)
