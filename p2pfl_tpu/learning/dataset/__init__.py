"""Dataset wrapper, partition strategies, and jax export."""

from p2pfl_tpu.learning.dataset.dataset import (  # noqa: F401
    FederatedDataset,
    cifar10,
    mnist,
    synthetic_cifar10,
    synthetic_mnist,
)
from p2pfl_tpu.learning.dataset.export_strategies import (  # noqa: F401
    BatchedArraysExportStrategy,
    ExportStrategy,
    NumpyExportStrategy,
    TensorFlowExportStrategy,
    TorchExportStrategy,
)
from p2pfl_tpu.learning.dataset.poison import (  # noqa: F401
    flip_labels,
    poison_partitions,
    select_poisoned,
)
from p2pfl_tpu.learning.dataset.partition import (  # noqa: F401
    DirichletPartitionStrategy,
    LabelSkewedPartitionStrategy,
    PercentageBasedNonIIDPartitionStrategy,
    RandomIIDPartitionStrategy,
)
from p2pfl_tpu.learning.dataset.vision import (  # noqa: F401
    from_vision_datasets,
    load_torchvision,
    vision_pairs_to_arrays,
)
