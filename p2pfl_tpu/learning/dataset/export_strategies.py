"""Pluggable framework-native data export strategies.

Parity with the reference's export surface
(p2pfl/learning/dataset/p2pfl_dataset.py:224-248 ``export(strategy)``,
pytorch/lightning_dataset.py:29-69 ``PyTorchExportStrategy`` -> DataLoader,
tensorflow/keras_dataset.py:29-69 ``TensorFlowExportStrategy`` -> tf.data),
redesigned around dense arrays: every strategy receives the split as numpy
``(x, y)`` and returns whatever its framework trains from. The TPU-native
path is itself a strategy (:class:`BatchedArraysExportStrategy` — the
fixed-shape ``lax.scan`` layout), so JAX, torch and keras learners all pull
batches through the same seam.

Strategies are stateless classes dispatched by
:meth:`FederatedDataset.export`; register new ones by subclassing
:class:`ExportStrategy` — nothing is looked up by name.
"""

from __future__ import annotations

import abc
from typing import Any, Tuple

import numpy as np


class ExportStrategy(abc.ABC):
    """Interface: dense ``(x, y)`` arrays -> framework-native dataset."""

    @staticmethod
    @abc.abstractmethod
    def export(
        x: np.ndarray,
        y: np.ndarray,
        *,
        train: bool,
        batch_size: int,
        seed: Any,
        **kwargs: Any,
    ) -> Any: ...


class NumpyExportStrategy(ExportStrategy):
    """The identity export: ``(x, y)`` dense arrays."""

    @staticmethod
    def export(x, y, *, train, batch_size, seed, **kwargs):
        return x, y


class BatchedArraysExportStrategy(ExportStrategy):
    """Fixed-shape ``(xb, yb, wb)`` batch stacks for a jitted ``lax.scan``
    epoch — the TPU-native layout (see
    :meth:`FederatedDataset.export_batches`, which delegates here)."""

    @staticmethod
    def export(x, y, *, train, batch_size, seed, drop_remainder=False, **kwargs):
        n = len(y)
        rng = np.random.default_rng(seed)
        order = rng.permutation(n)
        x, y = x[order], y[order]
        if drop_remainder:
            steps = n // batch_size
            pad = 0
        else:
            steps = -(-n // batch_size)
            pad = steps * batch_size - n
        if pad:
            x = np.concatenate([x, np.zeros((pad, *x.shape[1:]), x.dtype)])
            y = np.concatenate([y, np.zeros((pad,), y.dtype)])
        w = np.ones((steps * batch_size,), np.float32)
        if pad:
            w[-pad:] = 0.0
        m = steps * batch_size  # drop_remainder: slice off the ragged tail
        return (
            x[:m].reshape(steps, batch_size, *x.shape[1:]),
            y[:m].reshape(steps, batch_size),
            w.reshape(steps, batch_size),
        )


class TorchExportStrategy(ExportStrategy):
    """``torch.utils.data.DataLoader`` over a ``TensorDataset`` (reference
    pytorch/lightning_dataset.py:29-69 — without the Lightning wrapper).

    Shuffling uses a seeded generator so runs stay reproducible under a
    pinned learner seed; the final partial batch is kept (torch losses
    handle ragged batches natively, no padding mask needed).
    """

    @staticmethod
    def export(x, y, *, train, batch_size, seed, num_workers=0, **kwargs):
        import torch
        from torch.utils.data import DataLoader, TensorDataset

        ds = TensorDataset(
            torch.from_numpy(np.ascontiguousarray(x, dtype=np.float32)),
            torch.from_numpy(np.ascontiguousarray(y, dtype=np.int64)),
        )
        gen = torch.Generator()
        gen.manual_seed(int(np.random.SeedSequence(seed).generate_state(1)[0]))
        return DataLoader(
            ds,
            batch_size=batch_size,
            shuffle=train,
            generator=gen if train else None,
            num_workers=num_workers,
        )


class TensorFlowExportStrategy(ExportStrategy):
    """``tf.data.Dataset`` of ``(x, y)`` batches (reference
    tensorflow/keras_dataset.py:29-69).

    Shuffle buffer covers the whole split (partitions are small relative to
    host RAM); reshuffles each epoch iteration from the given seed.
    """

    @staticmethod
    def export(x, y, *, train, batch_size, seed, **kwargs):
        import tensorflow as tf

        ds = tf.data.Dataset.from_tensor_slices(
            (np.asarray(x, np.float32), np.asarray(y, np.int32))
        )
        if train:
            ds = ds.shuffle(
                buffer_size=len(y),
                seed=int(np.random.SeedSequence(seed).generate_state(1)[0]),
                reshuffle_each_iteration=True,
            )
        return ds.batch(batch_size).prefetch(tf.data.AUTOTUNE)
