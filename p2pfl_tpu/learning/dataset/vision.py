"""Vision dataset interop: torchvision-style datasets -> FederatedDataset.

Capability parity with the reference's torchvision bridge
(p2pfl/learning/frameworks/pytorch/utils/torchvision_to_datasets.py:41-79,
``create_huggingface_dataset_from_torchvision``): take a torchvision map- or
iterable-style dataset of ``(image, label)`` pairs and turn it into the
framework's federated dataset type, ready for partitioning and jitted export.

TPU-first difference: the reference converts through an HF generator dataset
(row-at-a-time python objects); here conversion lands directly in dense,
contiguous float32 arrays — the shape the jitted ``lax.scan`` epoch consumes —
so there is no per-row overhead between the vision dataset and the chip.

torchvision itself is optional (it is not installed in this image); the
converter works with ANY object yielding ``(image, label)`` pairs, and
:func:`load_torchvision` gates the import with an actionable error pointing
at the zero-egress alternatives (``mnist()`` / ``synthetic_mnist()``).
"""

from __future__ import annotations

import warnings
from typing import Any, Iterable, Optional, Tuple

import numpy as np

from p2pfl_tpu.learning.dataset.dataset import FederatedDataset

#: Dataset names the loader accepts without a warning — mirrors the
#: reference's SUPPORTED_DATASETS (torchvision_to_datasets.py:31-38).
SUPPORTED_DATASETS = (
    "CIFAR10",
    "CIFAR100",
    "MNIST",
    "FashionMNIST",
    "EMNIST",
    "QMNIST",
)


def vision_pairs_to_arrays(
    dataset: Iterable[Tuple[Any, Any]],
) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize an ``(image, label)`` dataset as dense float32 arrays.

    Accepts PIL images, numpy arrays, or torch tensors; integer pixel data
    is rescaled to [0, 1] by its dtype max (255 for uint8). Labels may be
    ints or 0-d tensors.
    """
    # Fast path: torchvision map-style datasets store the whole split as
    # dense .data/.targets — rescale in one vectorized op instead of
    # round-tripping every row through __getitem__ (which builds a PIL
    # image per sample).
    data = getattr(dataset, "data", None)
    targets = getattr(dataset, "targets", None)
    has_transform = any(
        getattr(dataset, attr, None) is not None
        for attr in ("transform", "target_transform", "transforms")
    )
    if data is not None and targets is not None and not has_transform:
        x = _rescale(np.asarray(data))
        y = np.asarray(targets, dtype=np.int32).reshape(-1)
        if len(x) == 0:
            raise ValueError("vision dataset is empty")
        if len(x) != len(y):
            raise ValueError(f"data/targets length mismatch: {len(x)} vs {len(y)}")
        return x, y
    xs = []
    ys = []
    for image, label in dataset:
        xs.append(_rescale(np.asarray(image)))
        ys.append(int(label))
    if not xs:
        raise ValueError("vision dataset is empty")
    return np.stack(xs), np.asarray(ys, dtype=np.int32)


def _rescale(arr: np.ndarray) -> np.ndarray:
    """float32 in [0, 1]: integer pixel data is scaled by its dtype max."""
    if np.issubdtype(arr.dtype, np.integer):
        return arr.astype(np.float32) / float(np.iinfo(arr.dtype).max)
    return arr.astype(np.float32, copy=False)


def from_vision_datasets(
    train: Iterable[Tuple[Any, Any]],
    test: Optional[Iterable[Tuple[Any, Any]]] = None,
) -> FederatedDataset:
    """Build a :class:`FederatedDataset` from torchvision-style datasets."""
    x_train, y_train = vision_pairs_to_arrays(train)
    if test is not None:
        x_test, y_test = vision_pairs_to_arrays(test)
        return FederatedDataset.from_arrays(x_train, y_train, x_test, y_test)
    return FederatedDataset.from_arrays(x_train, y_train)


def load_torchvision(
    name: str,
    cache_dir: str,
    download: bool = True,
    with_test_split: bool = True,
    **dataset_kwargs: Any,
) -> FederatedDataset:
    """Load a named torchvision dataset as a :class:`FederatedDataset`.

    Mirrors the reference's name->class dispatch and its off-list warning
    (torchvision_to_datasets.py:62-67,132-138). Extra ``dataset_kwargs``
    are forwarded to the torchvision constructor (EMNIST, for example,
    requires ``split="byclass"``). Raises ``ImportError`` with the
    zero-egress alternatives when torchvision is not installed.
    """
    try:
        from torchvision import datasets as tv_datasets
    except ImportError as e:  # pragma: no cover - torchvision absent in CI image
        raise ImportError(
            "torchvision is not installed; use "
            "p2pfl_tpu.learning.dataset.mnist() (HF hub with synthetic "
            "fallback) or synthetic_mnist() instead, or convert any "
            "(image, label) iterable with from_vision_datasets()"
        ) from e
    if name not in SUPPORTED_DATASETS:
        warnings.warn(
            f"torchvision dataset {name!r} is not on the supported list "
            f"{SUPPORTED_DATASETS}; it must follow the (image, label) "
            "map-style protocol with train=/download= constructor args",
            stacklevel=2,
        )
    dataset_cls = getattr(tv_datasets, name, None)
    if dataset_cls is None:
        raise ValueError(
            f"unknown torchvision dataset {name!r}; supported: {SUPPORTED_DATASETS}"
        )
    if name == "EMNIST":
        dataset_kwargs.setdefault("split", "byclass")
    train = dataset_cls(root=cache_dir, train=True, download=download, **dataset_kwargs)
    test = (
        dataset_cls(root=cache_dir, train=False, download=download, **dataset_kwargs)
        if with_test_split
        else None
    )
    return from_vision_datasets(train, test)
