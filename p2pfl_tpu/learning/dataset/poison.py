"""Byzantine data poisoning for robustness experiments.

Attack models used to exercise the robust aggregation rules
(:mod:`p2pfl_tpu.learning.aggregators.robust`, BASELINE.json config #4).
No reference analogue — p2pfl ships robust-aggregation stubs but no way to
actually attack a federation with them.

Two standard attacks:

* **label flip** (here) — a poisoned node trains on systematically wrong
  labels (``y -> (y + offset) mod C``), producing a model update that pulls
  the global model toward misclassification while looking statistically
  ordinary (hard for distance-based rules at low poison rates).
* **model poisoning** (``MeshSimulation(byzantine_mask=...,
  byzantine_attack="signflip"|"scaled")``) — the update itself is corrupted
  inside the jitted round body; the data-side helpers here only cover label
  attacks since the mesh simulation owns the update path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from p2pfl_tpu.learning.dataset.dataset import FederatedDataset


def flip_labels(
    dataset: FederatedDataset,
    num_classes: int,
    offset: int = 1,
) -> FederatedDataset:
    """A copy of ``dataset`` whose TRAIN labels are shifted by ``offset``
    (mod ``num_classes``); the test split is left clean so evaluation still
    measures true accuracy."""
    x, y = dataset.export_arrays(train=True)
    flipped = ((y.astype(np.int64) + offset) % num_classes).astype(y.dtype)
    try:
        xt, yt = dataset.export_arrays(train=False)
    except KeyError:
        xt = yt = None
    return FederatedDataset.from_arrays(x, flipped, xt, yt)


def select_poisoned(n: int, fraction: float, seed: int = 0) -> np.ndarray:
    """The Byzantine node set for a population of ``n``: ``round(fraction*n)``
    distinct indices, sorted. Shared by data-poisoning
    (:func:`poison_partitions`) and model-poisoning (``MeshSimulation``
    byzantine_mask builders) so the two attack families select identical
    node sets for the same ``(n, fraction, seed)`` — apples-to-apples
    defense comparisons depend on it."""
    if not 0.0 <= fraction < 1.0:
        raise ValueError(f"fraction must be in [0, 1), got {fraction}")
    k = int(round(fraction * n))
    rng = np.random.default_rng(seed)
    return np.sort(rng.choice(n, size=k, replace=False))


def poison_partitions(
    partitions: Sequence[FederatedDataset],
    fraction: float,
    num_classes: int,
    seed: int = 0,
    offset: int = 1,
) -> Tuple[List[FederatedDataset], np.ndarray]:
    """Label-flip a random ``fraction`` of the partitions (Byzantine nodes).

    Returns ``(partitions, poisoned_indices)`` — the returned list is a new
    list where the chosen partitions are replaced by label-flipped copies;
    indices identify which nodes are Byzantine (ground truth for asserting
    that a robust rule excluded or out-voted them).
    """
    poisoned = select_poisoned(len(partitions), fraction, seed)
    out = list(partitions)
    for i in poisoned:
        out[i] = flip_labels(partitions[i], num_classes, offset)
    return out, poisoned
