"""Learner interface + the TPU-native JaxLearner.

Capability parity with the reference Learner ABC
(p2pfl/learning/frameworks/learner.py:33-167) and its Flax backend
(flax/flax_learner.py:40-173) — redesigned TPU-first:

* the whole local-training epoch is ONE jitted computation: parameters,
  optimizer state and the (pre-batched, fixed-shape) epoch data live on
  device, and ``lax.scan`` walks the batches (the reference runs an unjitted
  Python loop at batch_size=1 through a torch DataLoader, a TODO it never
  fixed),
* compute in bfloat16 via the model, reductions in float32,
* SCAFFOLD is implemented inside the same jitted step (gradient correction
  ``g + c - c_i``) instead of three per-framework callback classes
  (reference pytorch/callbacks/scaffold_callback.py:32-155 etc.),
* FedProx's proximal term is a loss addend under the same jit (config #5 in
  BASELINE.json).

``interrupt_fit`` (unimplemented for Flax in the reference,
flax_learner.py:167-171) is supported between epochs.
"""

from __future__ import annotations

import abc
import threading
import time
import zlib
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from p2pfl_tpu.learning.dataset.dataset import FederatedDataset
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.telemetry import REGISTRY
from p2pfl_tpu.telemetry.sketches import SKETCHES

Pytree = Any

_JIT_COMPILE_S = REGISTRY.gauge(
    "p2pfl_learner_jit_compile_seconds",
    "Wall-clock of the learner's FIRST jitted epoch call (XLA compile "
    "included) — compare against steady-state step time",
    labels=("node",),
)
_STEP_S = REGISTRY.gauge(
    "p2pfl_learner_step_seconds",
    "Steady-state seconds per training step (post-compile calls only)",
    labels=("node",),
)
_STEPS_PER_S = REGISTRY.gauge(
    "p2pfl_learner_steps_per_second",
    "Steady-state training steps per second",
    labels=("node",),
)
_RECOMPILES = REGISTRY.counter(
    "p2pfl_learner_recompiles_total",
    "XLA recompilations of the jitted train-epoch AFTER the node's first "
    "compile (lowered-cache probe) — nonzero in steady state means a "
    "retrace storm is hiding inside step time",
    labels=("node",),
)
_RECOMPILE_S = REGISTRY.gauge(
    "p2pfl_learner_recompile_seconds",
    "Wall-clock of the most recent steady-state segment that recompiled "
    "(compile included) — the latency spike each retrace costs",
    labels=("node",),
)


def _jit_cache_size(fn: Any) -> Optional[int]:
    """Compiled-program cache size of a ``jax.jit`` function, or ``None``
    when this jax version exposes no probe (recompiles then go uncounted
    rather than crashing the fit path)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001
        return None


class Learner(abc.ABC):
    """Template: owns a model + data, trains and evaluates on request."""

    def __init__(
        self,
        model: Optional[ModelHandle] = None,
        data: Optional[FederatedDataset] = None,
        self_addr: str = "unknown-node",
    ) -> None:
        self._model = model
        self._data = data
        self._self_addr = self_addr
        self.epochs = 1
        self.metric_reporter: Optional[Callable[[str, float, Optional[int]], None]] = None

    # --- wiring -------------------------------------------------------------

    def set_model(self, model: ModelHandle) -> None:
        self._model = model

    def get_model(self) -> ModelHandle:
        if self._model is None:
            raise ValueError("learner has no model")
        return self._model

    def set_data(self, data: FederatedDataset) -> None:
        self._data = data

    def get_data(self) -> FederatedDataset:
        if self._data is None:
            raise ValueError("learner has no data")
        return self._data

    def set_addr(self, addr: str) -> None:
        self._self_addr = addr

    def set_epochs(self, epochs: int) -> None:
        self.epochs = epochs

    def report(self, name: str, value: float, step: Optional[int] = None) -> None:
        if self.metric_reporter is not None:
            self.metric_reporter(name, value, step)

    # --- abstract surface (reference learner.py:92-146) ---------------------

    @abc.abstractmethod
    def fit(self) -> ModelHandle: ...

    @abc.abstractmethod
    def interrupt_fit(self) -> None: ...

    @abc.abstractmethod
    def evaluate(self) -> Dict[str, float]: ...

    @abc.abstractmethod
    def get_framework(self) -> str: ...


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array, mask: jax.Array) -> jax.Array:
    """Masked mean CE in float32 (mask zeroes padded rows)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    return jnp.sum(nll * mask) / denom


def fedprox_penalty(params: Pytree, anchor: Pytree, mu: float) -> jax.Array:
    """FedProx proximal term ``mu/2 * ||w - w_anchor||^2`` in float32 —
    shared by the nodes-mode learner and the mesh simulation so both
    execution modes stay provably identical."""
    sq = jax.tree.map(
        lambda a, b: jnp.sum((a.astype(jnp.float32) - b.astype(jnp.float32)) ** 2),
        params,
        anchor,
    )
    return 0.5 * mu * sum(jax.tree.leaves(sq))


def dp_grads(
    batch_loss_fn: Callable[[Pytree, jax.Array, jax.Array, jax.Array], jax.Array],
    params: Pytree,
    x: jax.Array,
    y: jax.Array,
    w: jax.Array,
    key: jax.Array,
    clip_norm: float,
    noise_multiplier: float,
) -> Tuple[jax.Array, Pytree]:
    """DP-SGD (loss, gradient): per-example clip to L2 ``clip_norm``, mean,
    Gaussian noise with std ``clip_norm * noise_multiplier / batch`` (Abadi
    et al. 2016, the standard sum-then-noise-then-average formulation).

    TPU-native: per-example losses and gradients come from one ``vmap``
    (a batched backward pass on the MXU — no extra forward, no per-sample
    Python loop). Shared by the nodes-mode learner and the mesh simulation
    so both execution modes stay provably identical. No reference analogue
    — p2pfl has no privacy machinery at all.

    Args:
        batch_loss_fn: the caller's masked batch loss
            ``(params, x, y, w) -> scalar`` (the pure data loss —
            regularizers that should not be clipped per example, like
            FedProx's proximal term, are added by the caller afterwards;
            see :func:`fedprox_grad`). Applied here to single-example
            batches.
        w: ``[B]`` 0/1 validity mask (padded rows contribute nothing).

    Returns:
        ``(loss, grads)``: the masked mean per-example loss and the private
        gradient estimate.
    """

    def example_loss(p: Pytree, xi: jax.Array, yi: jax.Array) -> jax.Array:
        return batch_loss_fn(p, xi[None], yi[None], jnp.ones((1,), jnp.float32))

    losses, grads = jax.vmap(
        jax.value_and_grad(example_loss), in_axes=(None, 0, 0)
    )(params, x, y)
    denom = jnp.maximum(w.sum(), 1.0)
    loss = jnp.sum(losses.astype(jnp.float32) * w) / denom
    sq = jax.tree.map(
        lambda g: jnp.sum(
            g.reshape(g.shape[0], -1).astype(jnp.float32) ** 2, axis=1
        ),
        grads,
    )
    norms = jnp.sqrt(sum(jax.tree.leaves(sq)))  # [B] per-example global norm
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(norms, 1e-12)) * w
    noise_std = clip_norm * noise_multiplier / denom
    leaves, treedef = jax.tree.flatten(grads)
    keys = list(jax.random.split(key, len(leaves)))
    out = []
    for g, k in zip(leaves, keys):
        mean = jnp.tensordot(scale, g.astype(jnp.float32), axes=1) / denom
        if noise_multiplier > 0.0:
            mean = mean + noise_std * jax.random.normal(k, mean.shape, jnp.float32)
        out.append(mean)
    return loss, jax.tree.unflatten(treedef, out)


def fedprox_grad(grads: Pytree, params: Pytree, anchor: Pytree, mu: float) -> Pytree:
    """Add FedProx's proximal-term gradient ``mu * (w - w_anchor)`` —
    applied *after* the DP mean so the regularizer is never clipped per
    example. Shared by both execution modes (like :func:`fedprox_penalty`)."""
    return jax.tree.map(
        lambda g, p, a: g + mu * (p.astype(g.dtype) - a.astype(g.dtype)),
        grads,
        params,
        anchor,
    )


def masked_lm_loss(logits: jax.Array, tokens: jax.Array, seq_mask: jax.Array) -> jax.Array:
    """Next-token CE over ``logits [B, L, V]`` / ``tokens [B, L]`` with a
    per-sequence validity mask ``[B]`` (padded rows of a stacked federated
    partition contribute zero). Thin wrapper broadcasting the sequence mask
    into :func:`p2pfl_tpu.models.transformer.causal_lm_loss`."""
    from p2pfl_tpu.models.transformer import causal_lm_loss

    mask = jnp.broadcast_to(seq_mask[:, None], tokens.shape)
    return causal_lm_loss(logits, tokens, mask)


class JaxLearner(Learner):
    """Fully-jitted local trainer.

    Args:
        optimizer: optax transformation (default ``optax.adam(lr)``).
        lr: learning rate used when ``optimizer`` is None and for SCAFFOLD's
            control-variate update (needs the raw step size).
        batch_size: local batch size (reference flax path hardcoded 1).
        fedprox_mu: if > 0, add the FedProx proximal term
            ``mu/2 * ||w - w_round_start||^2`` to the loss.
        dp_clip_norm: if > 0, train with DP-SGD: per-example gradients
            clipped to this L2 norm (see :func:`dp_grads`).
        dp_noise_multiplier: Gaussian noise scale sigma for DP-SGD (noise
            std = clip * sigma / batch on the mean gradient).
        seed: base RNG seed. Default ``None`` draws the base from OS
            entropy — required for the DP-SGD epsilon claim to mean
            anything, since a noise key derived from public values lets an
            observer regenerate and subtract the noise. Pinning an int is
            an explicit reproducibility opt-in; with DP enabled it voids
            the privacy claim against any adversary who learns the seed.
        interrupt_every: check ``interrupt_fit`` every this many STEPS by
            chunking the epoch's ``lax.scan`` into segments (at most two
            distinct segment lengths compile). Default ``None`` keeps the
            whole epoch as one compiled call and checks only between
            epochs — the torch path's per-batch granularity (reference
            lightning ``trainer.should_stop``,
            pytorch/lightning_learner.py:98-137) costs nothing there but
            would fragment the jitted scan here, so mid-epoch checks are
            opt-in.
    """

    SUPPORTED_CALLBACKS = ("scaffold",)

    # Process-wide compiled-cache watermark for the SHARED jitted train
    # epoch (`_train_epoch` is one static function for every in-process
    # learner): growth across a call means that call compiled something.
    _seen_cache_size = 0
    _cache_probe_lock = threading.Lock()

    def __init__(
        self,
        model: Optional[ModelHandle] = None,
        data: Optional[FederatedDataset] = None,
        self_addr: str = "unknown-node",
        optimizer: Optional[optax.GradientTransformation] = None,
        lr: float = 1e-3,
        batch_size: int = 64,
        fedprox_mu: float = 0.0,
        dp_clip_norm: Optional[float] = None,
        dp_noise_multiplier: Optional[float] = None,
        seed: Optional[int] = None,
        callbacks: Optional[List[str]] = None,
        interrupt_every: Optional[int] = None,
    ) -> None:
        super().__init__(model, data, self_addr)
        if interrupt_every is not None and interrupt_every < 1:
            raise ValueError(f"interrupt_every must be >= 1, got {interrupt_every}")
        self.interrupt_every = interrupt_every
        self.lr = float(lr)
        self.optimizer = optimizer if optimizer is not None else optax.adam(self.lr)
        self.batch_size = int(batch_size)
        self.fedprox_mu = float(fedprox_mu)
        # None defers to the privacy plane's process-wide DP defaults
        # (P2PFL_TPU_PRIVACY_DP_* — validated in config.py), so a federation
        # can be made private by environment without touching every Node
        # constructor; an explicit argument still wins.
        from p2pfl_tpu.config import Settings

        self.dp_clip_norm = float(
            Settings.PRIVACY_DP_CLIP if dp_clip_norm is None else dp_clip_norm
        )
        self.dp_noise_multiplier = float(
            Settings.PRIVACY_DP_SIGMA
            if dp_noise_multiplier is None
            else dp_noise_multiplier
        )
        if self.dp_noise_multiplier > 0.0 and self.dp_clip_norm <= 0.0:
            raise ValueError(
                "dp_noise_multiplier > 0 requires dp_clip_norm > 0 — without "
                "a clip bound the DP branch never runs and training would be "
                "silently non-private"
            )
        from p2pfl_tpu.learning.privacy import resolve_seed

        self.seed = resolve_seed(seed, self.dp_noise_multiplier)
        self.callbacks = list(callbacks or [])
        # Reserved names run inside the jitted step; everything else is a
        # host-side callback resolved through the open registry
        # (reference CallbackFactory contract, callback_factory.py:16-101).
        from p2pfl_tpu.learning.callbacks import CallbackFactory

        self._callback_objs = CallbackFactory.create(
            self.get_framework(),
            [cb for cb in self.callbacks if cb not in self.SUPPORTED_CALLBACKS],
        )
        self._interrupt = threading.Event()
        self._jit_timed = False  # first jitted call (compile) already gauged
        self._fit_count = 0
        self._dp_total_steps = 0  # cumulative DP-SGD steps across fit() calls
        self._nonprivate_steps = 0  # steps taken WITHOUT the DP mechanism
        self._opt_state: Optional[Pytree] = None
        self._scaffold_c_i: Optional[Pytree] = None
        self._scaffold = "scaffold" in self.callbacks

    def get_framework(self) -> str:
        return "jax"

    def interrupt_fit(self) -> None:
        self._interrupt.set()

    # --- jitted kernels -----------------------------------------------------

    @staticmethod
    @partial(
        jax.jit,
        static_argnames=(
            "apply_fn", "optimizer", "fedprox_mu", "use_scaffold",
            "dp_clip_norm", "dp_noise_multiplier",
        ),
    )
    def _train_epoch(
        params: Pytree,
        opt_state: Pytree,
        xb: jax.Array,
        yb: jax.Array,
        wb: jax.Array,
        anchor: Pytree,
        c_global: Pytree,
        c_local: Pytree,
        key: jax.Array,
        *,
        apply_fn: Callable,
        optimizer: optax.GradientTransformation,
        fedprox_mu: float,
        use_scaffold: bool,
        dp_clip_norm: float = 0.0,
        dp_noise_multiplier: float = 0.0,
    ) -> Tuple[Pytree, Pytree, jax.Array]:
        """One epoch = lax.scan over fixed-shape batches. Returns
        (params, opt_state, mean_loss). With ``dp_clip_norm > 0`` the
        gradient is the DP-SGD estimate (:func:`dp_grads`); FedProx's
        proximal pull and SCAFFOLD's correction apply after the private
        mean (they depend only on params/control state, not on data)."""

        def loss_fn(p: Pytree, x: jax.Array, y: jax.Array, w: jax.Array) -> jax.Array:
            loss = softmax_cross_entropy(apply_fn(p, x), y, w)
            if fedprox_mu > 0.0:
                loss = loss + fedprox_penalty(p, anchor, fedprox_mu)
            return loss

        def step(carry, batch):
            p, s = carry
            x, y, w, k = batch
            if dp_clip_norm > 0.0:
                loss, grads = dp_grads(
                    lambda pp, bx, by, bw: softmax_cross_entropy(
                        apply_fn(pp, bx), by, bw
                    ),
                    p, x, y, w, k, dp_clip_norm, dp_noise_multiplier,
                )
                if fedprox_mu > 0.0:
                    loss = loss + fedprox_penalty(p, anchor, fedprox_mu)
                    grads = fedprox_grad(grads, p, anchor, fedprox_mu)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(p, x, y, w)
            if use_scaffold:  # SCAFFOLD drift correction: g + c - c_i
                grads = jax.tree.map(
                    lambda g, c, ci: g + c.astype(g.dtype) - ci.astype(g.dtype),
                    grads,
                    c_global,
                    c_local,
                )
            updates, s = optimizer.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), loss

        skeys = jax.random.split(key, xb.shape[0])
        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), (xb, yb, wb, skeys)
        )
        return params, opt_state, jnp.mean(losses)

    @staticmethod
    @partial(jax.jit, static_argnames=("apply_fn",))
    def _eval_batches(
        params: Pytree, xb: jax.Array, yb: jax.Array, wb: jax.Array, *, apply_fn: Callable
    ) -> Tuple[jax.Array, jax.Array]:
        """Masked (loss, accuracy) over pre-batched eval data, one jit."""

        def step(carry, batch):
            x, y, w = batch
            logits = apply_fn(params, x)
            loss = softmax_cross_entropy(logits, y, w) * jnp.maximum(w.sum(), 1.0)
            correct = jnp.sum((jnp.argmax(logits, axis=-1) == y) * w)
            return carry, (loss, correct, w.sum())

        _, (losses, corrects, counts) = jax.lax.scan(step, None, (xb, yb, wb))
        total = jnp.maximum(jnp.sum(counts), 1.0)
        return jnp.sum(losses) / total, jnp.sum(corrects) / total

    # --- public API ---------------------------------------------------------

    def fit(self) -> ModelHandle:
        """Run ``self.epochs`` of local SGD; returns the updated model.

        Mirrors the reference contract (learner.py:92-105): the model handle
        is updated in place with new params, the node's own address as
        contributor, and the local sample count.
        """
        model = self.get_model()
        self._interrupt.clear()
        for cb in self._callback_objs:
            cb.on_fit_start(self)
        t0 = time.monotonic()
        fit_idx = self._fit_count
        self._fit_count += 1
        # Collision-free (fit, epoch) streams: arithmetic like
        # seed + 1000*fit + epoch aliases across fit() calls at epochs>=1000
        # and would reuse both the batch permutation and the DP noise key
        # (ADVICE r3) — fold_in / SeedSequence hash instead.
        fit_key = jax.random.fold_in(jax.random.key(self.seed), fit_idx)

        params = model.params
        if self._opt_state is None:
            self._opt_state = self.optimizer.init(params)
        opt_state = self._opt_state

        zeros = jax.tree.map(lambda x: jnp.zeros_like(x, dtype=jnp.float32), params)
        anchor = params
        c_global, c_local = zeros, zeros
        if self._scaffold:
            if self._scaffold_c_i is None:
                self._scaffold_c_i = zeros
            c_local = self._scaffold_c_i
            g = model.get_info("scaffold_server", {})
            if "global_c" in g:
                c_global = jax.tree.unflatten(
                    jax.tree.structure(c_global), [jnp.asarray(a) for a in g["global_c"]]
                )

        total_steps = 0
        steady_time = 0.0
        steady_steps = 0
        last_loss = float("nan")
        for epoch in range(self.epochs):
            if self._interrupt.is_set():
                break
            xb, yb, wb = self.get_data().export_batches(
                self.batch_size, train=True, seed=(self.seed, fit_idx, epoch)
            )
            # Fold the node identity in: nodes sharing a pinned seed
            # must not inject identical (coherent, recomputable) DP noise.
            epoch_key = jax.random.fold_in(
                jax.random.fold_in(fit_key, epoch),
                zlib.crc32(self._self_addr.encode()),
            )
            steps = xb.shape[0]
            # Segment the epoch scan for mid-epoch interrupt checks. Segment
            # boundaries fall on `interrupt_every` multiples, so at most two
            # program shapes compile (full segment + one ragged tail).
            seg = self.interrupt_every or steps
            xb, yb, wb = jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(wb)
            seg_losses = []
            for start in range(0, steps, seg):
                if start > 0 and self._interrupt.is_set():
                    break
                stop = min(start + seg, steps)
                t_seg = time.perf_counter()
                params, opt_state, loss = self._train_epoch(
                    params,
                    opt_state,
                    xb[start:stop],
                    yb[start:stop],
                    wb[start:stop],
                    anchor,
                    c_global,
                    c_local,
                    jax.random.fold_in(epoch_key, start),
                    apply_fn=model.apply_fn,
                    optimizer=self.optimizer,
                    fedprox_mu=self.fedprox_mu,
                    use_scaffold=self._scaffold,
                    dp_clip_norm=self.dp_clip_norm,
                    dp_noise_multiplier=self.dp_noise_multiplier,
                )
                total_steps += stop - start
                loss_f = float(loss)  # blocks on the async dispatch
                seg_dur = time.perf_counter() - t_seg
                # Did this call compile? The lowered-cache watermark grows
                # exactly when XLA traced a new program — the signal the
                # first-compile gauge alone cannot give for RE-compiles
                # (shape drift, weak-type flips, donated-buffer mismatches)
                # that otherwise hide inside steady-state step time.
                grew = 0
                size = _jit_cache_size(type(self)._train_epoch)
                if size is not None:
                    with type(self)._cache_probe_lock:
                        grew = size - type(self)._seen_cache_size
                        if grew > 0:
                            type(self)._seen_cache_size = size
                if not self._jit_timed:
                    # First jitted call = XLA compile + the segment's steps;
                    # later calls hit the compile cache and time pure compute.
                    self._jit_timed = True
                    _JIT_COMPILE_S.labels(self._self_addr).set(seg_dur)
                elif grew > 0:
                    _RECOMPILES.labels(self._self_addr).inc(grew)
                    _RECOMPILE_S.labels(self._self_addr).set(seg_dur)
                else:
                    steady_time += seg_dur
                    steady_steps += stop - start
                    # Distribution, not just the latest value: the digest's
                    # step-time sketch carries every steady segment, so the
                    # fleet sees per-node step-time QUANTILES, not a racing
                    # last-write gauge.
                    SKETCHES.observe(
                        "step_time", self._self_addr, seg_dur / (stop - start)
                    )
                seg_losses.append((stop - start, loss_f))
            last_loss = sum(n * l for n, l in seg_losses) / max(
                sum(n for n, _ in seg_losses), 1
            )
            self.report("train_loss", last_loss, step=epoch)

        if steady_steps > 0 and steady_time > 0:
            _STEP_S.labels(self._self_addr).set(steady_time / steady_steps)
            _STEPS_PER_S.labels(self._self_addr).set(steady_steps / steady_time)

        self._opt_state = opt_state
        model.params = params
        model.set_contribution([self._self_addr], self.get_data().get_num_samples(True))

        # L2 norm of this fit's update (params - round-start anchor): the
        # exact quantity the sparse delta wire path transmits, so operators
        # can relate top-k compression error to real update magnitude.
        upd_sq = jax.tree.map(
            lambda p, a: jnp.sum(
                (p.astype(jnp.float32) - a.astype(jnp.float32)) ** 2
            ),
            params,
            anchor,
        )
        upd_norm = float(jnp.sqrt(sum(jax.tree.leaves(upd_sq))))
        self.report("update_norm", upd_norm)
        SKETCHES.observe("update_norm", self._self_addr, upd_norm)

        # Per-node privacy-budget ledger (p2pfl_tpu/privacy/budget.py): the
        # cumulative epsilon rides the health digest and fed_top, so the
        # fleet sees each node's spend — not just the node itself.
        from p2pfl_tpu.privacy.budget import BUDGETS

        if self.dp_clip_norm <= 0.0:
            self._nonprivate_steps += total_steps
            BUDGETS.record(
                self._self_addr,
                clip_norm=0.0,
                noise_multiplier=0.0,
                nonprivate_steps=total_steps,
            )
        else:
            self._dp_total_steps += total_steps
            BUDGETS.record(
                self._self_addr,
                clip_norm=self.dp_clip_norm,
                noise_multiplier=self.dp_noise_multiplier,
                dp_steps=total_steps,
            )
            # Reported as a metric, NOT stamped into model.additional_info:
            # aggregation merges peers' additional_info into the local model,
            # so a stamped entry could be overwritten by another node's
            # (smaller) epsilon — a privacy claim must never travel that way.
            self.report("dp_epsilon", self.privacy_spent()["epsilon"])

        if self._scaffold and total_steps > 0:
            # c_i' = c_i - c + (x - y)/(K*lr); deltas ride in additional_info
            # (contract of reference scaffold callbacks + aggregator,
            # scaffold.py:59-140).
            scale = 1.0 / (total_steps * self.lr)
            delta_y = jax.tree.map(
                lambda y_, x_: y_.astype(jnp.float32) - x_.astype(jnp.float32), params, anchor
            )
            c_i_new = jax.tree.map(
                lambda ci, c, dy: ci - c - dy * scale, c_local, c_global, delta_y
            )
            delta_c = jax.tree.map(lambda n, o: n - o, c_i_new, c_local)
            self._scaffold_c_i = c_i_new
            model.add_info(
                "scaffold",
                {
                    "delta_y_i": [np.asarray(a) for a in jax.tree.leaves(delta_y)],
                    "delta_c_i": [np.asarray(a) for a in jax.tree.leaves(delta_c)],
                },
            )

        for cb in self._callback_objs:
            cb.on_fit_end(self)
        self.report("fit_time_s", time.monotonic() - t0)
        return model

    def privacy_spent(self, delta: float = 1e-5) -> Dict[str, Any]:
        """Conservative (epsilon, delta) spent by all training so far
        (:mod:`p2pfl_tpu.learning.privacy`); epsilon is ``inf`` when any
        step ran without the DP mechanism (noise off, or DP disabled)."""
        from p2pfl_tpu.learning.privacy import dp_sgd_privacy_spent

        return dp_sgd_privacy_spent(
            self.dp_noise_multiplier,
            self.dp_clip_norm,
            self._dp_total_steps,
            delta,
            nonprivate_steps=self._nonprivate_steps,
        )

    def cost_analysis(self) -> Optional[Dict[str, float]]:
        """XLA's own cost model for ONE jitted train-epoch call at this
        learner's current shapes — FLOPs and logical bytes accessed, the
        numbers the bench ``perf`` section exports so regressions in the
        compiled program (not just its wall-clock) are diffable. Mirrors
        ``MeshSimulation.round_cost_analysis``; returns ``None`` when the
        backend exposes no cost analysis. AOT ``lower().compile()`` may
        compile an executable the jit cache never reuses — acceptable for
        a bench-time probe, never called on the round hot path.
        """
        model = self.get_model()
        try:
            xb, yb, wb = self.get_data().export_batches(
                self.batch_size, train=True, seed=0
            )
        except Exception:  # noqa: BLE001 — no train split, no cost model
            return None
        params = model.params
        opt_state = (
            self._opt_state if self._opt_state is not None
            else self.optimizer.init(params)
        )
        zeros = jax.tree.map(lambda x: jnp.zeros_like(x, dtype=jnp.float32), params)
        xb, yb, wb = jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(wb)
        try:
            lowered = type(self)._train_epoch.lower(
                params, opt_state, xb, yb, wb, params, zeros, zeros,
                jax.random.key(0),
                apply_fn=model.apply_fn,
                optimizer=self.optimizer,
                fedprox_mu=self.fedprox_mu,
                use_scaffold=self._scaffold,
                dp_clip_norm=self.dp_clip_norm,
                dp_noise_multiplier=self.dp_noise_multiplier,
            )
            ca = lowered.compile().cost_analysis()
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            return None
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca or "flops" not in ca:
            return None
        steps = int(xb.shape[0])
        flops = float(ca["flops"])
        return {
            "flops_per_epoch": flops,
            "bytes_accessed_per_epoch": float(ca.get("bytes accessed", 0.0)),
            "flops_per_step": flops / max(steps, 1),
            "steps_per_epoch": steps,
        }

    def evaluate(self) -> Dict[str, float]:
        model = self.get_model()
        try:
            xb, yb, wb = self.get_data().export_batches(
                self.batch_size, train=False, seed=0
            )
        except KeyError:
            return {}
        loss, acc = self._eval_batches(
            model.params,
            jnp.asarray(xb),
            jnp.asarray(yb),
            jnp.asarray(wb),
            apply_fn=model.apply_fn,
        )
        metrics = {"test_loss": float(loss), "test_acc": float(acc)}
        for k, v in metrics.items():
            self.report(k, v)
        return metrics


class LearnerFactory:
    """framework tag -> learner class (reference learner_factory.py:24-56)."""

    _registry: Dict[str, type] = {"jax": JaxLearner}

    @classmethod
    def register(cls, framework: str, learner_cls: type) -> None:
        cls._registry[framework] = learner_cls

    @classmethod
    def create_learner(cls, model: ModelHandle) -> type:
        fw = model.get_framework()
        if fw not in cls._registry:
            raise ValueError(f"no learner registered for framework {fw!r}")
        return cls._registry[fw]
