"""Buffered asynchronous aggregation with staleness weighting.

The sync stage machine is bulk-synchronous: a round blocks on a vote barrier
and an aggregation deadline, so one slow committee member sets fleet p99.
This module is the async alternative in the Papaya / FedBuff style (arxiv
2111.04877, which extends the JIT-aggregation idea of arxiv 2208.09740 the
stall patience already uses): contributions are folded into a per-window
buffer AS THEY ARRIVE, each tagged with the window it trained against, and
the window closes as soon as a fill target is met (or a timeout expires) —
stragglers contribute LATE instead of being waited on or abandoned.

Weighting: a contribution that trained against window ``w - l`` (lag ``l``)
is weighted ``num_samples * staleness_weight(l)`` with the polynomial decay
``(1 + l) ** -alpha`` (Papaya §4's ``1/sqrt(1+l)`` is ``alpha = 0.5``, the
default). At ``l = 0`` the weight is exactly ``num_samples`` — a window whose
contributions are all fresh aggregates BIT-EXACTLY like
:class:`~p2pfl_tpu.learning.aggregators.fedavg.FedAvg` (same jitted kernel,
same weights).

Robust-rule interop: when the node runs a non-linear aggregation rule
(Krum/TrimmedMean/...), the window aggregate delegates to that rule over the
buffered models — the rules see individual contributions exactly as they do
on the sync path, so the Byzantine defense plane carries over unchanged.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp

from p2pfl_tpu.config import Settings
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops import aggregation as agg_ops
from p2pfl_tpu.telemetry import REGISTRY
from p2pfl_tpu.telemetry.ledger import LEDGERS
from p2pfl_tpu.telemetry.sketches import SKETCHES

_FOLDED = REGISTRY.counter(
    "p2pfl_async_contributions_total",
    "Contributions folded into the async buffer, by freshness "
    "(self | fresh: zero lag | stale: positive lag)",
    labels=("node", "kind"),
)
_DROPPED = REGISTRY.counter(
    "p2pfl_async_dropped_total",
    "Async contributions rejected before folding, by reason",
    labels=("node", "reason"),
)
_STALENESS = REGISTRY.gauge(
    "p2pfl_async_staleness",
    "Mean window lag of the contributions aggregated in the last window",
    labels=("node",),
)
_WINDOWS = REGISTRY.counter(
    "p2pfl_async_windows_total",
    "Async aggregation windows completed",
    labels=("node",),
)
_WINDOW_FILL = REGISTRY.gauge(
    "p2pfl_async_window_fill",
    "Distinct contributors aggregated in the last window",
    labels=("node",),
)
_WINDOW_CLOSE = REGISTRY.counter(
    "p2pfl_async_window_close_total",
    "Async windows closed, by reason (fill: target met; shrink: a live-"
    "shrunk target met after membership loss; timeout: deadline expired)",
    labels=("node", "reason"),
)


def staleness_discount(lag, alpha) -> jnp.ndarray:
    """THE staleness formula — a pure, jittable ``(1 + max(lag, 0)) ** -alpha``.

    Single source of truth for both execution paths: the wire buffer's
    :meth:`AsyncBufferedAggregator.aggregate_weighted` and the fused async
    window fold (:mod:`p2pfl_tpu.population.async_engine`) both weight a
    lag-``l`` contribution by ``num_samples * staleness_discount(l, alpha)``
    through this one function, which is what makes their aggregates
    bit-comparable. Accepts scalars or arrays; float32 in, float32 out —
    the dtype the weighted-FedAvg kernel consumes.

    Exactly ``1.0`` at ``lag = 0`` for every alpha (``1.0 ** -a == 1.0``
    bit-for-bit, so a fresh window aggregates as plain FedAvg) and
    identically ``1.0`` for ``alpha = 0`` (discount disabled).
    """
    lag_f = jnp.maximum(jnp.asarray(lag, jnp.float32), jnp.float32(0.0))
    return (jnp.float32(1.0) + lag_f) ** (-jnp.float32(alpha))


def staleness_weight(lag: int, alpha: Optional[float] = None) -> float:
    """Host-scalar convenience wrapper over :func:`staleness_discount`
    (Settings-defaulted alpha, int lag). Monotonically non-increasing in
    ``lag``; exactly ``1.0`` at ``lag = 0`` — see the pure function for
    the bit-exactness contract.
    """
    a = Settings.ASYNC_STALENESS_ALPHA if alpha is None else float(alpha)
    return float(staleness_discount(max(0, int(lag)), a))


class AsyncBufferedAggregator:
    """Per-node contribution buffer for one async experiment.

    Thread-safety: ``fold`` runs on transport threads, ``wait_window`` /
    ``drain`` on the scheduler thread; one lock guards the buffer, an Event
    wakes the window wait on every fold and on membership changes
    (:meth:`notify` — the death callbacks' re-evaluation hook).
    """

    def __init__(self, addr: str, rule: Optional[Callable[[List[ModelHandle]], ModelHandle]] = None) -> None:
        self.addr = addr
        #: non-None => window aggregation delegates to this robust rule
        #: (``rule(models) -> ModelHandle``); None => staleness-weighted
        #: FedAvg through the jitted kernel.
        self.rule = rule
        self._lock = threading.Lock()
        #: sender -> (model, lag-at-fold) — newest contribution per sender
        #: wins, so a sender that produced twice within one window is counted
        #: once (its fresher model).
        self._buffer: Dict[str, Tuple[ModelHandle, int]] = {}
        self._window = 0
        self._event = threading.Event()
        #: every sender folded at least once this experiment (the bench /
        #: async-check "joiner contributed within N windows" probe).
        self.seen_contributors: Dict[str, int] = {}  # sender -> first window
        self._last_mean_lag = 0.0
        #: exact lags of every contribution aggregated (bounded) — the
        #: ground truth the digest's staleness SKETCH is validated against.
        self.lag_log: deque = deque(maxlen=4096)
        #: why the last window closed ("fill" | "shrink" | "timeout") and
        #: how full it was — stamped onto the window_close marker span so
        #: the critical-path analyzer can break windows down by reason.
        self.last_close_reason = ""
        self.last_fill = 0

    # --- window lifecycle ----------------------------------------------------

    @property
    def window(self) -> int:
        with self._lock:
            return self._window

    def open_window(self, window: int) -> None:
        """Advance the window counter. The buffer is NOT cleared: anything
        that arrived after the previous drain belongs to this window."""
        with self._lock:
            self._window = int(window)
        self._event.set()  # re-evaluate any in-flight wait against the new index

    def notify(self) -> None:
        """Wake the window wait to re-evaluate its fill target (membership
        changed — a peer died or joined)."""
        self._event.set()

    # --- feeding -------------------------------------------------------------

    def fold(self, model: ModelHandle, origin_window: int, sender: str) -> bool:
        """Buffer one contribution that trained against ``origin_window``.

        Lag is clamped at 0 (a faster peer's future-window contribution is
        simply fresh). Contributions beyond ``ASYNC_MAX_STALENESS`` are
        dropped and counted. Returns True when buffered.
        """
        with self._lock:
            lag = max(0, self._window - int(origin_window))
            if Settings.ASYNC_MAX_STALENESS and lag > Settings.ASYNC_MAX_STALENESS:
                _DROPPED.labels(self.addr, "stale_limit").inc()
                return False
            self._buffer[sender] = (model, lag)
            self.seen_contributors.setdefault(sender, self._window)
            window_now = self._window
        if sender == self.addr:
            kind = "self"
        else:
            kind = "fresh" if lag == 0 else "stale"
        _FOLDED.labels(self.addr, kind).inc()
        # Trajectory ledger: the async fold is the window's contribution
        # event, lag included (the sync path's analogue lives in
        # Aggregator.add_model with lag pinned to 0).
        LEDGERS.emit(
            self.addr, "contribution_folded", round=window_now,
            sender=sender, lag=int(lag), num_samples=model.get_num_samples(),
        )
        self._event.set()
        return True

    def drop(self, sender: str, reason: str) -> None:
        """Count a pre-fold rejection (suspect gating, no-experiment...)."""
        _DROPPED.labels(self.addr, reason).inc()

    def fill(self) -> int:
        with self._lock:
            return len(self._buffer)

    # --- consuming -----------------------------------------------------------

    def wait_window(
        self,
        target_fn: Callable[[], int],
        timeout: Optional[float] = None,
        early_stop_fn: Optional[Callable[[], bool]] = None,
    ) -> Optional[ModelHandle]:
        """Block until the buffer holds ``target_fn()`` distinct contributors
        or ``timeout`` expires, then drain and aggregate.

        ``target_fn`` is re-evaluated on every wake (fold / death callback /
        :meth:`notify`), so the target SHRINKS live as peers die — the
        all-trainers-dead window completes with the own contribution alone
        instead of sleeping out the timeout.
        """
        timeout = Settings.ASYNC_WINDOW_TIMEOUT if timeout is None else timeout
        deadline = time.monotonic() + timeout
        initial_target = max(1, int(target_fn()))
        while True:
            if early_stop_fn is not None and early_stop_fn():
                return None
            target = max(1, int(target_fn()))
            with self._lock:
                have = len(self._buffer)
            if have > 0 and have >= target:
                # Close-reason attribution: a target met only because it
                # SHRANK below its window-open value is a membership story,
                # not a throughput one — the window report separates them.
                self.last_close_reason = (
                    "shrink" if target < initial_target and have < initial_target
                    else "fill"
                )
                break
            if have > 0 and time.monotonic() >= deadline:
                self.last_close_reason = "timeout"
                break
            # have == 0 past the deadline: keep a short grace loop (the own
            # contribution is still being produced) rather than raising.
            self._event.clear()
            self._event.wait(timeout=0.25)
        _WINDOW_CLOSE.labels(self.addr, self.last_close_reason).inc()
        return self._aggregate_drained()

    def _aggregate_drained(self) -> ModelHandle:
        with self._lock:
            drained = list(self._buffer.values())
            self._buffer.clear()
        if not drained:
            raise RuntimeError("async window drained empty")
        models = [m for m, _ in drained]
        lags = [lag for _, lag in drained]
        self._last_mean_lag = sum(lags) / len(lags)
        self.last_fill = len(models)
        _STALENESS.labels(self.addr).set(self._last_mean_lag)
        _WINDOW_FILL.labels(self.addr).set(len(models))
        _WINDOWS.labels(self.addr).inc()
        # Per-contribution staleness DISTRIBUTION (not just the mean): the
        # digest's staleness sketch is what lets any observer read this
        # node's staleness p90 off the gossip wire.
        for lag in lags:
            self.lag_log.append(int(lag))
            SKETCHES.observe("staleness", self.addr, float(lag))
        for m in models:
            for contributor in m.contributors:
                SKETCHES.distinct_add(self.addr, contributor)
        if self.rule is not None:
            return self.rule(models)
        return self.aggregate_weighted(models, lags)

    @property
    def last_mean_lag(self) -> float:
        return self._last_mean_lag

    @staticmethod
    def aggregate_weighted(
        models: List[ModelHandle], lags: List[int], alpha: Optional[float] = None
    ) -> ModelHandle:
        """Staleness-weighted FedAvg over ``models``.

        Weights are ``num_samples * staleness_discount(lag, alpha)`` computed
        as a float32 product — the SAME float order as the fused window fold
        in :mod:`p2pfl_tpu.population.async_engine`, so the two paths'
        aggregates are bit-comparable at any lag, and at all-zero lag this is
        float-for-float the same kernel invocation as :meth:`FedAvg.aggregate`
        (the discount is exactly 1.0, weights reduce to the plain sample
        counts), hence bit-exact.
        """
        a = Settings.ASYNC_STALENESS_ALPHA if alpha is None else float(alpha)
        stacked = agg_ops.tree_stack([m.params for m in models])
        weights = jnp.asarray(
            [m.get_num_samples() for m in models], jnp.float32
        ) * staleness_discount(jnp.asarray([int(l) for l in lags]), a)
        out = agg_ops.fedavg(stacked, weights)
        contributors: List[str] = []
        for m in models:
            contributors.extend(m.contributors)
        total = sum(m.get_num_samples() for m in models)
        return models[0].build_copy(
            params=out, contributors=sorted(set(contributors)), num_samples=total
        )

    def clear(self) -> None:
        with self._lock:
            self._buffer.clear()
        self._event.set()


__all__ = ["AsyncBufferedAggregator", "staleness_discount", "staleness_weight"]
