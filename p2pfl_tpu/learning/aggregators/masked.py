"""MaskedFedAvg — FedAvg whose round table can hold masked lattice frames.

Masked lattice vectors are ADDITIVE mod the ring (that is the whole design
of :mod:`p2pfl_tpu.privacy.secagg`), so the base aggregator's machinery —
contributor-set dedup, partial aggregation + re-gossip, retired-round
snapshots, death-shrunk expectations — works on masked handles unchanged;
only the combine step differs. Plaintext handles (init frames, a node that
could not mask) still aggregate through the plain FedAvg kernel, but the
two domains never mix: a masked merge drops plaintext entries with a
warning rather than summing floats into a ring.

The UNMASKING is not here: ``aggregate`` returns the merged masked handle
(still lattice-domain) and the stage machine finalizes it through
:meth:`p2pfl_tpu.privacy.secagg.PrivacyPlane.finalize` — the aggregator
stays a dumb accumulator, exactly like the plaintext path.
"""

from __future__ import annotations

import logging
from typing import List

import numpy as np

from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.privacy.secagg import MASKED_INFO_KEY, masked_info

log = logging.getLogger("p2pfl_tpu")


class MaskedFedAvg(FedAvg):
    """FedAvg with a masked-lattice merge path (``PRIVACY_SECAGG``)."""

    partial_aggregation = True

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        masked = [m for m in models if masked_info(m) is not None]
        if not masked:
            return super().aggregate(models)
        if len(masked) != len(models):
            # Mixed round table: a plaintext float model cannot enter a ring
            # sum. Keep the masked majority (the protocol's domain) — the
            # dropped plaintext entry's sender keeps gossiping and will be
            # counted missing at finalize like any other absentee.
            log.warning(
                "(%s) dropping %d plaintext model(s) from a masked merge",
                self.node_addr, len(models) - len(masked),
            )
        infos = [masked_info(m) for m in masked]
        first = infos[0]
        same = [
            m for m, i in zip(masked, infos)
            if i["round"] == first["round"]
            and i["bits"] == first["bits"]
            and i["n"] == first["n"]
        ]
        if len(same) != len(masked):
            log.warning(
                "(%s) dropping %d masked frame(s) from another lattice "
                "generation", self.node_addr, len(masked) - len(same),
            )
        out = [np.asarray(a).copy() for a in same[0].get_parameters()]
        for m in same[1:]:
            for i, a in enumerate(m.get_parameters()):
                out[i] = (out[i] + np.asarray(a)).astype(out[i].dtype)
        contributors, total = self._merge_metadata(same)
        return ModelHandle(
            params=out,
            contributors=contributors,
            num_samples=total,
            additional_info={MASKED_INFO_KEY: dict(first)},
        )


__all__ = ["MaskedFedAvg"]
