"""Byzantine-robust aggregation rules (BASELINE.json config #4:
"CIFAR-10 ResNet-18, 100 nodes, Byzantine-robust (Krum / trimmed-mean) with
10% adversarial nodes"). Not present in the reference — capability extension
required by the north-star configs.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from p2pfl_tpu.learning.aggregators.base import Aggregator
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops import aggregation as agg_ops


class TrimmedMean(Aggregator):
    """Coordinate-wise trimmed mean: robust to ``trim_ratio`` adversaries."""

    partial_aggregation = False

    def __init__(self, trim_ratio: float = 0.1) -> None:
        super().__init__()
        if not 0.0 <= trim_ratio < 0.5:
            raise ValueError("trim_ratio must be in [0, 0.5)")
        self.trim_ratio = trim_ratio

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        if not models:
            raise ValueError("nothing to aggregate")
        n = len(models)
        trim = min(int(n * self.trim_ratio), (n - 1) // 2)
        stacked = agg_ops.tree_stack([m.params for m in models])
        out = agg_ops.trimmed_mean(stacked, trim=trim)
        contributors, total = self._merge_metadata(models)
        return models[0].build_copy(params=out, contributors=contributors, num_samples=total)


class GeometricMedian(Aggregator):
    """Geometric median via Weiszfeld iterations (RFA, Pillutla et al.
    2019): rotation-invariant robust aggregation tolerating up to half the
    total weight being adversarial — no discrete-subset commitment like
    Krum, no per-coordinate independence assumption like trimmed mean.

    Contributions are weighted UNIFORMLY, not by self-reported
    ``get_num_samples()``: the breakdown point of the weighted geometric
    median is in terms of total *weight*, and sample counts arrive over the
    wire unauthenticated — a single Byzantine peer claiming ``10**9``
    samples would hold >50% of the weight and drag the median anywhere,
    voiding the robustness guarantee the rule exists for. Honest sample
    counts still flow through contributor metadata for FedAvg-style rules;
    this rule deliberately ignores them (one contributor, one vote).
    """

    partial_aggregation = False

    def __init__(self, iters: int = 8) -> None:
        super().__init__()
        if iters < 1:
            raise ValueError("iters must be >= 1")
        self.iters = int(iters)

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        if not models:
            raise ValueError("nothing to aggregate")
        stacked = agg_ops.tree_stack([m.params for m in models])
        weights = jnp.ones((len(models),), jnp.float32)
        out = agg_ops.geometric_median(stacked, weights, iters=self.iters)
        contributors, total = self._merge_metadata(models)
        return models[0].build_copy(params=out, contributors=contributors, num_samples=total)


class Krum(Aggregator):
    """(Multi-)Krum (Blanchard et al. 2017): select the model(s) closest to
    their peers, discarding up to ``num_byzantine`` outliers.

    ``partial_aggregation`` stays ``False``: Krum scores RAW models against
    each other, so intermediate subsets must never be pre-averaged and
    re-gossiped (an average would smuggle Byzantine mass past the distance
    filter). The round-survival machinery is unaffected — ``remove_node``
    and the JIT stall patience live on the base accumulator, so a dead
    trainset member still shrinks the wait and a stalled round still
    aggregates what arrived.
    """

    partial_aggregation = False

    def __init__(self, num_byzantine: int = 1, num_selected: int = 1) -> None:
        super().__init__()
        self.num_byzantine = int(num_byzantine)
        self.num_selected = int(num_selected)

    def _select_count(self, n: int) -> int:
        return min(self.num_selected, n)

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        if not models:
            raise ValueError("nothing to aggregate")
        n = len(models)
        sel = self._select_count(n)
        stacked = agg_ops.tree_stack([m.params for m in models])
        weights = jnp.asarray([m.get_num_samples() for m in models], jnp.float32)
        out, idx = agg_ops.krum(
            stacked, weights, num_byzantine=min(self.num_byzantine, n - 1), num_selected=sel
        )
        # Provenance: only the *selected* models contributed to the output —
        # stamping the full union would make downstream partial-aggregation
        # dedup (base.py add_model) treat discarded Byzantine nodes as merged.
        chosen = [models[i] for i in idx.tolist()]
        contributors, total = self._merge_metadata(chosen)
        return models[0].build_copy(params=out, contributors=contributors, num_samples=total)


class MultiKrum(Krum):
    """Multi-Krum with the paper's standard selection size: average the
    ``m = n - num_byzantine - 2`` lowest-scored models (Blanchard et al.
    2017, §4) instead of committing to a single winner — smoother than
    plain Krum (closer to FedAvg on the honest subset) while keeping the
    distance filter. Pass ``num_selected`` explicitly to override the
    automatic ``m``."""

    def __init__(self, num_byzantine: int = 1, num_selected: int = 0) -> None:
        super().__init__(num_byzantine=num_byzantine, num_selected=num_selected)

    def _select_count(self, n: int) -> int:
        if self.num_selected > 0:
            return min(self.num_selected, n)
        return max(1, n - self.num_byzantine - 2)
