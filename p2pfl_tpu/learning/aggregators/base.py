"""Round-scoped model accumulator.

Capability parity with the reference Aggregator base
(p2pfl/learning/aggregators/aggregator.py:35-270): a per-round accumulator
that nodes and gossip handlers feed models into, with

* contributor-set dedup (a model is redundant if its contributors are a
  subset of what we already merged — reference :113-175),
* trainset membership checks,
* a completion event set once every trainset member is covered,
* ``wait_and_get_aggregation`` blocking with timeout and aggregating whatever
  arrived (reference :177-207),
* ``get_partial_model(except_nodes)`` for partial-aggregation gossip
  (reference :219-270): combine everything the peer hasn't seen.

Thread-safety: a single RLock guards the model table; completion is an Event.
The reference's lock choreography releases an unacquired lock on edge cases
(aggregator.py:113-118, noted in SURVEY.md §7) — Events avoid that class of
bug.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from p2pfl_tpu.config import Settings
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.telemetry import REGISTRY
from p2pfl_tpu.telemetry.ledger import LEDGERS
from p2pfl_tpu.telemetry.sketches import SKETCHES

log = logging.getLogger("p2pfl_tpu")

_AGG_WAIT = REGISTRY.histogram(
    "p2pfl_aggregation_wait_seconds",
    "Time blocked in wait_and_get_aggregation before aggregating",
    labels=("node",),
)
_AGG_CONTRIBUTORS = REGISTRY.gauge(
    "p2pfl_aggregation_contributors",
    "Contributors merged into the last aggregation",
    labels=("node",),
)
_AGG_MISSING = REGISTRY.counter(
    "p2pfl_aggregation_timeout_partials_total",
    "Aggregations that proceeded with trainset members missing (timeout)",
    labels=("node",),
)
_AGG_DEAD = REGISTRY.counter(
    "p2pfl_aggregation_dead_contributors_total",
    "Trainset members dropped from the expected set after being declared dead",
    labels=("node",),
)
_AGG_STALL = REGISTRY.counter(
    "p2pfl_aggregation_stall_partials_total",
    "Aggregations cut short by the JIT stall patience (no progress while "
    "contributions were still missing)",
    labels=("node",),
)


class Aggregator:
    """Base class; subclasses implement :meth:`aggregate`."""

    #: whether intermediate subsets may be merged eagerly and re-gossiped
    #: (FedAvg-style linear rules) — reference ``partial_aggregation`` flag.
    partial_aggregation: bool = False

    def __init__(self) -> None:
        self.node_addr = "unknown-node"
        self._lock = threading.RLock()
        self._finish_event = threading.Event()
        self._train_set: List[str] = []
        self._models: List[ModelHandle] = []
        self._round: Optional[int] = None  # ledger stamp for this round's folds
        # Retired round snapshot (round, train_set, models) kept after
        # retire_round() so overlap drains can serve laggards post-boundary.
        self._retired: Optional[tuple] = None
        # monotonic timestamp of the last round progress (a stored model, a
        # death-shrink, or the round opening) — drives the JIT stall patience.
        self._last_progress = time.monotonic()
        # Optional stall hook (set by Node): called with the missing-
        # contributor list when the JIT stall patience fires — the trigger
        # that dumps the flight recorder, because a stalled aggregation is
        # exactly the postmortem the event ring exists for.
        self.on_stall: Optional[Callable[[List[str]], None]] = None

    # --- learner integration -------------------------------------------------

    def get_required_callbacks(self) -> List[str]:
        """Learner callbacks this rule depends on (reference
        CallbackFactory contract, callback_factory.py:16-101)."""
        return []

    def set_addr(self, addr: str) -> None:
        self.node_addr = addr

    # --- round lifecycle -----------------------------------------------------

    def set_nodes_to_aggregate(
        self, train_set: Sequence[str], round: Optional[int] = None
    ) -> None:
        """Open the round: declare whose contributions we expect
        (reference :66-81). ``round`` stamps this round's trajectory-ledger
        contribution events (None keeps the ledger's current round)."""
        with self._lock:
            if self._train_set:
                raise RuntimeError("aggregation already in progress — clear() first")
            self._train_set = list(train_set)
            self._models = []
            self._round = round
            self._finish_event.clear()
            self._last_progress = time.monotonic()

    def clear(self) -> None:
        with self._lock:
            self._train_set = []
            self._models = []
            self._retired = None
            self._finish_event.clear()

    def retire_round(self) -> None:
        """Close the round for NEW contributions but keep an immutable
        snapshot of its model table (train<->diffuse overlap,
        stages/base_node.py): the background partial-model drain keeps
        serving laggards out of the retired snapshot while the live side is
        already collecting the next round. Replacing an earlier snapshot
        implicitly ends any drain still reading it
        (:meth:`get_partial_model_for_round` starts returning ``None``)."""
        with self._lock:
            if self._train_set or self._models:
                self._retired = (self._round, list(self._train_set), list(self._models))
            self._train_set = []
            self._models = []
            self._finish_event.clear()

    def serves_round(self, round: int) -> bool:
        """True while this aggregator can still produce partials for
        ``round`` (it is the live round or the retired snapshot)."""
        with self._lock:
            if self._train_set and self._round == round:
                return True
            return self._retired is not None and self._retired[0] == round

    def get_aggregated_models(self) -> List[str]:
        """Addresses whose contributions have been merged so far."""
        with self._lock:
            out: List[str] = []
            for m in self._models:
                # Attribute access, not get_contributors(): a stored handle
                # whose contributor list was raced to empty (full-model
                # adoption mutating a shared handle) must degrade to "no
                # contributors", not blow up round bookkeeping from a
                # heartbeat or gossip thread.
                out.extend(m.contributors)
            return sorted(set(out))

    def get_missing_models(self) -> List[str]:
        with self._lock:
            return sorted(set(self._train_set) - set(self.get_aggregated_models()))

    def remove_node(self, addr: str) -> bool:
        """Death callback: shrink the round's expected-contributor set.

        Called when ``addr`` is declared dead mid-round (heartbeat timeout or
        send-failure write-off). If its contribution already arrived it is
        KEPT (the training happened); otherwise the node leaves the expected
        set, and — the whole point — the finish condition is re-evaluated so
        ``wait_and_get_aggregation`` wakes immediately instead of sleeping
        out ``AGGREGATION_TIMEOUT``. Returns True when the expected set
        actually shrank.
        """
        with self._lock:
            if addr not in self._train_set:
                return False
            if addr in self.get_aggregated_models():
                return False  # its model arrived before it died — keep it
            self._train_set.remove(addr)
            _AGG_DEAD.labels(self.node_addr).inc()
            self._last_progress = time.monotonic()
            if set(self.get_aggregated_models()) >= set(self._train_set):
                self._finish_event.set()
            return True

    # --- feeding models ------------------------------------------------------

    def add_model(self, model: ModelHandle, round: Optional[int] = None) -> List[str]:
        """Merge a (possibly partially-aggregated) model into the round.

        Returns the updated list of aggregated contributors (the caller
        broadcasts it as round progress — reference train_stage.py:79-85).
        Duplicate/subset contributions and contributors outside the trainset
        are ignored, matching reference :113-175. When the caller knows the
        frame's round (the wire handlers do), a mismatch against the OPEN
        round is dropped — under train<->diffuse overlap the table for round
        r stays populated while peers already gossip r+1 frames, and merging
        across generations would corrupt both.
        """
        contributors = set(model.contributors)
        if not contributors:
            return []  # anonymous model: nothing to account it against
        with self._lock:
            if (
                round is not None
                and self._round is not None
                and round != self._round
            ):
                return []  # cross-round frame: the sender's gossip re-ships
            if not self._train_set:
                # Round not open yet (e.g. model gossip raced ahead of the
                # vote result) — the caller may retry; reference logs this.
                return []
            if not contributors <= set(self._train_set):
                return self.get_aggregated_models()
            already = set(self.get_aggregated_models())
            if contributors <= already:
                return sorted(already)  # nothing new
            # Drop stored models that are now subsets of the incoming one.
            self._models = [
                m for m in self._models if not set(m.contributors) <= contributors
            ]
            self._models.append(model)
            self._last_progress = time.monotonic()
            # Trajectory ledger: one fold event per model actually merged
            # (dedup'd/subset frames returned above and never reach here),
            # so the event stream is the round's contribution set, not the
            # gossip traffic. Merged partials ledger as their sorted
            # contributor tuple; sync folds are zero-lag by construction.
            LEDGERS.emit(
                self.node_addr,
                "contribution_folded",
                round=self._round,
                sender="+".join(sorted(contributors)),
                lag=0,
                num_samples=model.get_num_samples(),
            )
            agg = self.get_aggregated_models()
            if set(agg) >= set(self._train_set):
                self._finish_event.set()
            return agg

    # --- consuming the result ------------------------------------------------

    def wait_and_get_aggregation(self, timeout: Optional[float] = None) -> ModelHandle:
        """Block until the round completes (or timeout) then aggregate
        whatever arrived (reference :177-207)."""
        timeout = Settings.AGGREGATION_TIMEOUT if timeout is None else timeout
        t0 = time.perf_counter()
        deadline = t0 + timeout
        patience = Settings.AGGREGATION_STALL_PATIENCE
        # Sliced wait so the finish condition is RE-EVALUATED on death
        # callbacks (remove_node sets the event) and the JIT stall patience
        # can fire: if nothing has advanced the round for ``patience``
        # seconds while we hold at least one model, aggregate what arrived
        # (Just-in-Time Aggregation) instead of sleeping out the timeout.
        while not self._finish_event.wait(timeout=0.25):
            if time.perf_counter() >= deadline:
                break
            if patience > 0:
                with self._lock:
                    stalled = (
                        bool(self._models)
                        and time.monotonic() - self._last_progress >= patience
                    )
                if stalled:
                    _AGG_STALL.labels(self.node_addr).inc()
                    missing = self.get_missing_models()
                    log.warning(
                        "(%s) aggregation stalled for %.1fs with %s still "
                        "missing — JIT-aggregating what arrived",
                        self.node_addr, patience, missing,
                    )
                    if self.on_stall is not None:
                        try:
                            self.on_stall(missing)
                        except Exception:  # a hook bug must not break the round
                            log.exception("(%s) on_stall hook failed", self.node_addr)
                    break
        wait_s = time.perf_counter() - t0
        _AGG_WAIT.labels(self.node_addr).observe(wait_s)
        SKETCHES.observe("agg_wait", self.node_addr, wait_s)
        with self._lock:
            if not self._models:
                raise RuntimeError("no models to aggregate")
            missing = self.get_missing_models()
            if missing:
                # Timeout path: proceed with partial participation (matches
                # reference behavior of aggregating what it has).
                _AGG_MISSING.labels(self.node_addr).inc()
            _AGG_CONTRIBUTORS.labels(self.node_addr).set(
                len(self.get_aggregated_models())
            )
            for contributor in self.get_aggregated_models():
                SKETCHES.distinct_add(self.node_addr, contributor)
            return self.aggregate(list(self._models))

    def get_partial_model(self, except_nodes: Sequence[str]) -> Optional[ModelHandle]:
        """Model to gossip to a peer that already merged ``except_nodes``.

        With ``partial_aggregation``: merge every stored model the peer has
        not seen into one. Otherwise return one unseen raw model
        (reference :219-270).
        """
        except_set = set(except_nodes)
        with self._lock:
            return self._partial_from(self._models, except_set)

    def get_partial_model_for_round(
        self, round: int, except_nodes: Sequence[str]
    ) -> Optional[ModelHandle]:
        """Round-scoped :meth:`get_partial_model` for overlap drains: serves
        the live table while ``round`` is open, the retired snapshot after
        the boundary, and ``None`` once the aggregator has moved on."""
        except_set = set(except_nodes)
        with self._lock:
            if self._train_set and self._round == round:
                return self._partial_from(self._models, except_set)
            if self._retired is not None and self._retired[0] == round:
                return self._partial_from(self._retired[2], except_set)
            return None

    def _partial_from(
        self, models: List[ModelHandle], except_set: set
    ) -> Optional[ModelHandle]:
        unseen = [m for m in models if not (set(m.contributors) & except_set)]
        if not unseen:
            return None
        if not self.partial_aggregation:
            return unseen[0]
        if len(unseen) == 1:
            return unseen[0]
        merged = self.aggregate(unseen)
        return merged

    # --- rule ---------------------------------------------------------------

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        """Combine models into one; contributors = union, num_samples = sum."""
        raise NotImplementedError

    @staticmethod
    def _merge_metadata(models: List[ModelHandle]) -> tuple[List[str], int]:
        contributors: List[str] = []
        for m in models:
            contributors.extend(m.contributors)
        total = sum(m.get_num_samples() for m in models)
        return sorted(set(contributors)), total
