"""Aggregators: round-scoped accumulators wrapping the jitted kernels."""

from p2pfl_tpu.learning.aggregators.async_buffer import (  # noqa: F401
    AsyncBufferedAggregator,
    staleness_discount,
    staleness_weight,
)
from p2pfl_tpu.learning.aggregators.base import Aggregator  # noqa: F401
from p2pfl_tpu.learning.aggregators.fedavg import (  # noqa: F401
    CanonicalFedAvg,
    FedAvg,
)
from p2pfl_tpu.learning.aggregators.fedmedian import FedMedian  # noqa: F401
from p2pfl_tpu.learning.aggregators.masked import MaskedFedAvg  # noqa: F401
from p2pfl_tpu.learning.aggregators.robust import (  # noqa: F401
    GeometricMedian,
    Krum,
    MultiKrum,
    TrimmedMean,
)
from p2pfl_tpu.learning.aggregators.scaffold import Scaffold  # noqa: F401

__all__ = [
    "Aggregator", "AsyncBufferedAggregator", "CanonicalFedAvg", "FedAvg",
    "FedMedian", "GeometricMedian", "Krum", "MaskedFedAvg", "MultiKrum",
    "TrimmedMean", "Scaffold", "staleness_discount", "staleness_weight",
]
