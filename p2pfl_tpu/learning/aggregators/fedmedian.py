"""FedMedian (Yin et al. 2018) — coordinate-wise median.

The reference declares this rule but its ``aggregate`` raises
NotImplementedError (fedmedian.py:41, dead code); implemented for real here
via the jitted kernel. Median is non-linear, so no partial aggregation.
"""

from __future__ import annotations

from typing import List

from p2pfl_tpu.learning.aggregators.base import Aggregator
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops import aggregation as agg_ops


class FedMedian(Aggregator):
    partial_aggregation = False

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        if not models:
            raise ValueError("nothing to aggregate")
        stacked = agg_ops.tree_stack([m.params for m in models])
        out = agg_ops.fedmedian(stacked)
        contributors, total = self._merge_metadata(models)
        return models[0].build_copy(params=out, contributors=contributors, num_samples=total)
