"""FedAvg (McMahan et al. 2017) — sample-weighted mean.

Parity with reference fedavg.py:29-77, computed by the jitted stacked-pytree
kernel (one fused XLA reduction instead of a per-layer numpy loop).
Supports partial aggregation.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from p2pfl_tpu.learning.aggregators.base import Aggregator
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops import aggregation as agg_ops


class FedAvg(Aggregator):
    partial_aggregation = True

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        if not models:
            raise ValueError("nothing to aggregate")
        stacked = agg_ops.tree_stack([m.params for m in models])
        weights = jnp.asarray([m.get_num_samples() for m in models], jnp.float32)
        out = agg_ops.fedavg(stacked, weights)
        contributors, total = self._merge_metadata(models)
        return models[0].build_copy(params=out, contributors=contributors, num_samples=total)


class CanonicalFedAvg(FedAvg):
    """FedAvg with a run-independent float reduction order — the wire-side
    aggregation rule of the sim↔real parity harness (:mod:`p2pfl_tpu.parity`).

    Plain :class:`FedAvg` merges partial aggregates eagerly en route, so the
    float reduction TREE depends on gossip arrival order: two runs of the
    same seeded scenario (or two nodes within one run) legitimately differ
    in final-bit rounding. This variant makes the aggregate a pure function
    of the contribution set: partial merging is disabled (raw per-sender
    models ride the gossip) and the stack is sorted by contributor before
    the jitted ``fedavg`` reduction — the same kernel, in node-name order,
    which is exactly the node-index order the fused mesh reduces in under
    ``canonical_committee=True``. Bit-exact cross-backend aggregates follow.
    """

    partial_aggregation = False

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        return super().aggregate(
            sorted(models, key=lambda m: sorted(m.contributors))
        )
