"""FedAvg (McMahan et al. 2017) — sample-weighted mean.

Parity with reference fedavg.py:29-77, computed by the jitted stacked-pytree
kernel (one fused XLA reduction instead of a per-layer numpy loop).
Supports partial aggregation.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from p2pfl_tpu.learning.aggregators.base import Aggregator
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops import aggregation as agg_ops


class FedAvg(Aggregator):
    partial_aggregation = True

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        if not models:
            raise ValueError("nothing to aggregate")
        stacked = agg_ops.tree_stack([m.params for m in models])
        weights = jnp.asarray([m.get_num_samples() for m in models], jnp.float32)
        out = agg_ops.fedavg(stacked, weights)
        contributors, total = self._merge_metadata(models)
        return models[0].build_copy(params=out, contributors=contributors, num_samples=total)
