"""SCAFFOLD server-side aggregation (Karimireddy et al. 2020).

Capability parity with reference scaffold.py:29-140: clients ship
``delta_y_i`` / ``delta_c_i`` in the model's additional-info side channel
(written by the learner's in-jit scaffold hook — see
:class:`p2pfl_tpu.learning.learner.JaxLearner`); the aggregator maintains the
simulated global model and the global control variate ``c`` across rounds and
hands ``global_c`` back to learners via ``additional_info['scaffold_server']``.
The update math itself is the jitted :func:`p2pfl_tpu.ops.aggregation.scaffold_update`.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.learning.aggregators.base import Aggregator
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops import aggregation as agg_ops

Pytree = Any


class Scaffold(Aggregator):
    partial_aggregation = False

    def __init__(self, global_lr: float = 1.0, total_population: Optional[int] = None) -> None:
        super().__init__()
        self.global_lr = float(global_lr)
        self.total_population = total_population
        self._global_params: Optional[Pytree] = None
        self._global_c: Optional[Pytree] = None

    def get_required_callbacks(self) -> List[str]:
        return ["scaffold"]

    def _deltas(self, model: ModelHandle, template: Pytree) -> tuple[Pytree, Pytree]:
        info = model.get_info("scaffold")
        if info is None or "delta_y_i" not in info:
            raise ValueError(
                "scaffold aggregation requires models trained with the "
                "'scaffold' learner callback"
            )
        treedef = jax.tree.structure(template)
        dy = jax.tree.unflatten(treedef, [jnp.asarray(a) for a in info["delta_y_i"]])
        dc = jax.tree.unflatten(treedef, [jnp.asarray(a) for a in info["delta_c_i"]])
        return dy, dc

    def aggregate(self, models: List[ModelHandle]) -> ModelHandle:
        if not models:
            raise ValueError("nothing to aggregate")
        template = models[0].params
        if self._global_params is None:
            # Bootstrap the simulated global model: client params minus their
            # deltas reconstruct the common round-start point.
            dy0, _ = self._deltas(models[0], template)
            self._global_params = jax.tree.map(
                lambda p, d: p.astype(jnp.float32) - d, template, dy0
            )
        if self._global_c is None:
            self._global_c = jax.tree.map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), template
            )

        deltas = [self._deltas(m, template) for m in models]
        dy_stack = agg_ops.tree_stack([d[0] for d in deltas])
        dc_stack = agg_ops.tree_stack([d[1] for d in deltas])
        population = float(
            self.total_population if self.total_population is not None else len(models)
        )
        self._global_params, self._global_c = agg_ops.scaffold_update(
            self._global_params,
            self._global_c,
            dy_stack,
            dc_stack,
            jnp.float32(self.global_lr),
            jnp.float32(population),
        )

        contributors, total = self._merge_metadata(models)
        out = models[0].build_copy(
            params=jax.tree.map(
                lambda g, t: g.astype(t.dtype), self._global_params, template
            ),
            contributors=contributors,
            num_samples=total,
        )
        out.add_info(
            "scaffold_server",
            {"global_c": [np.asarray(a) for a in jax.tree.leaves(self._global_c)]},
        )
        # The per-round delta payload is consumed; don't re-gossip it.
        out.additional_info.pop("scaffold", None)
        return out

    def clear(self) -> None:  # keep global state across rounds (reference does)
        super().clear()
