"""Privacy accounting for DP-SGD training.

Conservative Renyi-DP composition for the Gaussian mechanism (Mironov
2017): each DP-SGD step with noise multiplier sigma is a Gaussian
mechanism with sensitivity equal to the clip norm, whose RDP at order
``alpha`` is ``alpha / (2 sigma^2)``; T steps compose additively and the
RDP bound converts to (epsilon, delta)-DP via
``epsilon = T alpha / (2 sigma^2) + log(1/delta) / (alpha - 1)``.

This bound deliberately does NOT claim privacy amplification by
subsampling (which needs assumptions about how batches are formed —
Poisson vs shuffling — that a federation cannot verify for its peers), so
the reported epsilon is a valid upper bound on the true privacy loss for
any batching scheme. No reference analogue — p2pfl has no privacy
machinery.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence


def gaussian_rdp_epsilon(
    noise_multiplier: float,
    steps: int,
    delta: float,
    orders: Optional[Sequence[float]] = None,
) -> float:
    """(epsilon, delta)-DP bound for ``steps`` composed Gaussian mechanisms.

    Minimizes the RDP-to-DP conversion over ``orders``; the analytic
    minimizer ``alpha* = 1 + sqrt(2 sigma^2 log(1/delta) / T)`` is always
    included, so the default grid is only a refinement.

    Returns ``inf`` when ``noise_multiplier <= 0`` (no noise, no guarantee).
    """
    if steps <= 0:
        return 0.0
    if noise_multiplier <= 0.0:
        return math.inf
    if not (0.0 < delta < 1.0):
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    sigma2 = noise_multiplier**2
    log1d = math.log(1.0 / delta)
    alpha_star = 1.0 + math.sqrt(2.0 * sigma2 * log1d / steps)
    candidates = [alpha_star]
    if orders is not None:
        candidates += list(orders)

    def eps(alpha: float) -> float:
        if alpha <= 1.0:
            return math.inf
        return steps * alpha / (2.0 * sigma2) + log1d / (alpha - 1.0)

    return min(eps(a) for a in candidates)


def dp_sgd_privacy_spent(
    noise_multiplier: float,
    clip_norm: float,
    steps: int,
    delta: float = 1e-5,
) -> dict:
    """Summary dict for a completed DP-SGD run (ready for metadata/info)."""
    return {
        "mechanism": "gaussian-rdp-conservative",
        "noise_multiplier": float(noise_multiplier),
        "clip_norm": float(clip_norm),
        "steps": int(steps),
        "delta": float(delta),
        "epsilon": gaussian_rdp_epsilon(noise_multiplier, steps, delta),
    }
