"""Privacy accounting for DP-SGD training.

Conservative Renyi-DP composition for the Gaussian mechanism (Mironov
2017): each DP-SGD step with noise multiplier sigma is a Gaussian
mechanism with sensitivity equal to the clip norm, whose RDP at order
``alpha`` is ``alpha / (2 sigma^2)``; T steps compose additively and the
RDP bound converts to (epsilon, delta)-DP via
``epsilon = T alpha / (2 sigma^2) + log(1/delta) / (alpha - 1)``.

This bound deliberately does NOT claim privacy amplification by
subsampling (which needs assumptions about how batches are formed —
Poisson vs shuffling — that a federation cannot verify for its peers), so
the reported epsilon is a valid upper bound on the true privacy loss for
any batching scheme. No reference analogue — p2pfl has no privacy
machinery.
"""

from __future__ import annotations

import math
import secrets
import warnings
from typing import Optional


def resolve_seed(seed: Optional[int], dp_noise_multiplier: float = 0.0) -> int:
    """Entropy-or-pinned base RNG seed for a trainer (shared by JaxLearner
    and MeshSimulation so the DP seed policy can't drift between modes).

    ``None`` (the default everywhere) draws the base from OS entropy —
    required for a DP-SGD epsilon claim to mean anything, since a noise key
    derived from public values lets an observer regenerate and subtract the
    noise. Pinning an int is an explicit reproducibility opt-in (simulation
    studies, bit-identical resume); with DP enabled it triggers a warning
    because the epsilon claim then only holds while the seed stays secret
    (note: MeshSimulation persists the seed in plaintext checkpoint
    metadata).
    """
    if seed is None:
        return secrets.randbits(31)
    if dp_noise_multiplier > 0.0:
        warnings.warn(
            "DP-SGD with a pinned seed: the Gaussian noise is recomputable "
            "by anyone who knows the seed, so the reported epsilon only "
            "holds while the seed stays secret. Pass seed=None (default) "
            "for entropy-derived noise.",
            stacklevel=3,
        )
    return int(seed)


def gaussian_rdp_epsilon(noise_multiplier: float, steps: int, delta: float) -> float:
    """(epsilon, delta)-DP bound for ``steps`` composed Gaussian mechanisms.

    The conversion ``eps(alpha) = T alpha / (2 sigma^2) + log(1/delta) /
    (alpha - 1)`` is convex in ``alpha`` with the closed-form minimizer
    ``alpha* = 1 + sqrt(2 sigma^2 log(1/delta) / T)``, which is evaluated
    exactly — no order grid is needed for this bound.

    Returns ``inf`` when ``noise_multiplier <= 0`` (no noise, no guarantee)
    and ``0`` when ``steps == 0`` (nothing was released).
    """
    if steps <= 0:
        return 0.0
    if noise_multiplier <= 0.0:
        return math.inf
    if not (0.0 < delta < 1.0):
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    sigma2 = noise_multiplier**2
    log1d = math.log(1.0 / delta)
    alpha = 1.0 + math.sqrt(2.0 * sigma2 * log1d / steps)
    return steps * alpha / (2.0 * sigma2) + log1d / (alpha - 1.0)


def dp_sgd_privacy_spent(
    noise_multiplier: float,
    clip_norm: float,
    steps: int,
    delta: float = 1e-5,
    nonprivate_steps: int = 0,
) -> dict:
    """Summary dict for a completed DP-SGD run (ready for metadata/info).

    ``nonprivate_steps`` counts training steps taken WITHOUT the DP
    mechanism on the same released model: any such step voids the guarantee,
    so epsilon becomes ``inf`` (a non-DP run must never read as epsilon=0).
    """
    eps = gaussian_rdp_epsilon(noise_multiplier, steps, delta)
    if nonprivate_steps > 0:
        eps = math.inf
    return {
        "mechanism": "gaussian-rdp-conservative",
        "noise_multiplier": float(noise_multiplier),
        "clip_norm": float(clip_norm),
        "steps": int(steps),
        "nonprivate_steps": int(nonprivate_steps),
        "delta": float(delta),
        "epsilon": eps,
    }
