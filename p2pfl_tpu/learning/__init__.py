"""Learning layer: datasets, learners, aggregators."""
