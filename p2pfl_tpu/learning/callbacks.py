"""Open callback registry.

Capability parity with the reference CallbackFactory
(p2pfl/learning/frameworks/callback_factory.py:16-101): aggregators declare
required callback *names* (`Aggregator.get_required_callbacks`), learners
resolve names into callback objects at construction, and users can register
their own callbacks per framework.

TPU-first difference: local training is one jitted XLA program, so user
callbacks are *host-side* hooks around the compiled fit (``on_fit_start`` /
``on_fit_end``) rather than per-batch interposition (which would break
compilation). In-jit behaviors (SCAFFOLD's ``g + c - c_i`` correction,
FedProx's proximal term) are implemented natively inside the learners and
exposed under reserved names — learners recognize them before consulting
this registry.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple, Type


class P2PFLCallback:
    """Base host-side callback: subclass and override the hooks.

    The model handle is available as ``learner.get_model()`` inside hooks;
    ``add_info``/``get_info`` on it is the side channel that rides the wire
    (reference: callbacks communicate with aggregators the same way,
    learner.py:126-146).
    """

    name: str = "callback"

    def on_fit_start(self, learner) -> None:  # noqa: ANN001
        """Runs before local training (host side)."""

    def on_fit_end(self, learner) -> None:  # noqa: ANN001
        """Runs after local training, before the model is handed back."""


class CallbackFactory:
    """(framework, name) -> callback class registry."""

    _registry: Dict[Tuple[str, str], Type[P2PFLCallback]] = {}

    @classmethod
    def register(
        cls, framework: str, name: str, callback_cls: Type[P2PFLCallback]
    ) -> None:
        cls._registry[(framework, name)] = callback_cls

    @classmethod
    def registered(cls, framework: str) -> List[str]:
        return sorted(n for fw, n in cls._registry if fw == framework)

    @classmethod
    def create(cls, framework: str, names: List[str]) -> List[P2PFLCallback]:
        """Instantiate callbacks for ``names``; unknown names raise with the
        available set listed (reference raises the same way,
        callback_factory.py:58-76)."""
        out: List[P2PFLCallback] = []
        for name in names:
            key = (framework, name)
            if key not in cls._registry:
                raise ValueError(
                    f"no callback {name!r} registered for framework "
                    f"{framework!r}; available: {cls.registered(framework)}"
                )
            out.append(cls._registry[key]())
        return out

    @classmethod
    def decorator(
        cls, framework: str, name: str
    ) -> Callable[[Type[P2PFLCallback]], Type[P2PFLCallback]]:
        """``@CallbackFactory.decorator("jax", "my-cb")`` registration."""

        def wrap(callback_cls: Type[P2PFLCallback]) -> Type[P2PFLCallback]:
            cls.register(framework, name, callback_cls)
            return callback_cls

        return wrap
