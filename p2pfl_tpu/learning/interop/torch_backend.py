"""PyTorch interop: a torch-backed ModelHandle + Learner for the federation.

Parity with the reference's PyTorch backend (p2pfl/learning/frameworks/
pytorch/lightning_model.py:37-116 state_dict<->numpy, lightning_learner.py:
43-137 fit/evaluate): a ``torch.nn.Module``'s state_dict is the parameter
pytree, so the gossip/aggregation machinery (numpy weight lists over the
PFLT wire format) is shared unchanged with JAX nodes. Training runs eager
torch on host CPU — this is the *migration* path for reference users; the
TPU-native path is :class:`~p2pfl_tpu.learning.learner.JaxLearner`.

Also provides exact weight translation between the torch MLP and the flax
MLP of the model zoo (``Linear.weight`` is ``[out, in]``; flax ``Dense``
kernels are ``[in, out]``), so a federation can be migrated mid-experiment
from torch to the jitted TPU learner without losing the model.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from p2pfl_tpu.learning.dataset.dataset import FederatedDataset
from p2pfl_tpu.learning.dataset.export_strategies import TorchExportStrategy
from p2pfl_tpu.learning.interop.wire import CanonicalWireMixin
from p2pfl_tpu.learning.learner import Learner, LearnerFactory
from p2pfl_tpu.models.model_handle import ModelHandle

try:  # torch (CPU) is in the image; gate anyway per environment rules
    import torch
    from torch import nn

    TORCH_AVAILABLE = True
except ImportError:  # pragma: no cover
    torch = None
    nn = None
    TORCH_AVAILABLE = False


def _require_torch() -> None:
    if not TORCH_AVAILABLE:
        raise ImportError(
            "PyTorch is not available; install torch or use the JAX backend"
        )


def copy_module(module: "nn.Module") -> "nn.Module":
    """Independent clone of a torch module (weights included)."""
    import copy as _copy

    return _copy.deepcopy(module)


class TorchModelHandle(CanonicalWireMixin, ModelHandle):
    """ModelHandle whose parameters are a torch module's state_dict.

    The pytree is ``{name: np.ndarray}`` in state_dict order; ``apply_fn``
    runs the module forward under ``torch.no_grad`` on numpy batches, so
    evaluation works through the same interface as JAX handles.

    ``to_wire`` / ``from_wire`` optionally translate between the native
    state_dict leaves and a *canonical* cross-framework wire layout, letting
    torch nodes join a heterogeneous federation with JAX/keras nodes (the
    reference cannot mix frameworks — its weight lists are framework-layout
    specific). For the MLP twin, :func:`torch_mlp_model` wires the exact
    flax-layout translation in via ``canonical=True``.
    """

    framework = "pytorch"

    def __init__(
        self,
        module: "nn.Module",
        to_wire: Optional[Any] = None,
        from_wire: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        _require_torch()
        self.module = module
        self._to_wire = to_wire
        self._from_wire = from_wire
        params = {
            k: v.detach().cpu().numpy().copy() for k, v in module.state_dict().items()
        }

        def apply_fn(params: Dict[str, np.ndarray], x: np.ndarray) -> np.ndarray:
            self._load(params)
            with torch.no_grad():
                out = module(torch.from_numpy(np.asarray(x, np.float32)))
            return out.numpy()

        super().__init__(params=params, apply_fn=apply_fn, model_def=module, **kwargs)

    def _load(self, params: Optional[Dict[str, np.ndarray]] = None) -> None:
        """Push the handle's numpy params into the live torch module."""
        params = self.params if params is None else params

        def as_tensor(v: np.ndarray) -> "torch.Tensor":
            a = np.ascontiguousarray(v)
            if not a.flags.writeable:  # wire-decoded views are read-only
                a = a.copy()
            return torch.from_numpy(a)

        self.module.load_state_dict({k: as_tensor(v) for k, v in params.items()})

    def pull_from_module(self) -> None:
        """Refresh the handle's numpy params from the live torch module."""
        self.params = {
            k: v.detach().cpu().numpy().copy()
            for k, v in self.module.state_dict().items()
        }

    # canonical wire layout (heterogeneous federations): CanonicalWireMixin

    def build_copy(self, params=None, contributors=None, num_samples=None):
        # Each copy gets its own module: apply_fn pushes the handle's params
        # into its module, so sharing one would let copies clobber each other
        # (and a learner mid-fit) through load_state_dict.
        copy = TorchModelHandle(
            copy_module(self.module),
            to_wire=self._to_wire,
            from_wire=self._from_wire,
            contributors=contributors if contributors is not None else list(self.contributors),
            num_samples=num_samples if num_samples is not None else self.num_samples,
            additional_info=dict(self.additional_info),
        )
        copy.set_parameters(self.params if params is None else params)
        return copy


class TorchLearner(Learner):
    """Eager torch CPU trainer with the reference learner's contract
    (fit updates the handle in place with params + contribution metadata;
    interrupt_fit takes effect between epochs — reference
    lightning_learner.py:98-104 uses trainer.should_stop the same way).

    Supports the ``scaffold`` callback: per-step gradient correction
    ``g + c - c_i`` and delta_y/delta_c emission into ``additional_info``
    (same contract as ``JaxLearner.fit``; reference analogue:
    pytorch/callbacks/scaffold_callback.py:32-155)."""

    SUPPORTED_CALLBACKS: Sequence[str] = ("scaffold",)

    def __init__(
        self,
        model: Optional[ModelHandle] = None,
        data: Optional[FederatedDataset] = None,
        self_addr: str = "unknown-node",
        lr: float = 1e-3,
        batch_size: int = 64,
        seed: int = 0,
        callbacks: Optional[List[str]] = None,
    ) -> None:
        _require_torch()
        super().__init__(model, data, self_addr)
        self.lr = float(lr)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.callbacks = list(callbacks or [])
        from p2pfl_tpu.learning.callbacks import CallbackFactory

        self._callback_objs = CallbackFactory.create(
            self.get_framework(),
            [cb for cb in self.callbacks if cb not in self.SUPPORTED_CALLBACKS],
        )
        self._scaffold = "scaffold" in self.callbacks
        self._scaffold_c_i: Optional[Dict[str, np.ndarray]] = None
        self._interrupt = threading.Event()
        self._fit_count = 0

    def get_framework(self) -> str:
        return "pytorch"

    def interrupt_fit(self) -> None:
        self._interrupt.set()

    def _handle(self) -> TorchModelHandle:
        model = self.get_model()
        if not isinstance(model, TorchModelHandle):
            raise TypeError("TorchLearner requires a TorchModelHandle")
        return model

    def fit(self) -> ModelHandle:
        model = self._handle()
        self._interrupt.clear()
        for cb in self._callback_objs:
            cb.on_fit_start(self)
        t0 = time.monotonic()
        torch.manual_seed(self.seed + self._fit_count)
        fit_idx = self._fit_count
        self._fit_count += 1

        model._load()
        module = model.module
        module.train()
        opt = torch.optim.Adam(module.parameters(), lr=self.lr)
        loss_fn = nn.CrossEntropyLoss(reduction="none")

        # SCAFFOLD state covers the full state_dict (the aggregator
        # unflattens deltas against the handle's params treedef); the
        # per-step correction only touches entries that get gradients.
        corrections: Dict[str, "torch.Tensor"] = {}
        if self._scaffold:
            if model._to_wire is not None:
                raise ValueError(
                    "SCAFFOLD is not supported on canonical-wire (heterogeneous"
                    " federation) handles: control-variate payloads are"
                    " framework-layout specific"
                )
            anchor = {k: np.asarray(v, np.float32).copy() for k, v in model.params.items()}
            c_global = {k: np.zeros_like(a) for k, a in anchor.items()}
            if self._scaffold_c_i is None:
                self._scaffold_c_i = {k: np.zeros_like(a) for k, a in anchor.items()}
            server = model.get_info("scaffold_server", {}) or {}
            if "global_c" in server:
                # Flat list in jax.tree leaf order of the params dict
                # (sorted keys) — the same order the deltas are emitted in.
                c_global = dict(zip(sorted(anchor), (np.asarray(a, np.float32) for a in server["global_c"])))
            corrections = {
                k: torch.from_numpy(c_global[k] - self._scaffold_c_i[k])
                for k in anchor
            }

        total_steps = 0
        for epoch in range(self.epochs):
            if self._interrupt.is_set():
                break
            # Native batching (reference lightning_dataset.py:29-69):
            # a seeded DataLoader, ragged final batch and all — no padding
            # masks. Tuple seed = SeedSequence hash: collision-free across
            # (fit, epoch), matching JaxLearner's fold_in-derived streams.
            loader = self.get_data().export(
                TorchExportStrategy,
                train=True,
                batch_size=self.batch_size,
                seed=(self.seed, fit_idx, epoch),
            )
            losses = []
            for xt, yt in loader:
                if self._interrupt.is_set():
                    break
                opt.zero_grad()
                per = loss_fn(module(xt), yt)
                loss = per.mean()
                loss.backward()
                if self._scaffold:  # drift correction: g + c - c_i
                    for name, p in module.named_parameters():
                        if p.grad is not None:
                            p.grad.add_(corrections[name])
                opt.step()
                losses.append(loss.item())
                total_steps += 1
            if losses:  # interrupt can land before the first batch
                self.report("train_loss", float(np.mean(losses)), step=epoch)

        model.pull_from_module()
        model.set_contribution([self._self_addr], self.get_data().get_num_samples(True))

        if self._scaffold and total_steps > 0:
            # c_i' = c_i - c + (x - y)/(K*lr); deltas ride in additional_info
            # (contract of the Scaffold aggregator; JaxLearner.fit emits the
            # same payload).
            scale = 1.0 / (total_steps * self.lr)
            keys = sorted(anchor)
            final = {k: np.asarray(model.params[k], np.float32) for k in keys}
            delta_y = {k: final[k] - anchor[k] for k in keys}
            c_i_new = {
                k: self._scaffold_c_i[k] - c_global[k] - delta_y[k] * scale
                for k in keys
            }
            delta_c = {k: c_i_new[k] - self._scaffold_c_i[k] for k in keys}
            self._scaffold_c_i = c_i_new
            model.add_info(
                "scaffold",
                {
                    "delta_y_i": [delta_y[k] for k in keys],
                    "delta_c_i": [delta_c[k] for k in keys],
                },
            )

        for cb in self._callback_objs:
            cb.on_fit_end(self)
        self.report("fit_time_s", time.monotonic() - t0)
        return model

    def evaluate(self) -> Dict[str, float]:
        model = self._handle()
        try:
            loader = self.get_data().export(
                TorchExportStrategy, train=False, batch_size=self.batch_size
            )
        except KeyError:
            return {}
        model._load()
        module = model.module
        module.eval()
        loss_fn = nn.CrossEntropyLoss(reduction="none")
        tot_loss = tot_correct = tot_n = 0.0
        with torch.no_grad():
            for xt, yt in loader:
                logits = module(xt)
                per = loss_fn(logits, yt)
                tot_loss += float(per.sum())
                tot_correct += float((logits.argmax(-1) == yt).float().sum())
                tot_n += float(yt.numel())
        tot_n = max(tot_n, 1.0)
        metrics = {"test_loss": tot_loss / tot_n, "test_acc": tot_correct / tot_n}
        for k, v in metrics.items():
            self.report(k, v)
        return metrics


# --- model zoo translation ----------------------------------------------------


def torch_mlp_to_wire(state: Dict[str, np.ndarray]) -> List[np.ndarray]:
    """Canonical (flax-leaf-order) wire layout for the torch MLP twin:
    per Dense layer ``bias, kernel`` with kernels transposed to ``[in, out]``
    — exactly ``jax.tree.leaves`` order of the flax MLP params."""
    nested = torch_state_dict_to_jax_mlp(state)["params"]
    leaves: List[np.ndarray] = []
    for name in sorted(nested):
        leaves += [nested[name]["bias"], nested[name]["kernel"]]
    return leaves


def torch_mlp_from_wire(leaves: Sequence[np.ndarray]) -> Dict[str, np.ndarray]:
    """Inverse of :func:`torch_mlp_to_wire`."""
    nested = {
        f"Dense_{i}": {"bias": leaves[2 * i], "kernel": leaves[2 * i + 1]}
        for i in range(len(leaves) // 2)
    }
    return jax_mlp_params_to_torch({"params": nested})


def torch_mlp_model(
    seed: int = 0,
    hidden_sizes: Sequence[int] = (256, 128),
    out_channels: int = 10,
    in_features: int = 784,
    canonical: bool = False,
) -> TorchModelHandle:
    """Torch twin of :func:`p2pfl_tpu.models.mlp_model` (same architecture as
    the reference's per-framework MLPs, lightning_model.py:118+).

    With ``canonical=True`` the handle speaks the flax-layout wire format so
    it can federate with JAX and keras MLP nodes (heterogeneous federation).
    """
    _require_torch()
    torch.manual_seed(seed)
    layers: List[nn.Module] = [nn.Flatten()]
    prev = in_features
    for h in hidden_sizes:
        layers += [nn.Linear(prev, h), nn.ReLU()]
        prev = h
    layers.append(nn.Linear(prev, out_channels))
    return TorchModelHandle(
        nn.Sequential(*layers),
        to_wire=torch_mlp_to_wire if canonical else None,
        from_wire=torch_mlp_from_wire if canonical else None,
    )


def torch_state_dict_to_jax_mlp(state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Translate a torch MLP state_dict into flax MLP params.

    ``Linear.weight`` is ``[out, in]``; flax ``Dense`` kernels are
    ``[in, out]`` — transpose and re-nest into the linen naming scheme.
    """
    weights = sorted(
        (k for k in state if k.endswith(".weight")),
        key=lambda k: int(k.split(".")[0]),
    )
    params: Dict[str, Any] = {}
    for i, wk in enumerate(weights):
        bk = wk.rsplit(".", 1)[0] + ".bias"
        params[f"Dense_{i}"] = {
            "kernel": np.asarray(state[wk]).T.copy(),
            "bias": np.asarray(state[bk]).copy(),
        }
    return {"params": params}


def jax_mlp_params_to_torch(params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Inverse of :func:`torch_state_dict_to_jax_mlp` for the torch twin
    built by :func:`torch_mlp_model` (nn.Sequential indices: Flatten at 0,
    Linear at 1, 3, 5, ...)."""
    inner = params.get("params", params)
    state: Dict[str, np.ndarray] = {}
    for i, name in enumerate(sorted(inner, key=lambda n: int(n.split("_")[1]))):
        idx = 1 + 2 * i
        state[f"{idx}.weight"] = np.asarray(inner[name]["kernel"]).T.copy()
        state[f"{idx}.bias"] = np.asarray(inner[name]["bias"]).copy()
    return state


if TORCH_AVAILABLE:
    LearnerFactory.register("pytorch", TorchLearner)
