"""Canonical-wire mixin shared by the interop model handles.

Torch and Keras handles speak the flax-layout wire format through
``_to_wire`` / ``_from_wire`` translators so heterogeneous federations can
mix frameworks; the encode/decode choreography around those translators is
identical for every backend and lives here once.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class CanonicalWireMixin:
    """Wire frame encode/decode over ``self._to_wire`` / ``self._from_wire``.

    Expects the host class to be a :class:`~p2pfl_tpu.models.model_handle.
    ModelHandle` subclass with ``_to_wire``/``_from_wire`` attributes
    (``None`` disables translation and falls back to the native layout).
    """

    def encode_parameters(self, compression: Optional[str] = None) -> bytes:
        if self._to_wire is None:
            return super().encode_parameters(compression)
        if "scaffold" in self.additional_info or "scaffold_server" in self.additional_info:
            raise ValueError(
                "SCAFFOLD payloads cannot cross the canonical wire: their "
                "leaves are framework-layout specific (use a homogeneous "
                "federation for the Scaffold aggregator)"
            )
        from p2pfl_tpu.models.model_handle import encode_wire_frame

        return encode_wire_frame(
            [np.asarray(a) for a in self._to_wire(self.params)],
            self.contributors,
            self.num_samples,
            self.additional_info,
            compression,
        )

    def set_parameters(self, params) -> None:
        if self._from_wire is not None and isinstance(
            params, (bytes, bytearray, memoryview)
        ):
            from p2pfl_tpu.models.model_handle import decode_wire_frame

            arrays, meta = decode_wire_frame(params)
            self.contributors = list(meta.get("contributors", self.contributors))
            self.num_samples = int(meta.get("num_samples", self.num_samples))
            self.additional_info.update(meta.get("additional_info", {}))
            return super().set_parameters(self._from_wire(list(arrays)))
        return super().set_parameters(params)
