"""TensorFlow/Keras interop: a keras-backed ModelHandle + Learner.

Parity with the reference's TensorFlow backend (p2pfl/learning/frameworks/
tensorflow/keras_model.py:44-119 get/set_weights<->numpy, keras_learner.py:
36-124 fit/evaluate): ``keras.Model.get_weights()`` is the parameter pytree
(a flat list of numpy arrays), so the gossip/aggregation machinery — numpy
weight lists over the PFLT wire format — is shared unchanged with JAX and
torch nodes. Training runs an eager GradientTape loop on host CPU; this is
the migration path for reference Keras users, while the TPU-native path
stays :class:`~p2pfl_tpu.learning.learner.JaxLearner`.

SCAFFOLD is supported in the same loop (gradient correction ``g + c - c_i``
per step, delta emission at fit end) — exceeding the reference, whose Keras
SCAFFOLD needs a separate optimizer-wrapper class
(tensorflow/callbacks/scaffold_callback.py:30-163).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from p2pfl_tpu.learning.dataset.dataset import FederatedDataset
from p2pfl_tpu.learning.dataset.export_strategies import TensorFlowExportStrategy
from p2pfl_tpu.learning.interop.wire import CanonicalWireMixin
from p2pfl_tpu.learning.learner import Learner, LearnerFactory
from p2pfl_tpu.models.model_handle import ModelHandle

try:  # TF/keras are in the image; gate anyway per environment rules
    import keras
    import tensorflow as tf

    KERAS_AVAILABLE = True
except ImportError:  # pragma: no cover
    keras = None
    tf = None
    KERAS_AVAILABLE = False


def _require_keras() -> None:
    if not KERAS_AVAILABLE:
        raise ImportError(
            "TensorFlow/Keras is not available; install tensorflow or use "
            "the JAX backend"
        )


class KerasModelHandle(CanonicalWireMixin, ModelHandle):
    """ModelHandle whose parameters are a keras model's weight list.

    The pytree is the flat ``get_weights()`` list (stable variable order —
    reference keras_model.py:44-66 uses the same contract); ``apply_fn``
    runs the model forward on numpy batches so evaluation works through the
    same interface as JAX handles.
    """

    framework = "tensorflow"

    def __init__(
        self,
        model: "keras.Model",
        to_wire: Optional[Any] = None,
        from_wire: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        _require_keras()
        self.keras_model = model
        self._to_wire = to_wire
        self._from_wire = from_wire
        params = [np.asarray(w).copy() for w in model.get_weights()]

        def apply_fn(params: List[np.ndarray], x: np.ndarray) -> np.ndarray:
            self._load(params)
            out = model(np.asarray(x, np.float32), training=False)
            return np.asarray(out)

        super().__init__(params=params, apply_fn=apply_fn, model_def=model, **kwargs)

    def _load(self, params: Optional[List[np.ndarray]] = None) -> None:
        """Push the handle's numpy params into the live keras model."""
        params = self.params if params is None else params
        self.keras_model.set_weights([np.asarray(p) for p in params])

    def pull_from_model(self) -> None:
        """Refresh the handle's numpy params from the live keras model."""
        self.params = [np.asarray(w).copy() for w in self.keras_model.get_weights()]

    # canonical wire layout (heterogeneous federations): CanonicalWireMixin

    def build_copy(self, params=None, contributors=None, num_samples=None):
        # Each copy gets its own keras model: apply_fn pushes the handle's
        # params into its model, so sharing one would let copies clobber each
        # other (and a learner mid-fit) through set_weights.
        clone = keras.models.clone_model(self.keras_model)
        if not clone.built and self.keras_model.built:
            clone.build(self.keras_model.input_shape)
        clone.set_weights(self.keras_model.get_weights())
        copy = KerasModelHandle(
            clone,
            to_wire=self._to_wire,
            from_wire=self._from_wire,
            contributors=contributors if contributors is not None else list(self.contributors),
            num_samples=num_samples if num_samples is not None else self.num_samples,
            additional_info=dict(self.additional_info),
        )
        copy.set_parameters(self.params if params is None else params)
        return copy


class KerasLearner(Learner):
    """Eager TF trainer with the reference learner's contract (fit updates
    the handle in place with params + contribution metadata; interrupt_fit
    takes effect between epochs — reference keras_learner.py:36-124).

    Supports the ``scaffold`` callback: per-step gradient correction
    ``g + c - c_i`` and delta_y/delta_c emission into ``additional_info``
    (same contract as ``JaxLearner.fit``).
    """

    SUPPORTED_CALLBACKS: Sequence[str] = ("scaffold",)

    def __init__(
        self,
        model: Optional[ModelHandle] = None,
        data: Optional[FederatedDataset] = None,
        self_addr: str = "unknown-node",
        lr: float = 1e-3,
        batch_size: int = 64,
        seed: int = 0,
        callbacks: Optional[List[str]] = None,
    ) -> None:
        _require_keras()
        super().__init__(model, data, self_addr)
        self.lr = float(lr)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.callbacks = list(callbacks or [])
        from p2pfl_tpu.learning.callbacks import CallbackFactory

        self._callback_objs = CallbackFactory.create(
            self.get_framework(),
            [cb for cb in self.callbacks if cb not in self.SUPPORTED_CALLBACKS],
        )
        self._scaffold = "scaffold" in self.callbacks
        self._scaffold_c_i: Optional[List[np.ndarray]] = None
        self._interrupt = threading.Event()
        self._fit_count = 0

    def get_framework(self) -> str:
        return "tensorflow"

    def interrupt_fit(self) -> None:
        self._interrupt.set()

    def _handle(self) -> KerasModelHandle:
        model = self.get_model()
        if not isinstance(model, KerasModelHandle):
            raise TypeError("KerasLearner requires a KerasModelHandle")
        return model

    def fit(self) -> ModelHandle:
        model = self._handle()
        self._interrupt.clear()
        for cb in self._callback_objs:
            cb.on_fit_start(self)
        t0 = time.monotonic()
        keras.utils.set_random_seed((self.seed + self._fit_count) % 2**31)
        fit_idx = self._fit_count
        self._fit_count += 1

        model._load()
        km = model.keras_model
        opt = keras.optimizers.Adam(self.lr)
        # get_weights() order == km.weights order; grads come per trainable
        # variable, so map each trainable var to its weight-list index.
        weight_index = {id(v): i for i, v in enumerate(km.weights)}

        if self._scaffold:
            if model._to_wire is not None:
                raise ValueError(
                    "SCAFFOLD is not supported on canonical-wire (heterogeneous"
                    " federation) handles: control-variate payloads are"
                    " framework-layout specific"
                )
            anchor = [np.asarray(w, np.float32).copy() for w in km.get_weights()]
            c_global = [np.zeros_like(a) for a in anchor]
            if self._scaffold_c_i is None:
                self._scaffold_c_i = [np.zeros_like(a) for a in anchor]
            server = model.get_info("scaffold_server", {}) or {}
            if "global_c" in server:
                c_global = [np.asarray(a, np.float32) for a in server["global_c"]]
            corrections = [
                tf.constant(c - ci) for c, ci in zip(c_global, self._scaffold_c_i)
            ]

        total_steps = 0
        for epoch in range(self.epochs):
            if self._interrupt.is_set():
                break
            # Native batching (reference keras_dataset.py:29-69): a seeded
            # tf.data pipeline, ragged final batch and all — no padding
            # masks. Tuple seed = SeedSequence hash: collision-free across
            # (fit, epoch), matching JaxLearner's fold_in-derived streams.
            ds = self.get_data().export(
                TensorFlowExportStrategy,
                train=True,
                batch_size=self.batch_size,
                seed=(self.seed, fit_idx, epoch),
            )
            losses = []
            for xt, yt in ds:
                if self._interrupt.is_set():
                    break
                yt = tf.cast(yt, tf.int32)
                with tf.GradientTape() as tape:
                    logits = km(xt, training=True)
                    per = tf.nn.sparse_softmax_cross_entropy_with_logits(
                        labels=yt, logits=logits
                    )
                    loss = tf.reduce_mean(per)
                grads = tape.gradient(loss, km.trainable_variables)
                if self._scaffold:
                    grads = [
                        g + corrections[weight_index[id(v)]]
                        for g, v in zip(grads, km.trainable_variables)
                    ]
                opt.apply_gradients(zip(grads, km.trainable_variables))
                losses.append(float(loss))
                total_steps += 1
            if losses:  # interrupt can land before the first batch
                self.report("train_loss", float(np.mean(losses)), step=epoch)

        model.pull_from_model()
        model.set_contribution([self._self_addr], self.get_data().get_num_samples(True))

        if self._scaffold and total_steps > 0:
            # c_i' = c_i - c + (x - y)/(K*lr); deltas ride in additional_info
            # (contract of the Scaffold aggregator, aggregators/scaffold.py).
            scale = 1.0 / (total_steps * self.lr)
            final = [np.asarray(w, np.float32) for w in model.params]
            delta_y = [f - a for f, a in zip(final, anchor)]
            c_i_new = [
                ci - c - dy * scale
                for ci, c, dy in zip(self._scaffold_c_i, c_global, delta_y)
            ]
            delta_c = [n - o for n, o in zip(c_i_new, self._scaffold_c_i)]
            self._scaffold_c_i = c_i_new
            model.add_info("scaffold", {"delta_y_i": delta_y, "delta_c_i": delta_c})

        for cb in self._callback_objs:
            cb.on_fit_end(self)
        self.report("fit_time_s", time.monotonic() - t0)
        return model

    def evaluate(self) -> Dict[str, float]:
        model = self._handle()
        try:
            ds = self.get_data().export(
                TensorFlowExportStrategy, train=False, batch_size=self.batch_size
            )
        except KeyError:
            return {}
        model._load()
        km = model.keras_model
        tot_loss = tot_correct = tot_n = 0.0
        for xt, yt in ds:
            logits = np.asarray(km(xt, training=False))
            y = np.asarray(yt, np.int64)
            logp = logits - logits.max(-1, keepdims=True)
            logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
            per = -logp[np.arange(len(y)), y]
            tot_loss += float(per.sum())
            tot_correct += float((logits.argmax(-1) == y).sum())
            tot_n += float(len(y))
        tot_n = max(tot_n, 1.0)
        metrics = {"test_loss": tot_loss / tot_n, "test_acc": tot_correct / tot_n}
        for k, v in metrics.items():
            self.report(k, v)
        return metrics


# --- model zoo translation ----------------------------------------------------


def keras_mlp_to_wire(weights: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Canonical (flax-leaf-order) wire layout for the keras MLP twin: per
    Dense layer ``bias, kernel`` (keras kernels are already ``[in, out]``)."""
    leaves: List[np.ndarray] = []
    for i in range(len(weights) // 2):
        leaves += [np.asarray(weights[2 * i + 1]), np.asarray(weights[2 * i])]
    return leaves


def keras_mlp_from_wire(leaves: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Inverse of :func:`keras_mlp_to_wire`."""
    weights: List[np.ndarray] = []
    for i in range(len(leaves) // 2):
        weights += [np.asarray(leaves[2 * i + 1]), np.asarray(leaves[2 * i])]
    return weights


def keras_mlp_model(
    seed: int = 0,
    hidden_sizes: Sequence[int] = (256, 128),
    out_channels: int = 10,
    in_shape: Sequence[int] = (28, 28),
    canonical: bool = False,
) -> KerasModelHandle:
    """Keras twin of :func:`p2pfl_tpu.models.mlp_model` (same architecture as
    the reference's per-framework MLPs, keras_model.py:121-168).

    With ``canonical=True`` the handle speaks the flax-layout wire format so
    it can federate with JAX and torch MLP nodes (heterogeneous federation).
    """
    _require_keras()
    keras.utils.set_random_seed(seed)
    layers: List[Any] = [keras.Input(shape=tuple(in_shape)), keras.layers.Flatten()]
    for h in hidden_sizes:
        layers.append(keras.layers.Dense(h, activation="relu"))
    layers.append(keras.layers.Dense(out_channels))
    return KerasModelHandle(
        keras.Sequential(layers),
        to_wire=keras_mlp_to_wire if canonical else None,
        from_wire=keras_mlp_from_wire if canonical else None,
    )


def keras_weights_to_jax_mlp(weights: Sequence[np.ndarray]) -> Dict[str, Any]:
    """Translate keras MLP weights into flax MLP params. Keras ``Dense``
    kernels are already ``[in, out]`` (flax convention) — only re-nesting
    into the linen naming scheme is needed."""
    params: Dict[str, Any] = {}
    for i in range(len(weights) // 2):
        params[f"Dense_{i}"] = {
            "kernel": np.asarray(weights[2 * i]).copy(),
            "bias": np.asarray(weights[2 * i + 1]).copy(),
        }
    return {"params": params}


def jax_mlp_params_to_keras(params: Dict[str, Any]) -> List[np.ndarray]:
    """Inverse of :func:`keras_weights_to_jax_mlp`."""
    inner = params.get("params", params)
    out: List[np.ndarray] = []
    for name in sorted(inner, key=lambda n: int(n.split("_")[1])):
        out.append(np.asarray(inner[name]["kernel"]).copy())
        out.append(np.asarray(inner[name]["bias"]).copy())
    return out


if KERAS_AVAILABLE:
    LearnerFactory.register("tensorflow", KerasLearner)
