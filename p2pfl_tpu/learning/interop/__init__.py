"""Framework interop: run non-JAX learners inside the federation.

Capability parity with the reference's pluggable ML frameworks
(p2pfl/learning/frameworks/: LightningLearner for torch, KerasLearner for
TF, FlaxLearner — learner_factory.py:24-56): the federation protocol only
moves flat numpy weight lists, so any framework that can load/dump its
parameters as numpy can join. The TPU-native :class:`JaxLearner` stays the
first-class path; interop backends let reference users migrate
incrementally (bring a torch nn.Module or keras.Model today, port to flax
when ready).

Backends register themselves with :class:`LearnerFactory` on import when
their framework is importable (gate pattern per the environment
constraints); both torch (CPU) and TensorFlow/Keras are live in this image.
"""

from p2pfl_tpu.learning.interop.keras_backend import (  # noqa: F401
    KerasLearner,
    KerasModelHandle,
    jax_mlp_params_to_keras,
    keras_mlp_from_wire,
    keras_mlp_model,
    keras_mlp_to_wire,
    keras_weights_to_jax_mlp,
)
from p2pfl_tpu.learning.interop.torch_backend import (  # noqa: F401
    TorchLearner,
    TorchModelHandle,
    jax_mlp_params_to_torch,
    torch_mlp_from_wire,
    torch_mlp_model,
    torch_mlp_to_wire,
    torch_state_dict_to_jax_mlp,
)
