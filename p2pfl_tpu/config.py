"""Global configuration.

Capability parity with the reference's ``Settings`` class-attribute config
(reference: p2pfl/settings.py:8-153), upgraded with typed accessors, an
environment-variable override layer (``P2PFL_TPU_<NAME>``) and a scoped
``overridden()`` context manager — the reference mutates class attributes
directly with no load/save story (SURVEY.md §5 "Config / flag system").
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Iterator


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(f"P2PFL_TPU_{name}")
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def _env_int(name: str, default: int, lo: int, hi: int) -> int:
    """Fail-fast integer env override (same pattern as WIRE_COMPRESSION: a
    typo'd value fails at import, not mid-round in a gossip thread)."""
    try:
        v = int(_env_override(name, default))
    except ValueError:
        raise ValueError(
            f"P2PFL_TPU_{name}={os.environ.get(f'P2PFL_TPU_{name}')!r} "
            "is not an integer"
        ) from None
    if not lo <= v <= hi:
        raise ValueError(f"P2PFL_TPU_{name}={v} must be in [{lo}, {hi}]")
    return v


def _env_float(name: str, default: float, lo: float, hi: float) -> float:
    """Fail-fast float env override with a range check."""
    try:
        v = float(_env_override(name, default))
    except ValueError:
        raise ValueError(
            f"P2PFL_TPU_{name}={os.environ.get(f'P2PFL_TPU_{name}')!r} "
            "is not a number"
        ) from None
    if not lo <= v <= hi:
        raise ValueError(f"P2PFL_TPU_{name}={v} must be in [{lo}, {hi}]")
    return v


def _env_choice(name: str, default: str, choices: tuple) -> str:
    """Fail-fast enumerated env override: the value must be one of
    ``choices`` (a typo'd backend/trace name fails at import, not after a
    100k-vnode warmup)."""
    v = str(_env_override(name, default))
    if v not in choices:
        raise ValueError(
            f"P2PFL_TPU_{name}={v!r} must be one of {sorted(choices)}"
        )
    return v


class Settings:
    """Process-wide tunables.

    Defaults track the reference's (p2pfl/settings.py:34-148) so that round
    pacing, timeouts and gossip rates behave identically out of the box.
    Tests shrink them via :func:`p2pfl_tpu.utils.utils.set_test_settings`.
    """

    # --- transport ---------------------------------------------------------
    GRPC_TIMEOUT: float = _env_override("GRPC_TIMEOUT", 10.0)
    USE_SSL: bool = _env_override("USE_SSL", False)
    SSL_SERVER_KEY: str = _env_override("SSL_SERVER_KEY", "")
    SSL_SERVER_CRT: str = _env_override("SSL_SERVER_CRT", "")
    SSL_CLIENT_KEY: str = _env_override("SSL_CLIENT_KEY", "")
    SSL_CLIENT_CRT: str = _env_override("SSL_CLIENT_CRT", "")
    SSL_CA_CRT: str = _env_override("SSL_CA_CRT", "")
    MAX_MESSAGE_BYTES: int = _env_override("MAX_MESSAGE_BYTES", 1 << 30)  # 1 GiB

    # --- membership / failure detection ------------------------------------
    HEARTBEAT_PERIOD: float = _env_override("HEARTBEAT_PERIOD", 2.0)
    HEARTBEAT_TIMEOUT: float = _env_override("HEARTBEAT_TIMEOUT", 5.0)
    WAIT_HEARTBEATS_CONVERGENCE: float = _env_override("WAIT_HEARTBEATS_CONVERGENCE", 4.0)

    # --- gossip -------------------------------------------------------------
    TTL: int = _env_override("TTL", 10)
    GOSSIP_PERIOD: float = _env_override("GOSSIP_PERIOD", 0.1)
    GOSSIP_MESSAGES_PER_PERIOD: int = _env_override("GOSSIP_MESSAGES_PER_PERIOD", 100)
    GOSSIP_MODELS_PERIOD: float = _env_override("GOSSIP_MODELS_PERIOD", 1.0)
    GOSSIP_MODELS_PER_ROUND: int = _env_override("GOSSIP_MODELS_PER_ROUND", 2)
    GOSSIP_EXIT_ON_X_EQUAL_ROUNDS: int = _env_override("GOSSIP_EXIT_ON_X_EQUAL_ROUNDS", 10)
    AMOUNT_LAST_MESSAGES_SAVED: int = _env_override("AMOUNT_LAST_MESSAGES_SAVED", 100)
    # Bounded retry before a gossip send writes a peer off: the gossip path
    # (protocol._safe_send) retries a failed transport send this many times
    # with exponential backoff (base GOSSIP_SEND_BACKOFF, doubling per
    # attempt) before the neighbor is removed and death callbacks fire. A
    # transient blip no longer dismantles round membership; a real death is
    # still detected in well under a heartbeat timeout.
    GOSSIP_SEND_RETRIES: int = _env_int("GOSSIP_SEND_RETRIES", 2, 0, 16)
    GOSSIP_SEND_BACKOFF: float = _env_float("GOSSIP_SEND_BACKOFF", 0.1, 0.0, 10.0)

    # --- chaos / fault injection --------------------------------------------
    # Deterministic fault plane on the transport send path (chaos/plane.py).
    # All values validated at load with the WIRE_COMPRESSION fail-fast
    # pattern: a typo'd env value raises HERE, not mid-round in a gossip
    # thread. Rates are per-send probabilities in [0, 1]; delays in seconds.
    CHAOS_ENABLED: bool = _env_override("CHAOS_ENABLED", False)
    CHAOS_SEED: int = _env_int("CHAOS_SEED", 0, -(2**63), 2**63 - 1)
    CHAOS_DROP_RATE: float = _env_float("CHAOS_DROP_RATE", 0.0, 0.0, 1.0)
    CHAOS_DELAY_S: float = _env_float("CHAOS_DELAY_S", 0.0, 0.0, 10.0)
    CHAOS_DELAY_JITTER_S: float = _env_float("CHAOS_DELAY_JITTER_S", 0.0, 0.0, 10.0)
    CHAOS_DUPLICATE_RATE: float = _env_float("CHAOS_DUPLICATE_RATE", 0.0, 0.0, 1.0)

    # --- Byzantine defense / wire admission control -------------------------
    # Screening of inbound model-plane frames between decode and
    # aggregator.add_model / apply_frame (comm/admission.py): structural
    # validation against the local model spec, NaN/Inf rejection, and an
    # adaptive update-norm bound (median of recently admitted norms x
    # ADMISSION_NORM_MULT; before enough history exists the bound falls back
    # to the local model's own norm). All values validated at load with the
    # WIRE_COMPRESSION fail-fast pattern.
    ADMISSION_ENABLED: bool = _env_override("ADMISSION_ENABLED", True)
    ADMISSION_NORM_MULT: float = _env_float("ADMISSION_NORM_MULT", 5.0, 1.0, 1e6)
    ADMISSION_NORM_WINDOW: int = _env_int("ADMISSION_NORM_WINDOW", 16, 4, 4096)
    # Cap on the wire-supplied (unauthenticated) num_samples claim: a single
    # peer claiming 10**9 samples would dominate FedAvg's sample weighting
    # (the attack GeometricMedian's unit weights already neutralize). Claims
    # above the cap are clamped, warned about, and counted.
    MAX_CLAIMED_SAMPLES: int = _env_int("MAX_CLAIMED_SAMPLES", 1_000_000, 1, 2**53)

    # --- wire compression ---------------------------------------------------
    # Lossy-but-bounded codec for gossiped weights ("none" | "bf16" | "int8"
    # | "topk", ops/compression.py). Sender-local: the codec spec rides in
    # the frame, so mixed settings across a federation interoperate.
    # Validated at load so a typo'd env value fails here, not mid-round in a
    # gossip thread. "topk" switches the model gossip to the sparse delta
    # wire path (comm/delta.py): round-anchored deltas, error-feedback top-k
    # sparsification, index+values PFLT tensors.
    WIRE_COMPRESSION: str = _env_override("WIRE_COMPRESSION", "none")
    if WIRE_COMPRESSION not in ("none", "bf16", "int8", "topk"):
        raise ValueError(
            f"P2PFL_TPU_WIRE_COMPRESSION={WIRE_COMPRESSION!r} is not one of "
            "('none', 'bf16', 'int8', 'topk')"
        )
    # Fraction of each delta tensor's elements shipped under "topk"
    # (largest-|value| first). 0.1 => ~10x fewer wire bytes with bf16 values
    # + gap-packed u16 indices (ops/serialization.py sparse layout).
    WIRE_TOPK_RATIO: float = _env_override("WIRE_TOPK_RATIO", 0.1)
    if not 0.0 < WIRE_TOPK_RATIO <= 1.0:
        raise ValueError(
            f"P2PFL_TPU_WIRE_TOPK_RATIO={WIRE_TOPK_RATIO!r} must be in (0, 1]"
        )
    # Wire dtype of the transmitted top-k values: "bf16" (default, 2 bytes,
    # quantization error is absorbed by the error-feedback residual),
    # "float32" (exact values, bigger frames), or the linear-quantized
    # "int8" / "int4" layouts (1 byte / packed half-byte per value, symmetric
    # per-tensor scale + zero-point in the PFLT header; the same EF residual
    # absorbs the quantization error bit-exactly — comm/delta.py).
    WIRE_TOPK_VALUES: str = _env_override("WIRE_TOPK_VALUES", "bf16")
    if WIRE_TOPK_VALUES not in ("bf16", "float32", "int8", "int4"):
        raise ValueError(
            f"P2PFL_TPU_WIRE_TOPK_VALUES={WIRE_TOPK_VALUES!r} is not one of "
            "('bf16', 'float32', 'int8', 'int4')"
        )
    # Quantization floor: tensors whose top-k selection keeps fewer than this
    # many values ship bf16 instead of int8/int4 — on a handful of values the
    # scale header plus the coarser grid costs more than it saves (biases,
    # scalar leaves). Validated at load like every other wire knob.
    QUANT_MIN_VALUES: int = _env_int("QUANT_MIN_VALUES", 16, 1, 1 << 20)
    # Frame coalescing: pack all of a model's sparse tensors into ONE
    # length-prefixed multi-tensor body (two byte planes: indices + values)
    # instead of two PFLT arrays per tensor, so per-tensor header/alignment
    # overhead is paid once per frame — and DEFLATE the planes (stdlib zlib,
    # COALESCE_DEFLATE_LEVEL; 0 disables) so gap-packed index bytes compress
    # toward their entropy. Sender-local like WIRE_COMPRESSION: the frame is
    # self-describing, receivers need no configuration.
    COALESCE_ENABLED: bool = _env_override("COALESCE_ENABLED", True)
    COALESCE_DEFLATE_LEVEL: int = _env_int("COALESCE_DEFLATE_LEVEL", 6, 0, 9)
    # Train<->diffuse overlap (stages/base_node.py): model diffusion
    # (partial-model + full-model gossip drains) runs on background threads
    # while the stage machine proceeds to the aggregation wait and the NEXT
    # round's local training — the serialized-gossip headroom PR 6 measured
    # as overlap_fraction ~0. The aggregator retires each round's model
    # table as an immutable snapshot so a draining round can keep serving
    # laggards after the boundary; sparse encodes against the retired round
    # come from the codec's anchor history.
    OVERLAP_TRAIN_DIFFUSE: bool = _env_override("OVERLAP_TRAIN_DIFFUSE", True)
    # Bounded join on leftover diffusion drains at teardown/finish (seconds).
    OVERLAP_DRAIN_JOIN_S: float = _env_float("OVERLAP_DRAIN_JOIN_S", 5.0, 0.0, 300.0)

    # --- elastic async federation (stages/async_node.py) --------------------
    # Buffered asynchronous aggregation in the Papaya/FedBuff style (arxiv
    # 2111.04877): no vote barrier, no fleet-wide aggregation deadline. Each
    # node runs WINDOWS instead of rounds: train locally, broadcast the
    # contribution, fold whatever arrived (staleness-weighted), advance. All
    # values validated at load with the WIRE_COMPRESSION fail-fast pattern.
    #
    # Window fill target: close the window once this many distinct
    # contributors (self included) have been folded. The effective target is
    # min(ASYNC_BUFFER_K, live non-deprioritized participants + 1), so peer
    # deaths shrink it instead of stalling the window.
    ASYNC_BUFFER_K: int = _env_int("ASYNC_BUFFER_K", 3, 1, 4096)
    # Hard cap on one window's wait for the fill target; on expiry the window
    # closes with whatever arrived (own contribution at minimum).
    ASYNC_WINDOW_TIMEOUT: float = _env_float("ASYNC_WINDOW_TIMEOUT", 30.0, 0.1, 3600.0)
    # Staleness decay exponent: a contribution that trained against window
    # w-l is weighted num_samples * (1+l)^-alpha (polynomial staleness
    # discounting, Papaya §4). 0 disables the discount — every contribution
    # weighs its plain sample count, which makes a zero-staleness window
    # bit-exact FedAvg.
    ASYNC_STALENESS_ALPHA: float = _env_float("ASYNC_STALENESS_ALPHA", 0.5, 0.0, 16.0)
    # Contributions lagging more than this many windows are dropped (counted
    # p2pfl_async_dropped_total{reason="stale_limit"}) instead of folded —
    # beyond it the origin model generation is too far gone to help.
    ASYNC_MAX_STALENESS: int = _env_int("ASYNC_MAX_STALENESS", 10, 0, 1 << 20)
    # Sparse-delta anchor history under async: windows advance per node, so a
    # lagging peer's frame may be anchored several windows back — the codec
    # keeps this many recent anchors to decode it (sync uses 1: one round,
    # one anchor).
    ASYNC_ANCHOR_HISTORY: int = _env_int("ASYNC_ANCHOR_HISTORY", 4, 1, 64)
    # Observatory-driven participation (closes PR 5's detect->act loop):
    # peers whose fleet suspect score reaches the gate are not solicited and
    # their contributions are dropped (reason="suspect"); peers whose
    # straggler score reaches the gate are deprioritized — still folded when
    # they arrive, but the window fill target never waits on them. 0 disables
    # the respective gate.
    ASYNC_SUSPECT_GATE: float = _env_float("ASYNC_SUSPECT_GATE", 1.0, 0.0, 1e9)
    ASYNC_STRAGGLER_GATE: float = _env_float("ASYNC_STRAGGLER_GATE", 2.0, 0.0, 1e9)

    # --- privacy plane (p2pfl_tpu/privacy/) ---------------------------------
    # Committee-based distributed secure aggregation + DP-SGD on the gossip
    # wire (DisAgg, arxiv 2605.13708; Papaya, arxiv 2111.04877). All values
    # validated at load with the WIRE_COMPRESSION fail-fast pattern.
    #
    # Masked rounds: committee members exchange pairwise masks (finite-field
    # DH key agreement over the gossip wire -> per-(round, pair) PRG seeds)
    # that cancel EXACTLY in the integer-lattice sum, so no single frame
    # reveals an individual update but the committee sum decodes to the
    # plain aggregate (bit-exact with the same pipeline run maskless).
    PRIVACY_SECAGG: bool = _env_override("PRIVACY_SECAGG", False)
    # Fraction of each delta tensor shipped on masked rounds. Masked frames
    # use a SHARED pseudorandom support (rand-k from public round state, so
    # indices cost zero wire bytes and pairwise masks cancel position-wise);
    # per-sender top-k supports cannot cancel and are unusable here.
    PRIVACY_MASK_RATIO: float = _env_float("PRIVACY_MASK_RATIO", 0.1, 1e-6, 1.0)
    # Ring width of the masked integer lattice (frame bytes/value = bits/8;
    # 12-bit values pack two-per-three-bytes on the wire — 1.5 B/value, which
    # is what keeps masked frames under the topk+quant codec's byte budget
    # while qmax stays int8-class resolution). The committee sum must
    # decode: n * qmax * headroom < 2^(bits-1).
    PRIVACY_RING_BITS: int = _env_int("PRIVACY_RING_BITS", 12, 12, 32)
    if PRIVACY_RING_BITS not in (12, 16, 32):
        raise ValueError(
            f"P2PFL_TPU_PRIVACY_RING_BITS={PRIVACY_RING_BITS} is not one of "
            "(12, 16, 32)"
        )
    # Per-coordinate clamp applied at the SENDER before lattice quantization
    # (clipping-at-sender: the committee cannot norm-screen masked frames, so
    # the bound is enforced where the plaintext still exists). Sets the
    # lattice scale (RANGE / qmax): smaller range = finer quantization of
    # the typical tiny per-coordinate delta; clamp overflow lands in the EF
    # residual and ships next round, like every other codec error.
    PRIVACY_VALUE_RANGE: float = _env_float("PRIVACY_VALUE_RANGE", 0.25, 1e-9, 1e3)
    # Committee-side range check on the UNMASKED aggregate: reject the masked
    # round when the decoded lattice sum exceeds committee_size * qmax (only
    # a ring wrap — a hostile or unrepaired mask share — can get there).
    PRIVACY_RANGE_MULT: float = _env_float("PRIVACY_RANGE_MULT", 1.0, 1.0, 1e6)
    # Hard cap on masked-committee size (decode-bound fail-fast: beyond it
    # qmax degrades below 1 and the lattice cannot carry a value at all).
    PRIVACY_MAX_COMMITTEE: int = _env_int("PRIVACY_MAX_COMMITTEE", 256, 2, 16384)
    # Bounded wait for committee pubkeys during session bootstrap (seconds).
    PRIVACY_KEY_WAIT_S: float = _env_float("PRIVACY_KEY_WAIT_S", 10.0, 0.0, 600.0)
    # DP-SGD defaults picked up by JaxLearner when not set per-learner:
    # per-example L2 clip (0 disables DP) and Gaussian noise multiplier.
    PRIVACY_DP_CLIP: float = _env_float("PRIVACY_DP_CLIP", 0.0, 0.0, 1e6)
    PRIVACY_DP_SIGMA: float = _env_float("PRIVACY_DP_SIGMA", 0.0, 0.0, 1e3)
    # Target delta of the reported (epsilon, delta) privacy budget.
    PRIVACY_DELTA: float = _env_float("PRIVACY_DELTA", 1e-5, 1e-12, 0.5)

    # --- durable recovery plane (management/checkpoint.py NodeJournal,
    # stages/recovery.py, comm heal detection) ------------------------------
    # Crash-restart resume, partition-heal reconciliation and quorum-aware
    # degraded mode. All values validated at load with the WIRE_COMPRESSION
    # fail-fast pattern.
    #
    # Quorum fraction of the session's known membership that must be live
    # (self included) for a node to make vote/window progress. Below it the
    # node PARKS: no round progress, state journaled, heartbeats keep
    # running — it unparks when membership recovers instead of burning a
    # vote timeout per unwinnable round. 0 disables parking.
    RECOVERY_QUORUM_FRACTION: float = _env_float("RECOVERY_QUORUM_FRACTION", 0.0, 0.0, 1.0)
    # Poll slice while parked (early-stop and quorum re-checked per slice).
    RECOVERY_PARK_POLL_S: float = _env_float("RECOVERY_PARK_POLL_S", 0.5, 0.05, 60.0)
    # Hard cap on one park: on expiry the node unparks and proceeds degraded
    # (a federation that never heals must still terminate). 0 = park forever.
    RECOVERY_PARK_MAX_S: float = _env_float("RECOVERY_PARK_MAX_S", 300.0, 0.0, 86400.0)
    # Write-ahead node-state journal: snapshots retained / cadence in rounds.
    RECOVERY_JOURNAL_KEEP: int = _env_int("RECOVERY_JOURNAL_KEEP", 3, 1, 100)
    RECOVERY_JOURNAL_EVERY: int = _env_int("RECOVERY_JOURNAL_EVERY", 1, 1, 1000)
    # Partition-heal reconciliation: rounds/windows of lead before the ahead
    # side of a healed split sends its round anchor as a dense catch-up.
    RECOVERY_RECONCILE_MIN_LEAD: int = _env_int("RECOVERY_RECONCILE_MIN_LEAD", 1, 1, 1000)
    # Min seconds between reconcile pings to the same recovered peer (heals
    # fire from several paths at once; the exchange is idempotent but cheap
    # only when rate-limited).
    RECOVERY_RECONCILE_COOLDOWN_S: float = _env_float(
        "RECOVERY_RECONCILE_COOLDOWN_S", 1.0, 0.0, 3600.0
    )
    # Heal detection: the heartbeater's sweep re-probes peers that left the
    # table via FAILURE paths (heartbeat timeout, send write-off) — a healed
    # partition cannot re-announce itself on beats alone, because the first
    # failed send already dropped the only link that would carry them.
    # Probes respect chaos partitions/crashes and fire the recovery
    # listeners only on a confirmed round-trip. RECOVERY_PROBE_MAX bounds
    # the addresses probed per sweep.
    RECOVERY_PROBE_ENABLED: bool = _env_override("RECOVERY_PROBE_ENABLED", True)
    RECOVERY_PROBE_MAX: int = _env_int("RECOVERY_PROBE_MAX", 8, 1, 1024)

    # --- engine supervisor (population/supervisor.py) -----------------------
    # Preemption-proof wrapper around the fused engines' chunk-launch loops:
    # write-ahead journaling on the crash-safe FLCheckpointer, bounded
    # retry/backoff resume from the last journal, graceful degradation, and
    # deterministic host-fault drills. The fused half of the wire path's
    # durable-recovery plane above.
    #
    # Journal cadence in CHUNKS (scan launches), not rounds — the unit a
    # host fault can lose. 1 = journal after every chunk.
    SUPERVISOR_JOURNAL_EVERY: int = _env_int("SUPERVISOR_JOURNAL_EVERY", 1, 1, 1000)
    # Retries per failed chunk before the degrade ladder engages. Each retry
    # rolls back to the last journal and replays the seeded cohort/window
    # stream from its absolute cursor, so a successful retry is bit-exact.
    SUPERVISOR_MAX_RETRIES: int = _env_int("SUPERVISOR_MAX_RETRIES", 3, 0, 100)
    # Exponential backoff base between retries (sleep = base * 2**attempt).
    SUPERVISOR_BACKOFF_S: float = _env_float("SUPERVISOR_BACKOFF_S", 0.1, 0.0, 300.0)
    # Degradation ladder when retries at the current shape are exhausted:
    # "off" parks immediately; "chunks" shrinks rounds/windows-per-call
    # toward 1; "cohort" additionally halves cohort K within the plan's
    # min_size floor before parking with state readable.
    SUPERVISOR_DEGRADE: str = _env_choice(
        "SUPERVISOR_DEGRADE", "cohort", ("off", "chunks", "cohort")
    )

    # --- learning round -----------------------------------------------------
    TRAIN_SET_SIZE: int = _env_override("TRAIN_SET_SIZE", 4)
    VOTE_TIMEOUT: float = _env_override("VOTE_TIMEOUT", 60.0)
    AGGREGATION_TIMEOUT: float = _env_override("AGGREGATION_TIMEOUT", 300.0)
    # Just-in-Time partial aggregation (arxiv 2208.09740): if no new
    # contribution (or death) has advanced the round for this many seconds
    # while contributions are still missing, aggregate whatever arrived
    # instead of sleeping out AGGREGATION_TIMEOUT. Must sit well above
    # normal fit-time variance (it only fires on a genuine stall — lost
    # progress announcements, unreachable stragglers). 0 disables.
    AGGREGATION_STALL_PATIENCE: float = _env_float(
        "AGGREGATION_STALL_PATIENCE", 60.0, 0.0, 3600.0
    )

    # --- nodes-mode learner executor ----------------------------------------
    # Concurrent fit/eval jobs across all in-process nodes (the reference
    # sizes its Ray actor pool from cluster resources,
    # simulation/utils.py:33-96). 0 disables wrapping (inline fit).
    EXECUTOR_MAX_WORKERS: int = _env_override(
        "EXECUTOR_MAX_WORKERS", max(2, min(32, os.cpu_count() or 4))
    )

    # --- observability ------------------------------------------------------
    LOG_LEVEL: str = _env_override("LOG_LEVEL", "INFO")
    LOG_DIR: str = _env_override("LOG_DIR", "logs")
    RESOURCE_MONITOR_PERIOD: float = _env_override("RESOURCE_MONITOR_PERIOD", 1.0)
    # Federation observatory (telemetry/digest.py + observatory.py): each
    # node piggybacks a compact health digest on every DIGEST_EVERY_BEATS-th
    # heartbeat; peers assemble the digests into a fleet view with derived
    # straggler/suspect/link scores. Disabling emission keeps the node fully
    # wire-compatible — absent digests are tolerated by every receiver.
    DIGEST_ENABLED: bool = _env_override("DIGEST_ENABLED", True)
    DIGEST_EVERY_BEATS: int = _env_int("DIGEST_EVERY_BEATS", 1, 1, 1000)
    # Sketch-native observability (telemetry/sketches.py): digests v2 carry
    # mergeable relative-error quantile sketches (step-time, staleness,
    # update-norm, agg-wait) instead of raw scalars only, so fleet quantiles
    # compose from gossip at any population. SKETCH_REL_ERR bounds the
    # relative error of every quantile estimate; SKETCH_MAX_BINS caps one
    # sketch's in-memory buckets (lowest buckets collapse past it — upper
    # quantiles keep the guarantee).
    SKETCH_REL_ERR: float = _env_float("SKETCH_REL_ERR", 0.02, 0.001, 0.5)
    SKETCH_MAX_BINS: int = _env_int("SKETCH_MAX_BINS", 128, 16, 4096)
    # Observatory memory bounds (the observatory must stay sublinear in
    # population): peers whose last digest is older than OBS_PEER_TTL
    # seconds are EVICTED outright — dropped from the per-peer table, the
    # round-entry book, and every scoring statistic (a crashed peer must not
    # skew straggler z-scores forever), counted p2pfl_fed_evicted_total.
    # 0 disables eviction. Beyond OBS_MAX_TRACKED live peers, new peers'
    # digests fold into merged fleet sketches + a bounded worst-straggler
    # candidate table instead of growing the per-peer dict.
    OBS_PEER_TTL: float = _env_float("OBS_PEER_TTL", 120.0, 0.0, 86400.0)
    OBS_MAX_TRACKED: int = _env_int("OBS_MAX_TRACKED", 512, 8, 1 << 20)
    # Minimum seconds between Prometheus-gauge refreshes of the derived
    # fleet scores (each refresh is O(live peers); at population scale a
    # per-beat refresh would be quadratic). 0 = refresh on every ingest
    # (the n<=8 test-friendly default).
    OBS_REFRESH_MIN_S: float = _env_float("OBS_REFRESH_MIN_S", 0.0, 0.0, 60.0)
    # Flight recorder (telemetry/flight_recorder.py): bounded per-node ring
    # of structured events, dumped to artifacts/flightrec_<node>.json on
    # crash / aggregation-stall / workflow failure.
    FLIGHTREC_CAPACITY: int = _env_int("FLIGHTREC_CAPACITY", 512, 1, 1 << 20)
    # Span-buffer bound for the process-wide tracer (telemetry/tracing.py):
    # oldest spans are evicted past this (counted in
    # p2pfl_trace_spans_dropped_total) so multi-day experiments cannot grow
    # the span tree without limit.
    TRACE_MAX_SPANS: int = _env_int("TRACE_MAX_SPANS", 65536, 256, 1 << 22)
    # Trajectory ledger (telemetry/ledger.py): deterministic, seed-stable,
    # append-only structured events — round/window open+close, contribution
    # folded, aggregate committed (content hash), membership transitions,
    # chaos scenario steps, admission rejections — emitted identically by
    # the wire path and the fused mesh so scripts/parity_diff.py can
    # certify that both backends describe the same federation. Disabling
    # turns every emission point into a cheap no-op; the ring is bounded by
    # LEDGER_CAPACITY (oldest events evicted); LEDGER_SNAPSHOT_TAIL is how
    # many recent events ride the observatory snapshot for fed_top's
    # PARITY panel.
    LEDGER_ENABLED: bool = _env_override("LEDGER_ENABLED", True)
    LEDGER_CAPACITY: int = _env_int("LEDGER_CAPACITY", 4096, 16, 1 << 22)
    LEDGER_SNAPSHOT_TAIL: int = _env_int("LEDGER_SNAPSHOT_TAIL", 8, 0, 1024)
    # Sim↔real parity gate shape (scripts/parity_check.py): nodes/rounds of
    # the seeded scenario run on BOTH backends; bench.py --parity uses its
    # own 8-node shape.
    PARITY_NODES: int = _env_int("PARITY_NODES", 3, 2, 64)
    PARITY_ROUNDS: int = _env_int("PARITY_ROUNDS", 2, 1, 100)
    PARITY_SEED: int = _env_int("PARITY_SEED", 1234, 0, 2**31 - 1)
    # Device observatory (in-scan telemetry for the fused population
    # engines): when enabled, the compiled round/window body emits a
    # static-shape auxiliary stream — cohort loss, fold-weight mass,
    # update-norm sketch buckets, NaN/Inf + loss-divergence tripwire flags —
    # that the host folds into the SKETCHES registry and the p2pfl_mesh_*
    # Prometheus family per chunk. The aux stream rides only the scan's
    # outputs: the parameter math is bit-identical with telemetry on or off.
    DEVOBS_ENABLED: bool = _env_override("DEVOBS_ENABLED", True)
    # What a tripped health guard does at the next chunk boundary:
    # "abort" raises (state already safe — the trip is detected between
    # chunks, after donation completed), "park" stops launching chunks and
    # returns the partial result with the trip stamped on it.
    DEVOBS_TRIP_ACTION: str = _env_choice("DEVOBS_TRIP_ACTION", "abort", ("abort", "park"))
    # Leading timed chunks wrapped in device_trace_window (per-chunk device
    # profiles + memory watermarks); 0 disables per-chunk profiling.
    DEVOBS_PROFILE_CHUNKS: int = _env_int("DEVOBS_PROFILE_CHUNKS", 1, 0, 1024)
    # Loss-divergence tripwire: trip when a round's cohort loss exceeds this
    # multiple of the best (lowest) finite loss seen so far in the chunk.
    DEVOBS_LOSS_DIVERGE_MULT: float = _env_float(
        "DEVOBS_LOSS_DIVERGE_MULT", 100.0, 1.0, 1e9
    )
    # TTL for the cached live-array byte sum backing device_mem_bytes():
    # summing jax.live_arrays() on every digest beat is O(live arrays).
    DEVOBS_MEM_TTL_S: float = _env_float("DEVOBS_MEM_TTL_S", 5.0, 0.0, 3600.0)
    # Seeded fault injection for the tripwire gates (bench --devobs NaN arm,
    # make devobs-check): corrupt the aggregate with NaN at this ABSOLUTE
    # round/window index inside the compiled scan. -1 (default) disables —
    # and with it the injection branch is not even traced.
    DEVOBS_NAN_INJECT_ROUND: int = _env_int("DEVOBS_NAN_INJECT_ROUND", -1, -1, 1 << 30)
    # bench.py --devobs shape (overridable for CI-scale smoke runs): the
    # telemetry-overhead arm runs this population twice (devobs on vs off,
    # same seed) and gates the on/off wall ratio + params-hash equality.
    DEVOBS_BENCH_NODES: int = _env_int("DEVOBS_BENCH_NODES", 100_000, 8, 1 << 24)
    DEVOBS_BENCH_ROUNDS: int = _env_int("DEVOBS_BENCH_ROUNDS", 8, 2, 10_000)
    DEVOBS_BENCH_COHORT: float = _env_float("DEVOBS_BENCH_COHORT", 0.01, 0.0, 1.0)
    # Max telemetry-on / telemetry-off wall-clock ratio the bench accepts
    # (ISSUE acceptance: <5% overhead at the population shape).
    DEVOBS_BENCH_MAX_OVERHEAD: float = _env_float(
        "DEVOBS_BENCH_MAX_OVERHEAD", 1.05, 1.0, 10.0
    )
    # Diagnosis plane (telemetry/bundle.py + telemetry/diagnosis.py): RUN_ID
    # pins the federation-wide run id instead of minting one per launch —
    # CI replay harnesses (make doctor-check) use it to make evidence-bundle
    # manifests byte-comparable across reruns. Empty (default) mints a
    # seeded-deterministic body with a host-unique suffix at engine launch
    # or set_start_learning.
    RUN_ID: str = _env_override("RUN_ID", "")
    # Master switch for evidence-bundle capture: when off, the failure hooks
    # (workflow crash, supervisor park, devobs trip, campaign violation,
    # bench assertion) skip bundle writes entirely — zero happy-path cost.
    DOCTOR_BUNDLE_ENABLED: bool = _env_override("DOCTOR_BUNDLE_ENABLED", True)
    # Where bundle_<run_id>/ directories land (and where the fed_top
    # DIAGNOSIS banner's incident.json is refreshed).
    DOCTOR_BUNDLE_DIR: str = _env_override("DOCTOR_BUNDLE_DIR", "artifacts")
    # Findings below this confidence are dropped from incident reports —
    # the rule catalog's corroboration bonuses live above it, lone weak
    # signals below.
    DOCTOR_MIN_CONFIDENCE: float = _env_float("DOCTOR_MIN_CONFIDENCE", 0.5, 0.0, 1.0)

    # --- population-scale engine (population/) ------------------------------
    # Cohort sampling (Papaya, arxiv 2111.04877): each round/window solicits
    # only a seeded hash-sampled cohort instead of every live peer, so
    # fan-in stays sublinear in fleet size. The sampler is order-independent
    # (score = blake2b(seed:round:name)) so the fused mesh and the wire
    # schedulers derive the SAME cohort from the same (seed, round, names)
    # — which is what lets parity_diff gate a cohort-sampled scenario.
    # ENABLED gates the wire schedulers (sync vote + async solicitation);
    # the fused backend takes explicit committee schedules instead.
    POP_COHORT_ENABLED: bool = _env_override("POP_COHORT_ENABLED", False)
    POP_COHORT_FRACTION: float = _env_float("POP_COHORT_FRACTION", 1.0, 0.0, 1.0)
    POP_COHORT_MIN: int = _env_int("POP_COHORT_MIN", 1, 1, 1 << 20)
    POP_COHORT_SEED: int = _env_int("POP_COHORT_SEED", 0, 0, 2**31 - 1)
    # Seeded availability churn (population/scenarios.py): per-(round, node)
    # hash-derived down probability, applied identically by both backends as
    # a COHORT-ELIGIBILITY filter (a down node is never solicited; real node
    # death remains the wire-only chaos plane).
    POP_CHURN_RATE: float = _env_float("POP_CHURN_RATE", 0.0, 0.0, 1.0)
    # bench.py --population shape (overridable for CI-scale smoke runs).
    POP_BENCH_NODES: int = _env_int("POP_BENCH_NODES", 100_000, 8, 1 << 24)
    POP_BENCH_ROUNDS: int = _env_int("POP_BENCH_ROUNDS", 10, 1, 10_000)
    POP_BENCH_COHORT: float = _env_float("POP_BENCH_COHORT", 0.01, 0.0, 1.0)

    # --- campaign harness (campaigns/) --------------------------------------
    # Seeded scenario-matrix campaigns: CAMPAIGN_SEED roots the sampler (one
    # seed => one reproducible campaign of scenarios), CAMPAIGN_SCENARIOS is
    # the bench.py --campaign sample size (>= 20 per the robustness
    # acceptance; every scenario runs on BOTH backends under the parity
    # gate), CAMPAIGN_CHECK_SCENARIOS the small `make campaign-check` replay
    # subset diffed against the committed baseline.
    CAMPAIGN_SEED: int = _env_int("CAMPAIGN_SEED", 20260806, 0, 2**31 - 1)
    CAMPAIGN_SCENARIOS: int = _env_int("CAMPAIGN_SCENARIOS", 20, 1, 10_000)
    CAMPAIGN_CHECK_SCENARIOS: int = _env_int("CAMPAIGN_CHECK_SCENARIOS", 4, 1, 10_000)
    # Aggregation stall patience for campaign wire runs with an adaptive
    # adversary: rejected-stage rounds NEVER deliver the adversary's
    # contribution, so honest aggregators must break out of the
    # all-contributions wait quickly (the normal 60 s parity patience would
    # stretch a 20-scenario campaign by hours). Small but > the in-memory
    # gossip propagation time at campaign scale (n <= 12).
    CAMPAIGN_STALL_PATIENCE: float = _env_float(
        "CAMPAIGN_STALL_PATIENCE", 2.0, 0.1, 3600.0
    )

    # --- async population windows (population/async_engine.py) --------------
    # FedBuff-style windows over the fused mesh: each scanned step is one
    # WINDOW, fill target = FILL_FRACTION of the solicited cohort K (clamped
    # to >= 1). A window past its fill target closes "fill"; one that sat
    # TIMEOUT_TICKS virtual ticks without reaching it closes "timeout"; an
    # EMPTY window is tolerated for STALL_PATIENCE consecutive windows (the
    # backpressure rule of arxiv 2208.09740) before closing "stall" with the
    # global carried unchanged. MAX_LAG bounds both the staleness-anchor
    # history ring and the fold (contributions older are dropped+counted),
    # mirroring ASYNC_MAX_STALENESS on the wire.
    ASYNCPOP_FILL_FRACTION: float = _env_float("ASYNCPOP_FILL_FRACTION", 0.5, 0.0, 1.0)
    ASYNCPOP_TIMEOUT_TICKS: int = _env_int("ASYNCPOP_TIMEOUT_TICKS", 8, 1, 1 << 16)
    ASYNCPOP_STALL_PATIENCE: int = _env_int("ASYNCPOP_STALL_PATIENCE", 4, 1, 1 << 16)
    ASYNCPOP_MAX_LAG: int = _env_int("ASYNCPOP_MAX_LAG", 4, 1, 64)
    # Population-state dtype for the async engine's model/optimizer stacks:
    # bfloat16 halves the dominant per-vnode memory term when pushing the
    # vnode ceiling (bench ceiling arm); float32 is the parity default (the
    # wire path is f32, so bf16 state is NOT bit-comparable).
    ASYNCPOP_STATE_DTYPE: str = _env_choice(
        "ASYNCPOP_STATE_DTYPE", "float32", ("float32", "bfloat16")
    )
    # Arrival-trace process feeding window fill targets + per-vnode delays
    # (population/arrivals.py): uniform (constant intensity), diurnal
    # (sinusoid of period ARRIVAL_TRACE_PERIOD windows), regional (three
    # phase-shifted diurnal waves), flash (ARRIVAL_FLASH_MULT x spike over
    # the middle fifth of the run).
    ASYNCPOP_ARRIVAL_TRACE: str = _env_choice(
        "ASYNCPOP_ARRIVAL_TRACE", "uniform",
        ("uniform", "diurnal", "regional", "flash"),
    )
    ARRIVAL_TRACE_PERIOD: int = _env_int("ARRIVAL_TRACE_PERIOD", 24, 2, 1 << 16)
    ARRIVAL_FLASH_MULT: float = _env_float("ARRIVAL_FLASH_MULT", 10.0, 1.0, 1000.0)
    # bench.py --asyncpop shape (overridable for CI-scale smoke runs);
    # CEILING caps the vnode-ceiling doubling probe.
    ASYNCPOP_BENCH_NODES: int = _env_int("ASYNCPOP_BENCH_NODES", 100_000, 8, 1 << 24)
    ASYNCPOP_BENCH_WINDOWS: int = _env_int("ASYNCPOP_BENCH_WINDOWS", 12, 1, 10_000)
    ASYNCPOP_BENCH_COHORT: float = _env_float("ASYNCPOP_BENCH_COHORT", 0.01, 0.0, 1.0)
    ASYNCPOP_BENCH_CEILING: int = _env_int(
        "ASYNCPOP_BENCH_CEILING", 1_000_000, 8, 1 << 26
    )

    # --- bench TPU probe ----------------------------------------------------
    # Per-attempt timeout for the throwaway TPU probe subprocess bench.py
    # spawns before committing to the chip (BENCH_r03-r05 regression: hung
    # tunnel probes silently fell back to CPU). Validated here so a typo'd
    # value fails at import; bench.py retries one extra probe on timeout and
    # stamps fallback_reason either way so perf_diff's backend refusal fires.
    BENCH_PROBE_TIMEOUT: float = _env_float("BENCH_PROBE_TIMEOUT", 90.0, 1.0, 3600.0)
    # Skip the probe + wait ladder entirely and assume this backend ("cpu"
    # or "tpu"; empty = probe as usual). bench.py also self-propagates the
    # first probe's verdict through this knob into its per-arm subprocesses
    # so one invocation probes ONCE — fallback_reason is still stamped
    # ("assumed_backend") so perf_diff's backend refusal keeps working.
    BENCH_ASSUME_BACKEND: str = _env_choice(
        "BENCH_ASSUME_BACKEND", "", ("", "cpu", "tpu")
    )

    # Continuous performance profiling (management/profiler.py): when set,
    # the stage machine captures ONE windowed jax.profiler device trace of
    # a fit per process under this directory (capture-once, never-raising),
    # and MeshSimulation.run(profile_dir=...) defaults to it. Empty
    # disables capture — the production default.
    PERF_TRACE_DIR: str = _env_override("PERF_TRACE_DIR", "")

    # --- TPU execution ------------------------------------------------------
    # Default dtype for training compute. bfloat16 feeds the MXU at full rate;
    # aggregation math stays float32 for parity with the reference's numpy.
    COMPUTE_DTYPE: str = _env_override("COMPUTE_DTYPE", "bfloat16")
    # Disable device-mesh simulation (mirror of the reference's DISABLE_RAY).
    DISABLE_MESH: bool = _env_override("DISABLE_MESH", False)
    # Disable the native (C++) PFLT wire codec and use the byte-identical
    # pure-Python fallback. Previously a raw os.environ read inside
    # native/__init__.py (P2PFL_TPU_NO_NATIVE=1 exactly); routed through the
    # validated env layer so every accepted bool spelling works and the C5
    # drift checker (make analyze) holds all config at this choke point.
    NO_NATIVE: bool = _env_override("NO_NATIVE", False)

    @classmethod
    def snapshot(cls) -> dict[str, Any]:
        """Copy of all current settings (upper-case attributes only)."""
        return {k: getattr(cls, k) for k in dir(cls) if k.isupper()}

    @classmethod
    def restore(cls, snap: dict[str, Any]) -> None:
        for k, v in snap.items():
            setattr(cls, k, v)

    @classmethod
    @contextlib.contextmanager
    def overridden(cls, **kwargs: Any) -> Iterator[None]:
        """Scoped settings override (mainly for tests)."""
        snap = cls.snapshot()
        try:
            for k, v in kwargs.items():
                if k not in snap:
                    raise AttributeError(f"unknown setting {k!r}")
                setattr(cls, k, v)
            yield
        finally:
            cls.restore(snap)
