"""Command ABC + dispatcher (reference communication/commands/command.py:23-43)."""

from __future__ import annotations

import abc
import threading
from typing import Any, Dict, List, Optional


class Command(abc.ABC):
    """A named message handler."""

    @staticmethod
    @abc.abstractmethod
    def get_name() -> str: ...

    @abc.abstractmethod
    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None: ...


class CommandDispatcher:
    """Thread-safe name -> Command registry used by transport servers
    (reference grpc_server.py:186-196 dispatch)."""

    def __init__(self) -> None:
        self._commands: Dict[str, Command] = {}
        self._lock = threading.Lock()

    def register(self, commands: List[Command]) -> None:
        with self._lock:
            for cmd in commands:
                self._commands[cmd.get_name()] = cmd

    def get(self, name: str) -> Optional[Command]:
        with self._lock:
            return self._commands.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._commands)

    def dispatch(self, name: str, source: str, round: int, *args: str, **kwargs: Any) -> None:
        cmd = self.get(name)
        if cmd is None:
            raise ValueError(f"unknown command {name!r} (known: {self.names()})")
        cmd.execute(source, round, *args, **kwargs)
