"""Command pattern: message names dispatched to handlers.

Parity with the reference's command set (SURVEY.md §2.3 "Commands (10)"
— p2pfl/communication/commands/): message commands (beat, start_learning,
stop_learning, model_initialized, vote_train_set, models_aggregated,
models_ready, metrics) and weights commands (init_model, partial_model,
full_model).
"""

from p2pfl_tpu.comm.commands.command import Command, CommandDispatcher  # noqa: F401
