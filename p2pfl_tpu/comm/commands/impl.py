"""The framework's command set.

Parity with the reference's commands (SURVEY.md §2.3, p2pfl/communication/
commands/message/*.py and weights/*.py). Each command captures the node
facade and manipulates its state / learner / aggregator exactly like the
reference handlers:

* control plane: start_learning, stop_learning, model_initialized,
  vote_train_set, models_aggregated, models_ready, metrics
* model plane (weights payloads): init_model, partial_model, full_model
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any, List

from p2pfl_tpu.comm.commands.command import Command
from p2pfl_tpu.comm.delta import DELTA_META_KEY
from p2pfl_tpu.exceptions import DeltaAnchorError
from p2pfl_tpu.telemetry import TRACER, tracing

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.node import Node

log = logging.getLogger("p2pfl_tpu")


class StartLearningCommand(Command):
    """Kick off a learning session on this node
    (reference message/start_learning_command.py:26-79)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "start_learning"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        rounds, epochs = int(args[0]), int(args[1])
        self._node.start_learning_thread(rounds, epochs)


class StopLearningCommand(Command):
    """(reference message/stop_learning_command.py:30)"""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "stop_learning"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        self._node.stop_learning_locally()


class ModelInitializedCommand(Command):
    """Peer announced an initialized model: nei_status[src] = -1
    (reference message/model_initialized_command.py:25)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "model_initialized"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        self._node.state.nei_status[source] = -1


class VoteTrainSetCommand(Command):
    """Store a peer's committee votes; args are a flat
    [candidate, weight, ...] list (reference
    message/vote_train_set_command.py:28-56: accept round r or r+1 because
    votes may arrive before the local round increments)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "vote_train_set"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        state = self._node.state
        current = state.round
        if current is None or round not in (current, current + 1):
            log.debug("vote from %s for round %s ignored (local round %s)", source, round, current)
            return
        votes = {args[i]: int(args[i + 1]) for i in range(0, len(args) - 1, 2)}
        with state.train_set_votes_lock:
            state.train_set_votes[source] = votes
        state.votes_ready_event.set()


class ModelsAggregatedCommand(Command):
    """Track a trainset peer's partial-aggregation progress
    (reference message/models_agregated_command.py:26)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "models_aggregated"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        state = self._node.state
        if state.round is not None and round == state.round:
            state.models_aggregated[source] = list(args)


class ModelsReadyCommand(Command):
    """Peer finished its round (reference message/models_ready_command.py:26:
    accept round-1 or round)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "models_ready"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        state = self._node.state
        current = state.round
        if current is None or round not in (current - 1, current):
            return
        state.nei_status[source] = round


class MetricsCommand(Command):
    """Peer metrics broadcast (reference message/metrics_command.py:26);
    args = flat [name, value, ...]."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "metrics"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        for i in range(0, len(args) - 1, 2):
            self._node.log_remote_metric(source, round, args[i], float(args[i + 1]))


class InitModelCommand(Command):
    """Adopt initial weights if we don't have a model yet
    (reference weights/init_model_command.py:31-97)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "init_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        from p2pfl_tpu.models.model_handle import decode_wire_frame

        state = self._node.state
        if state.model_initialized_event.is_set():
            return
        weights: bytes = kwargs["weights"]
        try:
            arrays, meta = decode_wire_frame(weights)
        except Exception as exc:  # corrupt/truncated init frame
            log.debug("init_model from %s undecodable: %s", source, exc)
            state.admission.record("corrupt", source, "init_model")
            return
        # Round-0 weights define every peer's starting point — a poisoned
        # init outlives any later defense, so screen structure/finiteness
        # plus the init-scale weight-norm sanity bound here.
        if state.admission.screen_init(
            arrays, self._node.learner.get_model(), source=source
        ):
            return
        try:
            self._node.learner.get_model().apply_frame(arrays, meta)
            state.model_initialized_event.set()
            self._node.protocol.broadcast(
                self._node.protocol.build_msg(ModelInitializedCommand.get_name())
            )
        except Exception:
            log.exception("init_model from %s failed", source)


class PartialModelCommand(Command):
    """Merge a partially-aggregated model from a trainset peer, then
    re-announce progress (reference weights/partial_model_command.py:33-112)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "partial_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        state = node.state
        if state.round is None:
            return
        if round != state.round:
            log.debug("partial model for round %s ignored (local %s)", round, state.round)
            return
        weights: bytes = kwargs["weights"]
        contributors: List[str] = list(kwargs.get("contributors", []))
        # Clamp the unauthenticated wire claim before it can weight FedAvg.
        num_samples: int = state.admission.clamp_num_samples(
            int(kwargs.get("num_samples", 1)), source
        )
        try:
            # Frames decode through the node's delta codec: dense frames pass
            # straight through; sparse top-k deltas reconstruct against this
            # round's anchor (jitted scatter-add — no host loop).
            arrays, meta = state.wire.decode_frame(weights)
        except DeltaAnchorError as exc:
            # Out of phase, not corrupt: drop it, the gossip loop re-ships.
            log.debug("partial model from %s dropped: %s", source, exc)
            return
        except Exception as exc:  # corrupt/truncated frame: reject, don't raise
            # Decode failures used to escape onto the transport thread; a
            # Byzantine (or bit-flipped) frame must be a counted rejection,
            # not an exception storm.
            log.debug("partial model from %s undecodable: %s", source, exc)
            state.admission.record("corrupt", source, "partial_model")
            return
        # Admission control: screen the RECONSTRUCTED arrays (post sparse-
        # delta decode) against the local model spec + adaptive norm bound
        # before anything reaches the aggregator.
        if state.admission.screen(
            arrays, node.learner.get_model(), source=source, cmd="partial_model"
        ):
            return
        # Trace context: the envelope slot (in-memory) is already attached by
        # handle_envelope; the PFLT header slot covers gRPC weights frames.
        wire_ctx = meta.get(tracing.TRACE_META_KEY, "") or tracing.current_wire()
        with TRACER.recv_span(
            "apply:partial_model", node.addr, wire_ctx, source=source, round=round
        ):
            model = node.learner.get_model().build_copy(
                params=arrays, contributors=contributors, num_samples=num_samples
            )
            agg = node.aggregator.add_model(model)
            if agg:
                node.protocol.broadcast(
                    node.protocol.build_msg(
                        ModelsAggregatedCommand.get_name(), args=agg, round=state.round
                    )
                )


class FullModelCommand(Command):
    """Adopt the round's fully-aggregated model
    (reference weights/full_model_command.py:31-89)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "full_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        state = node.state
        if state.round is None:
            return
        if round < state.round:
            return
        if round <= state.last_full_model_round:
            # Redundant re-delivery: we already hold this round's full model
            # (adopted from the wire, or our own aggregate — TrainStage marks
            # it). FIRST WINS: never re-apply — a later frame for the same
            # round can legitimately differ (aggregation-order epsilon) or
            # maliciously differ (a Byzantine peer overwriting the honest
            # aggregate in the post-aggregation window), and we have no basis
            # to prefer it. The sender keeps gossiping because it never saw
            # our round progress — our fire-once models_ready broadcast was
            # probably lost. Re-announce so the sender's candidate set
            # shrinks instead of it re-shipping full models until its stall
            # exit trips (ack repair under message loss).
            node.protocol.broadcast(
                node.protocol.build_msg(ModelsReadyCommand.get_name(), round=round)
            )
            return
        weights: bytes = kwargs["weights"]
        try:
            try:
                arrays, meta = state.wire.decode_frame(weights)
            except DeltaAnchorError as exc:
                # Sparse frame for a round we hold no anchor for (we lag or
                # lead the sender) — drop; the sender's gossip loop retries
                # and falls back to a dense frame for out-of-round peers.
                log.debug("full model from %s dropped: %s", source, exc)
                return
            except Exception as exc:  # corrupt/truncated frame
                log.debug("full model from %s undecodable: %s", source, exc)
                state.admission.record("corrupt", source, "full_model")
                return
            # Structure + finiteness screening BEFORE adoption and before the
            # anchor resync below, so a poisoned frame can never become the
            # next round's delta anchor. No norm bound here: a rejoining node
            # must be able to adopt an aggregate arbitrarily far from its
            # stale local weights (admission.py module docstring).
            if state.admission.screen(
                arrays, node.learner.get_model(),
                source=source, cmd="full_model", check_norm=False,
            ):
                return
            wire_ctx = meta.get(tracing.TRACE_META_KEY, "") or tracing.current_wire()
            with TRACER.recv_span(
                "apply:full_model", node.addr, wire_ctx, source=source, round=round
            ):
                node.learner.get_model().apply_frame(arrays, meta)
                state.last_full_model_round = max(state.last_full_model_round, round)
                # Rejoin/round-anchor resync: adopting a DENSE full model for
                # round r means we now hold the exact model every in-phase
                # node will anchor round r+1 against — so a crashed-and-
                # restarted (or partition-healed) node whose anchor lags
                # fast-forwards here, and subsequent sparse top-k frames for
                # r+1 decode instead of being dropped forever. Sparse frames
                # skip this: decoding one already required a current anchor,
                # and a trainer's error-feedback residuals must survive the
                # normal round boundary (RoundFinishedStage advances those).
                if meta.get(DELTA_META_KEY) is None and round + 1 > state.wire.anchor_round:
                    state.wire.resync(
                        node.learner.get_model().get_parameters(), round + 1
                    )
                state.aggregated_model_event.set()
        except Exception:
            log.exception("full_model from %s failed", source)
