"""The framework's command set.

Parity with the reference's commands (SURVEY.md §2.3, p2pfl/communication/
commands/message/*.py and weights/*.py). Each command captures the node
facade and manipulates its state / learner / aggregator exactly like the
reference handlers:

* control plane: start_learning, stop_learning, model_initialized,
  vote_train_set, models_aggregated, models_ready, metrics
* model plane (weights payloads): init_model, partial_model, full_model
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any, List

import numpy as np

from p2pfl_tpu.comm.commands.command import Command
from p2pfl_tpu.comm.delta import DELTA_META_KEY
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import DeltaAnchorError
from p2pfl_tpu.telemetry import TRACER, tracing

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.node import Node

log = logging.getLogger("p2pfl_tpu")


class StartLearningCommand(Command):
    """Kick off a learning session on this node
    (reference message/start_learning_command.py:26-79)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "start_learning"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        rounds, epochs = int(args[0]), int(args[1])
        # Third arg (absent on older peers) selects the scheduler: "sync"
        # rounds (default) or "async" elastic windows (stages/async_node.py).
        mode = args[2] if len(args) > 2 else "sync"
        self._node.start_learning_thread(rounds, epochs, mode=mode)


class StopLearningCommand(Command):
    """(reference message/stop_learning_command.py:30)"""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "stop_learning"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        self._node.stop_learning_locally()


class ModelInitializedCommand(Command):
    """Peer announced an initialized model: nei_status[src] = -1
    (reference message/model_initialized_command.py:25)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "model_initialized"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        self._node.state.nei_status[source] = -1


class VoteTrainSetCommand(Command):
    """Store a peer's committee votes; args are a flat
    [candidate, weight, ...] list (reference
    message/vote_train_set_command.py:28-56: accept round r or r+1 because
    votes may arrive before the local round increments)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "vote_train_set"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        state = self._node.state
        current = state.round
        if current is None or round not in (current, current + 1):
            log.debug("vote from %s for round %s ignored (local round %s)", source, round, current)
            return
        votes = {args[i]: int(args[i + 1]) for i in range(0, len(args) - 1, 2)}
        with state.train_set_votes_lock:
            state.train_set_votes[source] = votes
        state.votes_ready_event.set()


class ModelsAggregatedCommand(Command):
    """Track a trainset peer's partial-aggregation progress
    (reference message/models_agregated_command.py:26)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "models_aggregated"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        state = self._node.state
        if state.round is not None and round == state.round:
            state.models_aggregated[source] = list(args)
        elif round == state.prev_coverage_round:
            # Train<->diffuse overlap: a laggard still in the round we just
            # closed reports progress — the background drain reads this
            # retired coverage table, so its candidate set keeps shrinking.
            state.models_aggregated_prev[source] = list(args)


class ModelsReadyCommand(Command):
    """Peer finished its round (reference message/models_ready_command.py:26:
    accept round-1 or round)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "models_ready"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        state = self._node.state
        current = state.round
        if current is None or round not in (current - 1, current):
            return
        state.nei_status[source] = round


class MetricsCommand(Command):
    """Peer metrics broadcast (reference message/metrics_command.py:26);
    args = flat [name, value, ...]."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "metrics"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        for i in range(0, len(args) - 1, 2):
            self._node.log_remote_metric(source, round, args[i], float(args[i + 1]))


class InitModelCommand(Command):
    """Adopt initial weights if we don't have a model yet
    (reference weights/init_model_command.py:31-97)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "init_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        from p2pfl_tpu.models.model_handle import decode_wire_frame

        state = self._node.state
        if state.model_initialized_event.is_set():
            return
        weights: bytes = kwargs["weights"]
        try:
            arrays, meta = decode_wire_frame(weights)
        except Exception as exc:  # corrupt/truncated init frame
            log.debug("init_model from %s undecodable: %s", source, exc)
            state.admission.record("corrupt", source, "init_model")
            return
        # Round-0 weights define every peer's starting point — a poisoned
        # init outlives any later defense, so screen structure/finiteness
        # plus the init-scale weight-norm sanity bound here.
        if state.admission.screen_init(
            arrays, self._node.learner.get_model(), source=source
        ):
            return
        try:
            self._node.learner.get_model().apply_frame(arrays, meta)
            state.model_initialized_event.set()
            self._node.protocol.broadcast(
                self._node.protocol.build_msg(ModelInitializedCommand.get_name())
            )
        except Exception:
            log.exception("init_model from %s failed", source)


class PartialModelCommand(Command):
    """Merge a partially-aggregated model from a trainset peer, then
    re-announce progress (reference weights/partial_model_command.py:33-112)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "partial_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        state = node.state
        if state.round is None:
            return
        if round != state.round:
            log.debug("partial model for round %s ignored (local %s)", round, state.round)
            return
        weights: bytes = kwargs["weights"]
        contributors: List[str] = list(kwargs.get("contributors", []))
        # Clamp the unauthenticated wire claim before it can weight FedAvg.
        num_samples: int = state.admission.clamp_num_samples(
            int(kwargs.get("num_samples", 1)), source
        )
        try:
            # Frames decode through the node's delta codec: dense frames pass
            # straight through; sparse top-k deltas reconstruct against this
            # round's anchor (jitted scatter-add — no host loop). Masked
            # lattice frames (privacy plane) carry neither delta nor codec
            # spec and pass through untouched — they are handled below.
            arrays, meta = state.wire.decode_frame(weights)
        except DeltaAnchorError as exc:
            # Out of phase, not corrupt: drop it, the gossip loop re-ships.
            log.debug("partial model from %s dropped: %s", source, exc)
            return
        except Exception as exc:  # corrupt/truncated frame: reject, don't raise
            # Decode failures used to escape onto the transport thread; a
            # Byzantine (or bit-flipped) frame must be a counted rejection,
            # not an exception storm.
            log.debug("partial model from %s undecodable: %s", source, exc)
            state.admission.record("corrupt", source, "partial_model")
            return
        from p2pfl_tpu.privacy.secagg import MASKED_META_KEY, PrivacyPlane

        if PrivacyPlane.is_masked_frame(meta):
            # Masked lattice frame: structural screening only (uniform ring
            # values cannot be norm-screened — the committee-side range
            # check at finalize owns the rest), then straight into the
            # lattice-summing aggregator. Never touches the model or the
            # delta anchor.
            if not Settings.PRIVACY_SECAGG:
                state.admission.record("masked_structure", source, "partial_model")
                return
            if not state.train_set:
                # Out of phase, not hostile: the round's committee is not
                # elected here yet (vote in progress), so the frame's
                # declared geometry CANNOT be validated — drop silently and
                # let the sender's gossip loop re-ship, exactly like a
                # sparse frame ahead of our anchor. Rejecting would both
                # poison the honest sender's suspect score and stall its
                # gossip coverage into an abandonment.
                log.debug(
                    "masked partial from %s dropped: round %s committee not "
                    "elected yet", source, round,
                )
                return
            try:
                lattices = PrivacyPlane.parse_frame(arrays, meta)
            except Exception as exc:  # hostile plane geometry
                log.debug("masked partial from %s unparseable: %s", source, exc)
                state.admission.record("corrupt", source, "partial_model")
                return
            try:
                model = node.learner.get_model()
                shapes = [tuple(np.asarray(p).shape) for p in model.get_parameters()]
                dtypes = [np.asarray(p).dtype for p in model.get_parameters()]
                supports = PrivacyPlane.supports(round, shapes, dtypes)
                expected_ks = [0 if s is None else int(s.size) for s in supports]
            except Exception:  # noqa: BLE001 — geometry failure = reject
                state.admission.record("masked_structure", source, "partial_model")
                return
            if state.admission.screen_masked(
                lattices,
                meta.get(MASKED_META_KEY),
                committee=state.train_set,
                contributors=contributors,
                expected_ks=expected_ks,
                source=source,
                cmd="partial_model",
            ):
                return
            handle = PrivacyPlane.handle_from_frame(
                lattices, meta, contributors, num_samples
            )
            agg = node.aggregator.add_model(handle, round=round)
            if agg:
                node.protocol.broadcast(
                    node.protocol.build_msg(
                        ModelsAggregatedCommand.get_name(), args=agg, round=state.round
                    )
                )
            return
        # Admission control: screen the RECONSTRUCTED arrays (post sparse-
        # delta decode) against the local model spec + adaptive norm bound
        # before anything reaches the aggregator.
        if state.admission.screen(
            arrays, node.learner.get_model(), source=source, cmd="partial_model"
        ):
            return
        # Trace context: the envelope slot (in-memory) is already attached by
        # handle_envelope; the PFLT header slot covers gRPC weights frames.
        wire_ctx = meta.get(tracing.TRACE_META_KEY, "") or tracing.current_wire()
        with TRACER.recv_span(
            "apply:partial_model", node.addr, wire_ctx, source=source, round=round
        ):
            model = node.learner.get_model().build_copy(
                params=arrays, contributors=contributors, num_samples=num_samples
            )
            # Round-scoped: under overlap the previous round's table stays
            # populated (retired) while peers gossip the new round — the
            # aggregator drops a frame whose round is not the OPEN one
            # (the sender's gossip loop re-ships until we open it).
            agg = node.aggregator.add_model(model, round=round)
            if agg:
                node.protocol.broadcast(
                    node.protocol.build_msg(
                        ModelsAggregatedCommand.get_name(), args=agg, round=state.round
                    )
                )


class FullModelCommand(Command):
    """Adopt the round's fully-aggregated model
    (reference weights/full_model_command.py:31-89)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "full_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        state = node.state
        if state.round is None:
            return
        if round < state.round:
            return
        if round <= state.last_full_model_round:
            # Redundant re-delivery: we already hold this round's full model
            # (adopted from the wire, or our own aggregate — TrainStage marks
            # it). FIRST WINS: never re-apply — a later frame for the same
            # round can legitimately differ (aggregation-order epsilon) or
            # maliciously differ (a Byzantine peer overwriting the honest
            # aggregate in the post-aggregation window), and we have no basis
            # to prefer it. The sender keeps gossiping because it never saw
            # our round progress — our fire-once models_ready broadcast was
            # probably lost. Re-announce so the sender's candidate set
            # shrinks instead of it re-shipping full models until its stall
            # exit trips (ack repair under message loss).
            node.protocol.broadcast(
                node.protocol.build_msg(ModelsReadyCommand.get_name(), round=round)
            )
            return
        weights: bytes = kwargs["weights"]
        try:
            try:
                arrays, meta = state.wire.decode_frame(weights)
            except DeltaAnchorError as exc:
                # Sparse frame for a round we hold no anchor for (we lag or
                # lead the sender) — drop; the sender's gossip loop retries
                # and falls back to a dense frame for out-of-round peers.
                log.debug("full model from %s dropped: %s", source, exc)
                return
            except Exception as exc:  # corrupt/truncated frame
                log.debug("full model from %s undecodable: %s", source, exc)
                state.admission.record("corrupt", source, "full_model")
                return
            # Structure + finiteness screening BEFORE adoption and before the
            # anchor resync below, so a poisoned frame can never become the
            # next round's delta anchor. No norm bound here: a rejoining node
            # must be able to adopt an aggregate arbitrarily far from its
            # stale local weights (admission.py module docstring).
            if state.admission.screen(
                arrays, node.learner.get_model(),
                source=source, cmd="full_model", check_norm=False,
            ):
                return
            wire_ctx = meta.get(tracing.TRACE_META_KEY, "") or tracing.current_wire()
            with TRACER.recv_span(
                "apply:full_model", node.addr, wire_ctx, source=source, round=round
            ):
                node.learner.get_model().apply_frame(arrays, meta)
                state.note_full_model_round(round)
                from p2pfl_tpu.telemetry.ledger import (
                    LEDGERS,
                    canonical_params_hash,
                )

                if LEDGERS.enabled():
                    # Non-trainers commit the round aggregate here — the
                    # trainer-side analogue (own aggregate) is in TrainStage.
                    adopted = node.learner.get_model()
                    LEDGERS.get(node.addr).emit(
                        "aggregate_committed",
                        round=round,
                        dedup_key=("commit", round),
                        hash=canonical_params_hash(adopted.get_parameters()),
                        contributors=sorted(adopted.contributors),
                        num_samples=adopted.get_num_samples(),
                        origin="full_model",
                    )
                # Rejoin/round-anchor resync: adopting a DENSE full model for
                # round r means we now hold the exact model every in-phase
                # node will anchor round r+1 against — so a crashed-and-
                # restarted (or partition-healed) node whose anchor lags
                # fast-forwards here, and subsequent sparse top-k frames for
                # r+1 decode instead of being dropped forever. Sparse frames
                # skip this: decoding one already required a current anchor,
                # and a trainer's error-feedback residuals must survive the
                # normal round boundary (RoundFinishedStage advances those).
                if meta.get(DELTA_META_KEY) is None and round + 1 > state.wire.anchor_round:
                    state.wire.resync(
                        node.learner.get_model().get_parameters(), round + 1
                    )
                state.aggregated_model_event.set()
        except Exception:
            log.exception("full_model from %s failed", source)


class ReconcileCommand(Command):
    """Partition-heal progress exchange (control plane).

    Sent by a node's heal handler when a failure-departed peer demonstrably
    returns: ``args = [sender_round, sender_mode]``. Both sides of a healed
    split detect the heal and ping, so each handler only has to answer one
    question — *am I ahead?* If this node leads the sender by at least
    ``Settings.RECOVERY_RECONCILE_MIN_LEAD`` rounds/windows, it ships its
    current ROUND ANCHOR (the round-start model every in-phase node deltas
    against) as a dense ``reconcile_model`` catch-up; the behind side adopts
    it at its next round boundary and fast-forwards. Equal-round splits
    exchange nothing — the next round's normal aggregation merges the two
    branches (and the async buffer folds both halves staleness-weighted)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "reconcile"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        state = node.state
        my_round = state.round
        if my_round is None or source == node.addr:
            return
        try:
            sender_round = int(args[0]) if args else int(round)
        except ValueError:
            return
        if sender_round - my_round >= Settings.RECOVERY_RECONCILE_MIN_LEAD:
            # THEY are ahead: request the catch-up by pinging our own
            # position back (covers asymmetric heal detection — only one
            # side noticed the return). Cooldown-guarded on the node.
            node.send_reconcile_ping(source)
            return
        if my_round - sender_round < Settings.RECOVERY_RECONCILE_MIN_LEAD:
            return
        anchor = state.wire.anchor_model()
        if anchor is None:
            return
        leaves, anchor_round = anchor
        if anchor_round <= sender_round:
            return
        model = node.learner.get_model()
        catchup = model.build_copy(
            params=leaves,
            contributors=model.contributors or [node.addr],
            num_samples=model.get_num_samples(),
        )
        env = node.protocol.build_weights(
            ReconcileModelCommand.get_name(),
            anchor_round,
            catchup.encode_parameters(),  # always dense: generations diverged
            catchup.contributors,
            catchup.get_num_samples(),
        )
        try:
            node.protocol.send(
                source, env, create_connection=True,
                raise_error=False, remove_on_error=False,
            )
        except Exception:  # noqa: BLE001 — a failed catch-up must not hurt us
            log.exception("reconcile catch-up to %s failed", source)
            return
        from p2pfl_tpu.stages.recovery import reconcile_metric

        reconcile_metric(node.addr, "catchup_tx")
        node.protocol.flight_recorder.record(
            "reconcile", role="catchup_tx", peer=source,
            round=anchor_round, behind=sender_round,
        )
        log.warning(
            "%s: healed peer %s is %d behind (round %s vs %s) — shipped the "
            "round-%s anchor as dense catch-up",
            node.addr, source, my_round - sender_round, sender_round, my_round,
            anchor_round,
        )


class ReconcileModelCommand(Command):
    """Dense catch-up from the ahead side of a healed split (model plane).

    The payload is the sender's round anchor for ``round``. Adoption is
    deferred: the screened arrays are parked in the node state and applied
    ATOMICALLY at the next round/window boundary
    (:func:`p2pfl_tpu.stages.recovery.apply_pending_reconcile`) — applying
    mid-stage would race the stage's own model writes. The sliced stage
    waits are woken so the dead-branch round winds down fast."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "reconcile_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        from p2pfl_tpu.models.model_handle import decode_wire_frame

        node = self._node
        state = node.state
        if state.round is None or int(round) <= state.round:
            return
        weights: bytes = kwargs["weights"]
        try:
            arrays, meta = decode_wire_frame(weights)
        except Exception as exc:
            log.debug("reconcile_model from %s undecodable: %s", source, exc)
            state.admission.record("corrupt", source, "reconcile_model")
            return
        # Structure + finiteness screening; no norm bound — our stale branch
        # is arbitrarily far from the surviving generation (same rationale
        # as full_model / async_catchup adoption).
        if state.admission.screen(
            arrays, node.learner.get_model(),
            source=source, cmd="reconcile_model", check_norm=False,
        ):
            return
        if state.offer_reconcile(
            int(round), arrays, list(kwargs.get("contributors", [])), source
        ):
            # Wind the dead-branch round down: sliced waits re-check
            # reconcile_ahead() and exit instead of sleeping out deadlines.
            state.votes_ready_event.set()
            state.aggregated_model_event.set()
            node.protocol.flight_recorder.record(
                "reconcile", role="offer", peer=source, round=int(round)
            )
            log.info(
                "%s: reconcile catch-up for round %s staged (from %s)",
                node.addr, round, source,
            )


class PrivacyKeyCommand(Command):
    """Session public key for the privacy plane's pairwise mask agreement.

    ``args = [pubkey_hex]``. TTL-gossiped at session bootstrap
    (``establish_initial_model``); the handler answers a FIRST-seen key with
    its own key sent directly back, so a joiner (or a peer whose broadcast
    was dropped) converges on a symmetric pair secret without a dedicated
    handshake round. Idempotent: repeated keys no-op."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "privacy_key"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        if source == node.addr or not args:
            return
        if node.state.privacy.learn_key(source, args[0]):
            # New peer: answer with our key so the pair secret is derivable
            # on both ends even if our bootstrap broadcast never reached it.
            try:
                node.protocol.send(
                    source,
                    node.protocol.build_msg(
                        PrivacyKeyCommand.get_name(),
                        args=[node.state.privacy.key_payload()],
                    ),
                    create_connection=True,
                    raise_error=False,
                    remove_on_error=False,
                )
            except Exception:  # noqa: BLE001 — a failed reply must not hurt us
                log.debug("privacy_key reply to %s failed", source)


class PrivacyRepairCommand(Command):
    """Mask-repair share for a dead masker (privacy plane).

    ``args = [dead_addr, round_secret_hex]``, ``round`` = the masked round
    being repaired. The payload is the survivor's ROUND-SCOPED pair secret
    (``H(pair_secret, round)``) — never the pair secret itself, so a wire
    capture opens exactly one round's mask streams. Broadcast by every
    survivor whose pairwise mask with the dead committee member would
    otherwise stay uncancelled in the round's lattice sum (withheld when
    coverage shows the "dead" peer's frame already circulated — the
    false-dropout gate in ``Node._on_peer_death``); every aggregating node
    stores the share first-write-wins with both parties validated against
    the round's committee (:meth:`PrivacyPlane.note_repair` — the claimed
    survivor is bound to the transport source here), and
    :meth:`PrivacyPlane.finalize` subtracts the reconstructed mask."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "privacy_repair"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        if len(args) < 2 or source == node.addr:
            return
        dead, secret_hex = args[0], args[1]
        if node.state.privacy.note_repair(int(round), source, dead, secret_hex):
            node.protocol.flight_recorder.record(
                "privacy_repair", survivor=source, dead=dead, round=int(round)
            )


class AsyncContributionCommand(Command):
    """Fold a peer's async contribution into the buffered aggregator.

    The envelope ``round`` is the WINDOW the sender trained against; the
    receiver computes the lag against its own window at fold time. Every
    contribution passes the same wire path as sync partial models — delta
    decode (against the multi-window anchor history), admission screening,
    sample-count clamping — before it can weigh an aggregate, and the
    observatory's suspect score gates admission on top (detect→act: a peer
    the fleet attributes rejections to stops being folded at all)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_model"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        state = node.state
        agg = node.async_agg
        if state.round is None or state.fed_mode != "async" or agg is None:
            return  # not in an async session (mixed-mode peers tolerate)
        gate = Settings.ASYNC_SUSPECT_GATE
        if gate > 0:
            try:
                suspicion = node.protocol.observatory.suspect_score(source)
            except Exception:  # noqa: BLE001
                suspicion = 0.0
            if suspicion >= gate:
                agg.drop(source, "suspect")
                node.protocol.flight_recorder.record(
                    "async_drop", peer=source, reason="suspect", round=round
                )
                return
        weights: bytes = kwargs["weights"]
        contributors: List[str] = list(kwargs.get("contributors", [])) or [source]
        num_samples: int = state.admission.clamp_num_samples(
            int(kwargs.get("num_samples", 1)), source
        )
        try:
            arrays, meta = state.wire.decode_frame(weights)
        except DeltaAnchorError as exc:
            # Anchored beyond the history window (sender lags or leads too
            # far): drop — it keeps emitting every window, a later frame
            # will land inside the history.
            agg.drop(source, "anchor")
            log.debug("async contribution from %s dropped: %s", source, exc)
            return
        except Exception as exc:  # corrupt/truncated frame
            log.debug("async contribution from %s undecodable: %s", source, exc)
            state.admission.record("corrupt", source, "async_model")
            return
        if state.admission.screen(
            arrays, node.learner.get_model(), source=source, cmd="async_model"
        ):
            return
        wire_ctx = meta.get(tracing.TRACE_META_KEY, "") or tracing.current_wire()
        with TRACER.recv_span(
            "apply:async_model", node.addr, wire_ctx, source=source, round=round
        ):
            model = node.learner.get_model().build_copy(
                params=arrays, contributors=contributors, num_samples=num_samples
            )
            agg.fold(model, round, source)


class AsyncDoneCommand(Command):
    """A peer completed all of its async windows. The window fill target
    stops counting it (it will produce no further contributions) and any
    in-flight window wait re-evaluates immediately — without this, the last
    nodes standing would burn ``ASYNC_WINDOW_TIMEOUT`` per remaining window
    waiting on peers that already went home."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_done"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        node.state.async_done_peers.add(source)
        if node.async_agg is not None:
            node.async_agg.notify()


class AsyncJoinCommand(Command):
    """A peer wants to enter the running async experiment.

    Every member that receives the (TTL-gossiped) join request replies with
    the session parameters (``async_welcome``) plus a DENSE full-model
    catch-up frame (``async_catchup``) — the joiner keeps the first of each,
    the rest are idempotent no-ops. Sync experiments ignore joins: elastic
    membership is exactly what the sync barrier cannot offer."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_join"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        state = node.state
        if state.round is None or state.fed_mode != "async" or source == node.addr:
            return
        w = state.round or 0
        node.protocol.flight_recorder.record("membership", event="join_request", peer=source)
        try:
            node.protocol.send(
                source,
                node.protocol.build_msg(
                    AsyncWelcomeCommand.get_name(),
                    args=[str(state.total_rounds or 0), str(state.epochs)],
                    round=w,
                ),
                create_connection=True,
                raise_error=False,
                remove_on_error=False,
            )
            model = node.learner.get_model()
            env = node.protocol.build_weights(
                AsyncCatchupCommand.get_name(),
                w,
                model.encode_parameters(),  # always dense: the joiner holds no anchor
                model.contributors or [node.addr],
                model.get_num_samples(),
            )
            node.protocol.send(
                source, env, create_connection=True,
                raise_error=False, remove_on_error=False,
            )
        except Exception:  # noqa: BLE001 — a failed welcome must not hurt us
            log.exception("async_join reply to %s failed", source)


class AsyncWelcomeCommand(Command):
    """Session parameters for a joiner: total windows + epochs in ``args``,
    the sender's current window in ``round``. The joiner's experiment starts
    fast-forwarded to that window; duplicate welcomes no-op."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_welcome"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        node = self._node
        if node.learning_in_progress():
            return
        total = int(args[0])
        epochs = int(args[1]) if len(args) > 1 else 1
        if total <= 0 or int(round) >= total:
            return  # session is over (or malformed) — nothing to join
        log.info(
            "%s: joining async experiment at window %s/%s (welcomed by %s)",
            node.addr, round, total, source,
        )
        node.start_learning_thread(
            total, epochs, mode="async", start_round=int(round)
        )


class AsyncCatchupCommand(Command):
    """Dense full-model bootstrap for a cold joiner: adopt the weights,
    resync the sparse-delta anchor to the sender's window (residual-dropping
    :meth:`DeltaWireCodec.resync` — the rejoin path built in PR 3), and mark
    the model initialized so :class:`AsyncStartStage` proceeds. A node that
    already holds an initialized model ignores catch-ups — rejoining live
    nodes converge through the normal staleness-weighted folds instead."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_catchup"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        from p2pfl_tpu.models.model_handle import decode_wire_frame

        node = self._node
        state = node.state
        if state.model_initialized_event.is_set():
            return
        weights: bytes = kwargs["weights"]
        try:
            arrays, meta = decode_wire_frame(weights)
        except Exception as exc:
            log.debug("async_catchup from %s undecodable: %s", source, exc)
            state.admission.record("corrupt", source, "async_catchup")
            return
        # Structure + finiteness screening; no norm bound — a joiner's local
        # random init is arbitrarily far from the trained federation model
        # (same rationale as full_model adoption, comm/admission.py).
        if state.admission.screen(
            arrays, node.learner.get_model(),
            source=source, cmd="async_catchup", check_norm=False,
        ):
            return
        try:
            node.learner.get_model().apply_frame(arrays, meta)
            state.wire.resync(node.learner.get_model().get_parameters(), int(round))
            state.note_full_model_round(int(round))
            state.model_initialized_event.set()
            node.protocol.flight_recorder.record(
                "membership", event="catchup", peer=source, window=int(round)
            )
        except Exception:
            log.exception("async_catchup from %s failed", source)
