"""Transport-agnostic message envelope.

Plays the role of the reference's protobuf ``RootMessage`` with its
``Message``/``Weights`` oneof (grpc/proto/node.proto:26-59): a command name
plus either small string args (control plane, TTL-gossiped) or a weights
payload (model plane). Both transports carry this same shape — the in-memory
transport passes the dataclass directly, the gRPC transport maps it onto its
proto schema.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass, field
from typing import List, Optional

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry import tracing
from p2pfl_tpu.telemetry.bundle import current_run_id


@dataclass
class Envelope:
    source: str
    cmd: str
    round: int = 0
    args: List[str] = field(default_factory=list)
    ttl: int = 0
    msg_id: int = 0
    payload: Optional[bytes] = None  # serialized weights (ops.serialization)
    contributors: List[str] = field(default_factory=list)
    num_samples: int = 0
    # Wire-propagated span context ("<trace_id>:<span_id>", empty when the
    # frame was built outside any span — e.g. heartbeats). The in-memory
    # transport carries it as-is; gRPC maps it onto a reserved trailing
    # control arg (weights frames carry it in the PFLT header instead —
    # telemetry/tracing.py module docstring).
    trace: str = ""
    # Piggybacked health digest (telemetry/digest.py encoded JSON, normally
    # only on heartbeats). Same wire story as ``trace``: native on the
    # in-memory transport, a reserved trailing control arg on gRPC. Empty =
    # absent, and absent digests MUST be tolerated by every receiver —
    # digest-free (older or opted-out) nodes share the wire.
    digest: str = ""
    # Federation-wide run id (telemetry/bundle.py) correlating every
    # artifact of one experiment. Same wire story as ``trace``: native on
    # the in-memory transport, a reserved trailing control arg on gRPC;
    # weights frames skip it (the control plane converges the id before
    # any model traffic flows). Empty = sender predates run contexts or
    # none established — receivers MUST tolerate that.
    run_id: str = ""
    # SENDER-LOCAL codec attribution for weights payloads ("topk" /
    # "topk-int8" / "topk-int4" / "dense"; comm/delta.py CODEC_LABELS).
    # Never serialized onto the wire — the frame itself is self-describing;
    # this tag only feeds the gossiper's TX accounting and the per-codec
    # compression metrics at the send choke point.
    codec: str = "dense"

    @property
    def is_weights(self) -> bool:
        return self.payload is not None

    @staticmethod
    def message(source: str, cmd: str, args: Optional[List[str]] = None, round: int = 0) -> "Envelope":
        """Control-plane message with fresh TTL and a random dedup id
        (reference grpc_client.py:56-88)."""
        return Envelope(
            source=source,
            cmd=cmd,
            round=round,
            args=[str(a) for a in (args or [])],
            ttl=Settings.TTL,
            msg_id=secrets.randbits(63),
            trace=tracing.current_wire(),
            run_id=current_run_id(),
        )

    @staticmethod
    def weights(
        source: str,
        cmd: str,
        round: int,
        payload: bytes,
        contributors: List[str],
        num_samples: int,
        codec: str = "dense",
    ) -> "Envelope":
        """Model-plane message (reference grpc_client.py:90-123). Not
        TTL-gossiped; routed point-to-point by the model gossip loop."""
        return Envelope(
            source=source,
            cmd=cmd,
            round=round,
            ttl=0,
            msg_id=secrets.randbits(63),
            # coerce once: the native codec hands out bytearray, and the
            # envelope is reused across gossip fan-out (bytes(bytes) is free)
            payload=bytes(payload),
            contributors=list(contributors),
            num_samples=int(num_samples),
            trace=tracing.current_wire(),
            codec=codec or "dense",
        )
