"""In-process transport for single-host simulation and tests."""

from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol  # noqa: F401
