"""Process-global address registry for the in-memory transport
(reference memory/server_singleton.py: a process-global dict of servers)."""

from __future__ import annotations

import itertools
import threading
from typing import TYPE_CHECKING, Dict, Optional

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol


class InMemoryRegistry:
    _lock = threading.Lock()
    _servers: Dict[str, "InMemoryCommunicationProtocol"] = {}
    _counter = itertools.count()

    @classmethod
    def fresh_addr(cls) -> str:
        return f"mem://node-{next(cls._counter)}"

    @classmethod
    def register(cls, addr: str, server: "InMemoryCommunicationProtocol") -> None:
        with cls._lock:
            if addr in cls._servers:
                raise ValueError(f"address {addr} already registered")
            cls._servers[addr] = server

    @classmethod
    def unregister(cls, addr: str, server: Optional["InMemoryCommunicationProtocol"] = None) -> None:
        """Remove ``addr``. When ``server`` is given, remove only if it is
        still the registered instance — a crashed-and-restarted node at the
        same address must not be torn out of the registry by the OLD
        instance's (late) stop."""
        with cls._lock:
            if server is None or cls._servers.get(addr) is server:
                cls._servers.pop(addr, None)

    @classmethod
    def lookup(cls, addr: str) -> Optional["InMemoryCommunicationProtocol"]:
        with cls._lock:
            return cls._servers.get(addr)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._servers.clear()
