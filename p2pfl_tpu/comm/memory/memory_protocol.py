"""In-memory communication protocol.

Parity with reference memory/memory_communication_protocol.py:33-66 +
memory_client.py:30-87: same envelope semantics as the gRPC transport but
delivery is a registry lookup + handoff to the receiver's executor (which
models the gRPC server's thread pool, so handlers never run reentrantly on
the sender's stack — avoiding the lock-inversion deadlocks a purely
synchronous in-proc transport would create).
"""

from __future__ import annotations

import concurrent.futures
import time
from dataclasses import replace
from typing import Optional

from p2pfl_tpu.comm.envelope import Envelope
from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
from p2pfl_tpu.comm.neighbors import Neighbors
from p2pfl_tpu.comm.protocol import CommunicationProtocol
from p2pfl_tpu.exceptions import CommunicationError


class _InMemoryNeighbors(Neighbors):
    def connect_to(self, addr: str, *, handshake: bool):
        peer = InMemoryRegistry.lookup(addr)
        if peer is None:
            raise CommunicationError(f"no in-memory server at {addr}")
        if handshake:
            peer.accept_handshake(self.self_addr)
        return addr  # connection object is just the address

    def disconnect_from(self, addr: str, conn, *, notify: bool) -> None:
        if notify:
            peer = InMemoryRegistry.lookup(addr)
            if peer is not None:
                peer.accept_disconnect(self.self_addr)


class InMemoryCommunicationProtocol(CommunicationProtocol):
    """Single-process transport backed by a global registry."""

    def __init__(self, addr: Optional[str] = None) -> None:
        self._executor: Optional[concurrent.futures.ThreadPoolExecutor] = None
        super().__init__(addr)

    def _default_addr(self) -> str:
        return InMemoryRegistry.fresh_addr()

    def _build_neighbors(self, addr: str) -> Neighbors:
        return _InMemoryNeighbors(addr)

    # --- server side --------------------------------------------------------

    def _server_start(self) -> None:
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"memsrv-{self.addr}"
        )
        InMemoryRegistry.register(self.addr, self)

    def _server_stop(self) -> None:
        # Unregister FIRST (identity-guarded: a restarted node at the same
        # address must not be torn out by this old instance), so no new
        # deliver() can reach a dying executor; then shut the executor down
        # and bound-join its workers so crash-simulating tests don't leak
        # handler threads or registry entries across cases even when
        # handlers are in flight at stop() time.
        InMemoryRegistry.unregister(self.addr, self)
        executor, self._executor = self._executor, None
        if executor is not None:
            executor.shutdown(wait=False, cancel_futures=True)
            deadline = time.monotonic() + 3.0
            for t in list(getattr(executor, "_threads", ())):
                t.join(timeout=max(0.0, deadline - time.monotonic()))

    def accept_handshake(self, source_addr: str) -> None:
        """Remote side of connect (reference grpc_server.py:135-143)."""
        if not self._running:
            raise CommunicationError(f"{self.addr} is not started")
        self.neighbors.add(source_addr, non_direct=False, handshake=False)

    def accept_disconnect(self, source_addr: str) -> None:
        # The peer said goodbye: graceful, not a failure departure — it owes
        # no heal and must not enter the recovery probe pool.
        self.neighbors.remove(source_addr, notify=False, departed=False)

    def deliver(self, env: Envelope) -> None:
        """Entry point for inbound envelopes (the "RPC")."""
        executor = self._executor
        if not self._running or executor is None:
            raise CommunicationError(f"{self.addr} is not started")
        try:
            executor.submit(self._handle_safely, env)
        except RuntimeError as exc:  # shut down between the check and submit
            raise CommunicationError(f"{self.addr} is stopping") from exc

    def _handle_safely(self, env: Envelope) -> None:
        try:
            self.handle_envelope(env)
        except Exception:
            import logging

            logging.getLogger("p2pfl_tpu").exception(
                "error handling %s from %s at %s", env.cmd, env.source, self.addr
            )

    # --- client side --------------------------------------------------------

    def _transport_send(self, nei: str, env: Envelope) -> None:
        peer = InMemoryRegistry.lookup(nei)
        if peer is None:
            raise CommunicationError(f"no in-memory server at {nei}")
        # Copy the envelope so receivers can't mutate the sender's view.
        # The trace and digest slots travel natively (str fields copied by
        # replace); the gRPC transport maps them onto reserved trailing
        # control args instead — same wire semantics either way.
        peer.deliver(replace(env, args=list(env.args), contributors=list(env.contributors)))
