"""Wire-path admission control: screen inbound model frames before they
touch the aggregator or the local model.

The federation wire path used to accept any decodable frame: a Byzantine
peer could ship a wrong-shaped tree, NaN/Inf payloads, or an
arbitrarily-scaled update and it would flow straight into
``aggregator.add_model`` / ``apply_frame`` (production FL systems treat
inbound-update validation as a first-class plane — Papaya, arxiv
2111.04877; APPFL, arxiv 2409.11585). This module is the screening step
between ``decode_frame`` and those sinks, applied AFTER sparse-delta
reconstruction so a poisoned top-k frame is judged by the dense model it
reconstructs to and can never corrupt the round anchor or residuals.

Checks, in order (first failure wins; every rejection is counted into
``p2pfl_updates_rejected_total{node, reason}``):

* ``corrupt`` — the frame did not decode at all (counted by the command
  handlers via :meth:`AdmissionController.record`, not here);
* ``tree`` — leaf count differs from the local model spec;
* ``shape`` — some leaf's shape differs;
* ``dtype`` — some leaf's float/non-float class differs (exact-width
  mismatches within a class are admitted: the wire codecs legitimately
  deliver e.g. float32 for bfloat16 leaves and ``set_parameters`` casts);
* ``nonfinite`` — any NaN/Inf in a float leaf;
* ``norm`` — the update norm ``||recv - local||`` exceeds the adaptive
  bound: ``median(recently admitted norms) * Settings.ADMISSION_NORM_MULT``
  once enough history exists, else the local model's own norm (an "update"
  as large as the whole model is not an update — the same norm-bounding
  idea as the mesh path's ``clip_update_norm``, Sun et al. 2019, applied
  as an accept/reject gate at the wire boundary).

The norm bound applies to PARTIAL models only (the path where Byzantine
mass enters aggregation). Full-model adoption is screened structurally and
for finiteness but not by norm: a crashed-and-rejoined node must be able
to adopt an aggregate arbitrarily far from its stale weights (the PR 3
anchor-resync path), so distance-from-local is not a meaningful signal
there.

``num_samples`` arrives unauthenticated on the same frames;
:meth:`AdmissionController.clamp_num_samples` caps it at
``Settings.MAX_CLAIMED_SAMPLES`` so a single peer cannot dominate FedAvg's
sample weighting (the inflation attack GeometricMedian's unit weights
already neutralize — robust.py docstring).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, List, Optional, Sequence, Set, Tuple

import numpy as np

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry import REGISTRY

log = logging.getLogger("p2pfl_tpu")

_REJECTED = REGISTRY.counter(
    "p2pfl_updates_rejected_total",
    "Inbound model-plane frames rejected by wire admission control, by "
    "reason and claimed sender (the observatory's suspect score sums the "
    "source attribution across the fleet's gossiped digests)",
    labels=("node", "reason", "source"),
)
_CLAMPED = REGISTRY.counter(
    "p2pfl_claimed_samples_clamped_total",
    "Wire-supplied num_samples claims clamped to MAX_CLAIMED_SAMPLES",
    labels=("node",),
)

#: Admitted-norm history entries required before the adaptive bound engages
#: (below this the bootstrap bound — the local model's own norm — applies).
MIN_NORM_HISTORY = 4

#: Init frames: reject when the received WEIGHT norm exceeds this multiple
#: of the local (fresh-init) weight norm. Both sides initialize the same
#: architecture, so honest inits sit near ratio 1; a x10-scaled init is ~10.
INIT_NORM_MULT = 4.0


def _is_floatlike(dt: np.dtype) -> bool:
    """Float class check that also covers ml_dtypes (bfloat16 reports numpy
    kind 'V', so ``np.issubdtype`` alone misses it)."""
    return (
        np.issubdtype(dt, np.floating)
        or dt.name == "bfloat16"
        or dt.name.startswith("float8")
    )


class AdmissionController:
    """Per-node screening state (held on :class:`~p2pfl_tpu.node_state.
    NodeState` like the delta codec). Thread-safe: screening runs on
    transport threads."""

    def __init__(self, addr: str = "unknown-node") -> None:
        self._addr = addr
        self._lock = threading.Lock()
        self._norms: deque = deque(maxlen=Settings.ADMISSION_NORM_WINDOW)
        # (source, reason) pairs already warned about — repeats drop to
        # debug so a gossip loop re-shipping a rejected frame every 100ms
        # cannot flood the log.
        self._warned: Set[Tuple[str, str]] = set()
        # Optional flight recorder (set by Node): every rejection becomes a
        # postmortem event alongside the metric.
        self.recorder: Optional[Any] = None
        # Permissive mode: admit every structurally-decodable frame. The
        # campaign harness sets this on the ADAPTIVE ADVERSARY's own node —
        # an attacker does not defend itself, and if it screened inbound
        # honest frames against its own poisoned local model it would
        # reject the entire federation and diverge from the very state it
        # is trying to ride (population/scenarios.py run_scenario_wire).
        self.permissive = False

    # --- accounting ----------------------------------------------------------

    def record(self, reason: str, source: str = "?", cmd: str = "?") -> str:
        """Count (and log) one rejection; returns ``reason`` so handlers can
        ``return admission.record(...)``-style early-exit. The ``source``
        label is the frame's CLAIMED sender (unauthenticated, like
        everything else on this wire) — per-sender attribution feeds the
        observatory's suspect score via the gossiped digest."""
        _REJECTED.labels(self._addr, reason, source).inc()
        if self.recorder is not None:
            self.recorder.record("reject", reason=reason, source=source, cmd=cmd)
        # Trajectory ledger: one admission fact per (round, sender, reason) —
        # a gossip loop re-shipping the same bad frame every tick is ONE
        # trajectory event, however many times the screen fired (the metric
        # above keeps the raw count). Lazy import: admission must stay
        # importable before the telemetry package finishes wiring.
        from p2pfl_tpu.telemetry.ledger import LEDGERS

        if LEDGERS.enabled():
            led = LEDGERS.get(self._addr)
            led.emit(
                "admission_rejected",
                round=led.current_round,  # best-effort: frames carry no round here
                sender=source,
                reason=reason,
                dedup_key=("admission", led.current_round, source, reason),
            )
        key = (source, reason)
        msg = "(%s) rejected %s frame from %s: reason=%s"
        if key in self._warned:
            log.debug(msg, self._addr, cmd, source, reason)
        else:
            self._warned.add(key)
            log.warning(msg, self._addr, cmd, source, reason)
        return reason

    def rejected_count(self, reason: Optional[str] = None) -> int:
        fam = REGISTRY.get("p2pfl_updates_rejected_total")
        if fam is None:
            return 0
        total = 0
        for labels, child in fam.samples():
            if labels.get("node") != self._addr:
                continue
            if reason is not None and labels.get("reason") != reason:
                continue
            total += int(child.value)
        return total

    # --- the screen -----------------------------------------------------------

    def screen(
        self,
        arrays: Sequence[np.ndarray],
        local_model: Any,
        *,
        source: str = "?",
        cmd: str = "?",
        check_norm: bool = True,
    ) -> Optional[str]:
        """Validate decoded ``arrays`` against ``local_model``'s spec.

        Returns ``None`` when the frame is admitted (and, with
        ``check_norm``, records its update norm into the adaptive-bound
        history), else the rejection reason (already counted/logged).
        """
        if not Settings.ADMISSION_ENABLED or self.permissive:
            return None
        local: List[np.ndarray] = local_model.get_parameters()
        if len(arrays) != len(local):
            return self.record("tree", source, cmd)
        for recv, mine in zip(arrays, local):
            recv = np.asarray(recv)
            if tuple(recv.shape) != tuple(mine.shape):
                return self.record("shape", source, cmd)
            if _is_floatlike(recv.dtype) != _is_floatlike(mine.dtype):
                return self.record("dtype", source, cmd)
        # Finiteness + norm in one float32 pass over the float leaves.
        sq_dist = 0.0
        sq_local = 0.0
        for recv, mine in zip(arrays, local):
            recv = np.asarray(recv)
            if not _is_floatlike(recv.dtype):
                continue
            r32 = recv.astype(np.float32, copy=False)
            if not np.isfinite(r32).all():
                return self.record("nonfinite", source, cmd)
            m32 = mine.astype(np.float32, copy=False)
            d = (r32 - m32).ravel()
            sq_dist += float(np.dot(d, d))
            m = m32.ravel()
            sq_local += float(np.dot(m, m))
        if not check_norm:
            return None
        norm = float(np.sqrt(sq_dist))
        with self._lock:
            if len(self._norms) >= MIN_NORM_HISTORY:
                bound = float(np.median(self._norms)) * Settings.ADMISSION_NORM_MULT
            else:
                # Bootstrap: before history exists, an update at least as
                # large as the entire local model is rejected outright.
                bound = float(np.sqrt(sq_local))
            if norm > bound:
                pass  # reject outside the lock (record logs)
            else:
                self._norms.append(norm)
                return None
        log.debug(
            "(%s) update norm %.3f exceeds bound %.3f (history=%d)",
            self._addr, norm, bound, len(self._norms),
        )
        return self.record("norm", source, cmd)

    def screen_init(
        self,
        arrays: Sequence[np.ndarray],
        local_model: Any,
        *,
        source: str = "?",
    ) -> Optional[str]:
        """Screen an init-model frame: structure + finiteness, plus an
        init-scale sanity bound on the WEIGHT norm (not the update norm —
        there is no meaningful "update" before round 0). Both sides hold a
        fresh init of the same architecture, so ``||recv||`` should be
        comparable to ``||local||``; a scaled init (x10 weights from a
        Byzantine initiator) is ~10x out and rejected as ``init_norm``.
        Sign-preserving attacks (e.g. signflip) pass — a negated init is
        still a valid-scale init, which is exactly why init frames are the
        one place the protocol must trust the experiment operator."""
        reason = self.screen(
            arrays, local_model, source=source, cmd="init_model", check_norm=False
        )
        if reason is not None or not Settings.ADMISSION_ENABLED:
            return reason
        sq_recv = 0.0
        sq_local = 0.0
        for recv, mine in zip(arrays, local_model.get_parameters()):
            recv = np.asarray(recv)
            if not _is_floatlike(recv.dtype):
                continue
            r = recv.astype(np.float32, copy=False).ravel()
            m = mine.astype(np.float32, copy=False).ravel()
            sq_recv += float(np.dot(r, r))
            sq_local += float(np.dot(m, m))
        local_norm = float(np.sqrt(sq_local))
        if local_norm < 1e-6:  # zero-init local model: nothing to compare to
            return None
        if float(np.sqrt(sq_recv)) > INIT_NORM_MULT * local_norm:
            return self.record("init_norm", source, "init_model")
        return None

    # --- masked frames (privacy plane) ----------------------------------------

    def screen_masked(
        self,
        arrays: Sequence[np.ndarray],
        info: Any,
        *,
        committee: Sequence[str],
        contributors: Sequence[str],
        expected_ks: Sequence[int],
        source: str = "?",
        cmd: str = "partial_model",
    ) -> Optional[str]:
        """Screen a masked lattice frame (``p2pfl_tpu/privacy/secagg.py``).

        A masked frame's VALUES are uniform ring elements by design, so the
        norm/finiteness screens are meaningless here — that is the
        admission-vs-secrecy tension, resolved the DisAgg/Papaya way:
        clipping-at-sender bounds what an honest masker can inject, the
        committee-side range check at finalize catches a dishonest one, and
        THIS screen validates everything structural a hostile frame
        controls (declared round/ring/committee geometry, per-tensor
        support sizes, ring dtype, membership of the claimed contributors)
        BEFORE the frame can enter the lattice sum. Every rejection is a
        counted ``masked_structure`` / ``masked_member`` — the same
        accounting surface as every other screen.
        """
        if not Settings.ADMISSION_ENABLED:
            return None
        from p2pfl_tpu.privacy.masking import ring_dtype

        if not isinstance(info, dict):
            return self.record("masked_structure", source, cmd)
        try:
            bits = int(info["bits"])
            declared_n = int(info["n"])
            int(info["round"])
        except (KeyError, TypeError, ValueError):
            return self.record("masked_structure", source, cmd)
        if bits != Settings.PRIVACY_RING_BITS or declared_n != len(set(committee)):
            return self.record("masked_structure", source, cmd)
        if not contributors or not set(contributors) <= set(committee):
            return self.record("masked_member", source, cmd)
        ks = [int(k) for k in expected_ks if int(k) > 0]
        if len(arrays) != len(ks):
            return self.record("masked_structure", source, cmd)
        dt = ring_dtype(bits)
        for a, k in zip(arrays, ks):
            a = np.asarray(a)
            if a.dtype != dt or a.shape != (k,):
                return self.record("masked_structure", source, cmd)
        return None

    # --- num_samples clamp ----------------------------------------------------

    def clamp_num_samples(self, claimed: int, source: str = "?") -> int:
        """Cap the unauthenticated wire claim at ``MAX_CLAIMED_SAMPLES``."""
        claimed = int(claimed)
        cap = Settings.MAX_CLAIMED_SAMPLES
        if claimed <= cap:
            return max(claimed, 0)
        _CLAMPED.labels(self._addr).inc()
        key = (source, "samples")
        if key not in self._warned:
            self._warned.add(key)
            log.warning(
                "(%s) %s claims %d samples — clamped to MAX_CLAIMED_SAMPLES=%d",
                self._addr, source, claimed, cap,
            )
        return cap

    def reset(self) -> None:
        with self._lock:
            self._norms.clear()
            self._warned.clear()
