"""Heartbeat-based membership / failure detector.

Parity with reference communication/protocols/heartbeater.py:33-113: a thread
broadcasts a ``beat`` every ``HEARTBEAT_PERIOD``; every second tick it sweeps
neighbors whose last_seen is older than ``HEARTBEAT_TIMEOUT``. Incoming beats
call :meth:`beat` -> ``neighbors.refresh_or_add`` — this is how non-direct
neighbors are discovered.

Telemetry: the sender's ``timestamp`` (previously discarded) now feeds a
per-peer clock-skew gauge — in-process federations read ~0, a real
deployment surfaces NTP drift, the thing that silently breaks timeout-based
failure detection — plus a beat inter-arrival gauge (receive-side jitter),
a live-peer gauge and a missed-beat counter.

Observatory piggyback: when a digest source is wired (``digest_fn``) and
``Settings.DIGEST_ENABLED``, every ``DIGEST_EVERY_BEATS``-th beat carries
the node's encoded health digest in ``Envelope.digest`` — the heartbeat was
already the one frame every peer sees periodically, so fleet observability
rides it for free. Beats without a digest stay byte-identical to the
pre-digest wire format.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

log = logging.getLogger("p2pfl_tpu")

from p2pfl_tpu.comm.envelope import Envelope
from p2pfl_tpu.comm.neighbors import Neighbors
from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry import REGISTRY

HEARTBEAT_CMD = "beat"

_LIVE_PEERS = REGISTRY.gauge(
    "p2pfl_heartbeat_live_peers",
    "Neighbors with a fresh heartbeat at the last sweep",
    labels=("node",),
)
_MISSED = REGISTRY.counter(
    "p2pfl_heartbeat_missed_total",
    "Neighbors dropped for missing heartbeats past HEARTBEAT_TIMEOUT",
    labels=("node", "peer"),
)
_CLOCK_SKEW = REGISTRY.gauge(
    "p2pfl_heartbeat_clock_skew_seconds",
    "Receiver wall-clock minus the sender-stamped beat timestamp",
    labels=("node", "peer"),
)
_INTERARRIVAL = REGISTRY.gauge(
    "p2pfl_heartbeat_interarrival_seconds",
    "Seconds between consecutive beats from the same peer",
    labels=("node", "peer"),
)


class Heartbeater:
    def __init__(
        self,
        self_addr: str,
        neighbors: Neighbors,
        broadcast_fn: Callable[[Envelope], None],
        digest_fn: Optional[Callable[[], Optional[str]]] = None,
        probe_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        self._self_addr = self_addr
        self._neighbors = neighbors
        self._broadcast = broadcast_fn
        # Returns the node's ENCODED health digest (or None to skip this
        # beat). Settable after construction (protocol.set_digest_source);
        # None keeps beats digest-free — the pre-observatory wire format.
        self._digest_fn = digest_fn
        # Heal detection (protocol._probe_departed): invoked on every sweep
        # tick so write-offs that were a PARTITION, not a death, are
        # rediscovered once the partition heals — beats alone cannot carry
        # a peer back after the failed send dropped the last link to it.
        self._probe_fn = probe_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_beat_at: Dict[str, float] = {}  # peer -> local monotonic
        self._clock_skew: Dict[str, float] = {}  # peer -> our wall - theirs
        self._live_peers = _LIVE_PEERS.labels(self_addr)

    def clock_skews(self) -> Dict[str, float]:
        """Latest per-peer clock skew (our wall clock minus the sender's
        stamped beat time, seconds). The snapshot trace export
        (``CommunicationProtocol.export_trace``) annotates dumps with this
        so the critical-path merge can align per-process timelines."""
        return dict(self._clock_skew)

    def set_digest_source(self, digest_fn: Optional[Callable[[], Optional[str]]]) -> None:
        self._digest_fn = digest_fn

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeater-{self._self_addr}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def beat(self, source: str, timestamp: float) -> None:
        """Incoming heartbeat (reference heartbeater.py:66-80)."""
        if source == self._self_addr:
            return
        if timestamp > 0.0:
            # Skew folds in one-way latency; for drift detection that noise
            # floor (ms) is far below the drift that matters (seconds).
            skew = time.time() - timestamp
            self._clock_skew[source] = skew
            _CLOCK_SKEW.labels(self._self_addr, source).set(skew)
        now = time.monotonic()
        prev = self._last_beat_at.get(source)
        self._last_beat_at[source] = now
        if prev is not None:
            _INTERARRIVAL.labels(self._self_addr, source).set(now - prev)
        self._neighbors.refresh_or_add(source)

    def _run(self) -> None:
        tick = 0
        while not self._stop.is_set():
            try:
                env = Envelope.message(
                    self._self_addr, HEARTBEAT_CMD, args=[str(time.time())]
                )
                if (
                    self._digest_fn is not None
                    and Settings.DIGEST_ENABLED
                    and tick % Settings.DIGEST_EVERY_BEATS == 0
                ):
                    try:
                        env.digest = self._digest_fn() or ""
                    except Exception:  # digest trouble must not stop the beat
                        log.exception(
                            "(%s) health-digest source failed", self._self_addr
                        )
                self._broadcast(env)
            except Exception:
                pass
            tick += 1
            if tick % 2 == 0:  # sweep stale neighbors (reference :85-105)
                now = time.time()
                last_seen = self._neighbors.last_seen()
                for addr, seen in last_seen.items():
                    if now - seen > Settings.HEARTBEAT_TIMEOUT:
                        _MISSED.labels(self._self_addr, addr).inc()
                        self._last_beat_at.pop(addr, None)
                        self._clock_skew.pop(addr, None)
                        log.warning(
                            "(%s) declaring %s dead: no heartbeat for %.1fs "
                            "(timeout %.1fs)",
                            self._self_addr, addr, now - seen,
                            Settings.HEARTBEAT_TIMEOUT,
                        )
                        # remove() fires the protocol's death callbacks, so
                        # vote/aggregation waits re-evaluate immediately
                        # instead of sleeping out their fixed timeouts.
                        self._neighbors.remove(addr, notify=False)
                self._live_peers.set(
                    sum(1 for s in last_seen.values() if now - s <= Settings.HEARTBEAT_TIMEOUT)
                )
                if self._probe_fn is not None:
                    try:
                        self._probe_fn()
                    except Exception:  # probes must not stop the beat
                        log.exception(
                            "(%s) heal-detection probe failed", self._self_addr
                        )
            if self._stop.wait(Settings.HEARTBEAT_PERIOD):
                return
