"""Heartbeat-based membership / failure detector.

Parity with reference communication/protocols/heartbeater.py:33-113: a thread
broadcasts a ``beat`` every ``HEARTBEAT_PERIOD``; every second tick it sweeps
neighbors whose last_seen is older than ``HEARTBEAT_TIMEOUT``. Incoming beats
call :meth:`beat` -> ``neighbors.refresh_or_add`` — this is how non-direct
neighbors are discovered.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from p2pfl_tpu.comm.envelope import Envelope
from p2pfl_tpu.comm.neighbors import Neighbors
from p2pfl_tpu.config import Settings

HEARTBEAT_CMD = "beat"


class Heartbeater:
    def __init__(
        self,
        self_addr: str,
        neighbors: Neighbors,
        broadcast_fn: Callable[[Envelope], None],
    ) -> None:
        self._self_addr = self_addr
        self._neighbors = neighbors
        self._broadcast = broadcast_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeater-{self._self_addr}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def beat(self, source: str, timestamp: float) -> None:
        """Incoming heartbeat (reference heartbeater.py:66-80)."""
        if source == self._self_addr:
            return
        self._neighbors.refresh_or_add(source)

    def _run(self) -> None:
        tick = 0
        while not self._stop.is_set():
            try:
                env = Envelope.message(
                    self._self_addr, HEARTBEAT_CMD, args=[str(time.time())]
                )
                self._broadcast(env)
            except Exception:
                pass
            tick += 1
            if tick % 2 == 0:  # sweep stale neighbors (reference :85-105)
                now = time.time()
                for addr, seen in self._neighbors.last_seen().items():
                    if now - seen > Settings.HEARTBEAT_TIMEOUT:
                        self._neighbors.remove(addr, notify=False)
            if self._stop.wait(Settings.HEARTBEAT_PERIOD):
                return
