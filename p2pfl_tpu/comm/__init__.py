"""Communication layer: envelopes, protocols, gossip, membership."""

from p2pfl_tpu.comm.envelope import Envelope  # noqa: F401
from p2pfl_tpu.comm.protocol import CommunicationProtocol  # noqa: F401
