"""Sparse delta wire path: round-anchored deltas + error-feedback top-k.

The dense gossip path re-ships every float32 weight on every sync tick. With
``Settings.WIRE_COMPRESSION = "topk"`` the model plane switches to this
codec, which changes *what* is gossiped:

* **delta encoding** — senders transmit ``params - round_anchor`` instead of
  raw weights, where the round anchor is the model every node holds when the
  round opens (the previous round's aggregated model; snapshotted by the
  stage machine). The receiver reconstructs against ITS anchor through the
  jitted scatter-add (:func:`p2pfl_tpu.ops.aggregation.sparse_delta_apply`).
* **top-k + error feedback** — only the ``WIRE_TOPK_RATIO`` largest-magnitude
  elements of each delta tensor ship (gap-packed indices + bf16 values,
  :mod:`p2pfl_tpu.ops.serialization`); the untransmitted remainder (and the
  value quantization error) accumulates in a per-node residual that is added
  back before the next selection (DGC, Lin et al. 2018; EF-SGD, Karimireddy
  et al. 2019). Selection/scatter are jitted kernels
  (:mod:`p2pfl_tpu.ops.compression`) — no host loop walks elements.

Frames stay self-describing: the sparse layout rides the standard
``__codec__`` spec and a ``__delta__`` marker carries the anchor round +
anchor fingerprint, so receivers need no configuration. Anchor matching is
BY ROUND, not by fingerprint: FedAvg aggregation order and sparsification
itself leave nodes with fp-level (and tail-level) differences in their
round-start models, so byte-identical anchors don't exist in a live
federation. Applying a delta against an anchor that drifted by epsilon
perturbs the model by the same epsilon — the next aggregation contracts it,
and the error-feedback residual keeps the transmitted mass conserved. A
fingerprint mismatch is therefore logged (observability for genuinely
diverged peers, e.g. an aggregation-timeout node) but does not reject the
frame; a ROUND mismatch does reject (:class:`DeltaAnchorError`), because an
anchor from another round is a different model generation entirely.

Fallback ladder: no anchor yet / non-float leaves / shape mismatch → the
caller ships a dense frame (``encode_model`` returns ``None``). Dense frames
decode transparently through :func:`decode_frame` too, so mixed sparse/dense
federations interoperate.
"""

from __future__ import annotations

import logging
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import DecodingParamsError, DeltaAnchorError
from p2pfl_tpu.ops.compression import (
    CODEC_META_KEY,
    decompress_arrays,
    ef_topk_encode,
    ef_topk_quant_encode,
    pack_nibbles,
    topk_count,
    topk_select,
    unpack_nibbles,
)
from p2pfl_tpu.ops.serialization import (
    decode_sparse_indices,
    deserialize_arrays,
    encode_sparse_indices,
    serialize_arrays,
)
from p2pfl_tpu.telemetry import REGISTRY, tracing

log = logging.getLogger("p2pfl_tpu")

#: Reserved metadata key marking a frame as a round-anchored sparse delta.
DELTA_META_KEY = "__delta__"

#: Reserved metadata key describing a coalesced multi-tensor frame body: all
#: sparse tensors ride TWO shared byte planes (concatenated packed indices,
#: concatenated packed values — each optionally DEFLATEd) instead of two
#: PFLT arrays per tensor, so per-tensor header/alignment overhead is paid
#: once per frame. Per-tensor byte extents live in the ``__codec__`` spec
#: (``topk-c`` entries), making the body length-prefixed and verifiable
#: before any value is dequantized.
COALESCE_META_KEY = "__coalesce__"

#: Codec labels (telemetry + gossiper TX attribution). ``dense`` is every
#: non-sparse frame (init, fallback, catch-up, reconcile); ``masked`` is a
#: privacy-plane lattice frame (p2pfl_tpu/privacy/secagg.py — value planes
#: only, the shared rand-k support costs zero wire bytes).
CODEC_LABELS = ("topk", "topk-int8", "topk-int4", "dense", "masked")

_COMPRESSION_RATIO = REGISTRY.gauge(
    "p2pfl_wire_compression_ratio",
    "Dense float32 bytes over sparse frame bytes for the last encoded "
    "frame, by value codec (topk = bf16/f32 values)",
    labels=("node", "codec"),
)
_RESIDUAL_L2 = REGISTRY.gauge(
    "p2pfl_wire_residual_l2",
    "L2 norm of the error-feedback residual after the last encode",
    labels=("node",),
)
_SPARSE_FRAMES = REGISTRY.counter(
    "p2pfl_wire_sparse_frames_total",
    "Sparse delta frames encoded",
    labels=("node",),
)
_DENSE_FALLBACK = REGISTRY.counter(
    "p2pfl_wire_dense_fallback_total",
    "encode_model calls that fell back to the dense path",
    labels=("node",),
)


def _leaf_crc(leaves: Sequence[np.ndarray]) -> int:
    """Fingerprint of a float32 leaf list (observability, not an acceptance
    gate — see module docstring)."""
    crc = 0
    for a in leaves:
        crc = zlib.crc32(np.ascontiguousarray(a, dtype=np.float32).tobytes(), crc)
    return crc


def codec_label(value_dtype: Optional[str] = None) -> str:
    """Telemetry/TX codec label for the active sparse value dtype."""
    vd = Settings.WIRE_TOPK_VALUES if value_dtype is None else value_dtype
    return {"int8": "topk-int8", "int4": "topk-int4"}.get(vd, "topk")


def _bf16() -> np.dtype:
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _deflate_plane(raw: bytes, level: int) -> Tuple[bytes, bool]:
    """DEFLATE one coalesced byte plane; returns (bytes, deflated?). Skipped
    when it would not shrink (int4 value planes are near-incompressible)."""
    if level <= 0 or not raw:
        return raw, False
    packed = zlib.compress(raw, level)
    return (packed, True) if len(packed) < len(raw) else (raw, False)


def _inflate_plane(blob: bytes, raw_len: int) -> bytes:
    """Bounded INFLATE of a coalesced plane: a hostile frame cannot expand
    past its declared length (zip-bomb guard) or under-deliver silently."""
    if raw_len < 0 or raw_len > Settings.MAX_MESSAGE_BYTES:
        raise DecodingParamsError("coalesced plane length out of bounds")
    d = zlib.decompressobj()
    out = d.decompress(bytes(blob), raw_len)
    if len(out) != raw_len or d.decompress(b"", 1):
        raise DecodingParamsError("coalesced plane length mismatch")
    return out


def _encode_values(vals: Any, value_dtype: str) -> Tuple[bytes, Dict[str, Any]]:
    """Pack selected (or already-quantized) wire values into raw bytes plus
    the spec fields a receiver needs to invert them. For the integer layouts
    ``vals`` is the int8 grid from the quant kernel and the caller supplies
    ``scale``/``zero_point`` via the returned dict update."""
    a = np.asarray(vals)
    if value_dtype == "int4":
        return pack_nibbles(a).tobytes(), {"values": "int4"}
    if value_dtype == "int8":
        return a.astype(np.int8).tobytes(), {"values": "int8"}
    if value_dtype == "float32":
        return a.astype(np.float32).tobytes(), {"values": "float32"}
    return a.astype(_bf16()).tobytes(), {"values": "bf16"}


def _decode_values(buf: bytes, entry: Dict[str, Any], count: int) -> np.ndarray:
    """Invert :func:`_encode_values` with the pre-dequantize sanity checks:
    scale/zero-point finiteness and integer-range bounds are validated
    BEFORE any arithmetic touches the anchor, so a hostile quantized frame
    dies here as a ``DecodingParamsError`` (counted ``reason="corrupt"`` by
    the command handlers) instead of poisoning the reconstruction."""
    kind = entry.get("values", "bf16")
    if kind in ("int8", "int4"):
        scale = entry.get("scale")
        zp = entry.get("zero_point", 0)
        if (
            not isinstance(scale, (int, float))
            or not np.isfinite(scale)
            or not scale > 0
            or not isinstance(zp, (int, float))
            or not np.isfinite(zp)
        ):
            raise DecodingParamsError("quantized tensor has a hostile scale/zero-point")
        qmax = 127 if kind == "int8" else 7
        if abs(float(zp)) > qmax:
            raise DecodingParamsError("quantized zero-point outside the int range")
        if kind == "int4":
            q = unpack_nibbles(np.frombuffer(buf, np.uint8), count)
        else:
            if len(buf) < count:
                raise DecodingParamsError("int8 value plane shorter than declared")
            q = np.frombuffer(buf[:count], np.int8)
            if (np.abs(q.astype(np.int16)) > qmax).any():
                raise DecodingParamsError("int8 value outside the symmetric grid")
        return (q.astype(np.float32) - np.float32(zp)) * np.float32(scale)
    if kind == "float32":
        if len(buf) < 4 * count:
            raise DecodingParamsError("float32 value plane shorter than declared")
        return np.frombuffer(buf[: 4 * count], np.float32).copy()
    if kind == "bf16":
        if len(buf) < 2 * count:
            raise DecodingParamsError("bf16 value plane shorter than declared")
        return np.frombuffer(buf[: 2 * count], _bf16()).astype(np.float32)
    raise DecodingParamsError(f"unknown value codec {kind!r}")


class DeltaWireCodec:
    """Per-node sparse-delta encode/decode state.

    Owns the round anchor (set by the stage machine at every round boundary)
    and the error-feedback residuals (persistent across rounds — that is the
    point of error feedback). Thread-safe: encode runs on the stage thread,
    decode on transport threads.
    """

    def __init__(self, self_addr: str = "unknown-node") -> None:
        self._addr = self_addr
        self._lock = threading.RLock()
        self._anchor: Optional[List[np.ndarray]] = None  # float32 flat leaves
        self._shapes: Optional[List[tuple]] = None
        self._anchor_round: int = -1
        self._anchor_crc: int = 0
        self._residual: Optional[List[Any]] = None  # float32 flat, jax arrays
        # Anchor HISTORY for elastic async federation: windows advance per
        # node, so a lagging peer's sparse frame may be anchored several
        # windows back — keep the last ``anchor_history`` anchors (round ->
        # (flat leaves, shapes, crc)) so those frames still decode. Sync mode
        # keeps the default depth 1 (one round, one anchor — the pre-async
        # behavior, byte for byte). The async scheduler raises it to
        # ``Settings.ASYNC_ANCHOR_HISTORY``.
        self.anchor_history: int = 1
        self._history: Dict[int, Tuple[List[np.ndarray], List[tuple], int]] = {}
        # wire accounting (encode side): frames/bytes by (sparse|dense)
        self.sparse_frames = 0
        self.dense_fallback_frames = 0

    # --- anchor bookkeeping (driven by the stage machine) -------------------

    def set_anchor(self, leaves: Sequence[np.ndarray], round: int) -> None:
        """Snapshot the round-start model (float32). Residuals persist across
        rounds unless the model structure changed."""
        flat = [np.ascontiguousarray(a, dtype=np.float32).reshape(-1) for a in leaves]
        shapes = [tuple(np.asarray(a).shape) for a in leaves]
        with self._lock:
            if self._residual is not None and (
                self._shapes is None
                or [f.size for f in flat] != [int(np.prod(s, dtype=np.int64)) for s in self._shapes]
            ):
                self._residual = None
            # Retire the outgoing anchor into the history ring (async keeps
            # several so lagging peers' frames decode; depth 1 keeps none).
            if self._anchor is not None and self._anchor_round != int(round):
                self._history[self._anchor_round] = (
                    self._anchor, self._shapes, self._anchor_crc
                )
            self._anchor = flat
            self._shapes = shapes
            self._anchor_round = int(round)
            self._anchor_crc = _leaf_crc(flat)
            self._history.pop(self._anchor_round, None)
            # Trim: current + (anchor_history - 1) most recent retired rounds.
            excess = len(self._history) - max(0, self.anchor_history - 1)
            if excess > 0:
                for r in sorted(self._history)[:excess]:
                    del self._history[r]

    @property
    def anchor_round(self) -> int:
        with self._lock:
            return self._anchor_round

    def resync(self, leaves: Sequence[np.ndarray], round: int) -> None:
        """Rejoin path: re-anchor after the node fell out of phase (crash +
        restart, healed partition). Unlike :meth:`set_anchor` — the normal
        one-round-boundary advance, where residuals carry over — this DROPS
        the error-feedback residuals: they accumulated against a model
        generation the federation has moved past, and replaying them against
        the resynced anchor would inject stale mass into the next frames.
        The anchor history is dropped too — retired anchors from before the
        divergence would decode in-flight frames into the wrong generation."""
        with self._lock:
            self._residual = None
            self._history.clear()
        self.set_anchor(leaves, round)

    def reset(self) -> None:
        with self._lock:
            self._anchor = None
            self._shapes = None
            self._anchor_round = -1
            self._anchor_crc = 0
            self._residual = None
            self._history.clear()

    # --- recovery journal (management/checkpoint.py NodeJournal) ------------

    def export_state(self) -> Dict[str, Any]:
        """Snapshot of the recovery closure this codec owns: the current
        anchor (flat float32 leaves + shapes + round + fingerprint) and the
        error-feedback residuals. The anchor HISTORY is deliberately not
        exported — after a crash the federation has moved on, and retired
        anchors would decode in-flight frames into a dead generation (the
        same rationale as :meth:`resync` dropping it)."""
        with self._lock:
            return {
                "anchor": (
                    [a.copy() for a in self._anchor]
                    if self._anchor is not None
                    else None
                ),
                "shapes": list(self._shapes) if self._shapes is not None else None,
                "anchor_round": self._anchor_round,
                "anchor_crc": self._anchor_crc,
                "residual": (
                    [np.asarray(r, np.float32).copy() for r in self._residual]
                    if self._residual is not None
                    else None
                ),
            }

    def import_state(self, st: Dict[str, Any]) -> None:
        """Restore an :meth:`export_state` snapshot (crash-restart resume):
        the node re-enters the federation holding the exact anchor and EF
        residuals it journaled, so sparse frames for the journaled round
        keep decoding and the untransmitted-mass accounting survives the
        restart bit-exact."""
        with self._lock:
            anchor = st.get("anchor")
            self._anchor = (
                [np.ascontiguousarray(a, dtype=np.float32).reshape(-1) for a in anchor]
                if anchor is not None
                else None
            )
            shapes = st.get("shapes")
            self._shapes = [tuple(s) for s in shapes] if shapes is not None else None
            self._anchor_round = int(st.get("anchor_round", -1))
            self._anchor_crc = int(st.get("anchor_crc", 0))
            residual = st.get("residual")
            self._residual = (
                [np.ascontiguousarray(r, dtype=np.float32).reshape(-1) for r in residual]
                if residual is not None
                else None
            )
            self._history.clear()

    def anchor_model(self) -> Optional[Tuple[List[np.ndarray], int]]:
        """(leaves reshaped to model shapes, anchor round), or ``None`` when
        no anchor is set. This is the round-START model every in-phase node
        anchors the current round against — exactly what a healed
        partition's behind half must adopt to rejoin the ahead half's model
        generation (the reconcile catch-up payload)."""
        with self._lock:
            if self._anchor is None or self._shapes is None:
                return None
            return (
                [a.reshape(s).copy() for a, s in zip(self._anchor, self._shapes)],
                self._anchor_round,
            )

    # --- encode -------------------------------------------------------------

    def encode_model(self, model: Any, round: int) -> Optional[bytes]:
        """Sparse delta frame for ``model`` against the round anchor, or
        ``None`` when the dense path must be used (wrong scheme, no anchor
        for ``round``, structure mismatch). ``model`` is a
        :class:`~p2pfl_tpu.models.model_handle.ModelHandle`.
        """
        tagged = self.encode_tagged(model, round)
        return None if tagged is None else tagged[0]

    def encode_tagged(self, model: Any, round: int) -> Optional[Tuple[bytes, str]]:
        """Like :meth:`encode_model` but returns ``(payload, codec_label)``
        so send paths can attribute bytes per codec ("topk" / "topk-int8" /
        "topk-int4"; dense fallbacks return ``None`` and the caller labels
        the dense frame itself).

        Anchor selection: the CURRENT anchor round encodes through the
        error-feedback kernels (residuals persist — the point of EF). A
        round still in the anchor HISTORY (an overlap drain serving laggards
        after the boundary, or an async window already advanced past) encodes
        STATELESSLY against the retired anchor: those are late re-sends of a
        finished generation, and mutating the live residual stream against a
        dead anchor would corrupt the EF accounting of the current round.
        """
        if Settings.WIRE_COMPRESSION != "topk":
            return None
        with self._lock:
            ef_path = self._anchor is not None and self._anchor_round == int(round)
            if ef_path:
                anchor, shapes, crc = self._anchor, self._shapes, self._anchor_crc
            elif int(round) in self._history:
                anchor, shapes, crc = self._history[int(round)]
            else:
                self.dense_fallback_frames += 1
                _DENSE_FALLBACK.labels(self._addr).inc()
                return None
            leaves = model.get_parameters()
            if len(leaves) != len(anchor) or any(
                tuple(l.shape) != s for l, s in zip(leaves, shapes)
            ):
                self.dense_fallback_frames += 1
                _DENSE_FALLBACK.labels(self._addr).inc()
                return None
            if ef_path and self._residual is None:
                self._residual = [np.zeros((a.size,), np.float32) for a in anchor]

            ratio = Settings.WIRE_TOPK_RATIO
            value_dtype = Settings.WIRE_TOPK_VALUES
            coalesce = Settings.COALESCE_ENABLED
            label = codec_label(value_dtype)
            parts: List[np.ndarray] = []
            spec: List[Dict[str, Any]] = []
            idx_plane = bytearray()
            val_plane = bytearray()
            sparse_tensors = 0
            for i, (leaf, anchor_flat) in enumerate(zip(leaves, anchor)):
                leaf = np.asarray(leaf)
                if not np.issubdtype(leaf.dtype, np.floating) or leaf.size == 0:
                    parts.append(leaf)
                    spec.append({"codec": "raw"})
                    continue
                delta = (
                    np.ascontiguousarray(leaf, dtype=np.float32).reshape(-1)
                    - anchor_flat
                )
                if not np.isfinite(delta).all():
                    # diverged tensor: ship it raw (dense) like int8 does —
                    # sparsifying NaNs would launder the divergence. Raw here
                    # means the FULL leaf, so the receiver's reconstruction
                    # ignores its anchor for this tensor.
                    parts.append(leaf)
                    spec.append({"codec": "raw"})
                    continue
                k = topk_count(delta.size, ratio)
                # Per-tensor quantization floor: a handful of values is not
                # worth a scale header or the coarser grid — ship bf16.
                vd = value_dtype
                if vd in ("int8", "int4") and k < Settings.QUANT_MIN_VALUES:
                    vd = "bf16"
                extra: Dict[str, Any] = {}
                if ef_path:
                    if vd in ("int8", "int4"):
                        idx, q, scale, new_resid = ef_topk_quant_encode(
                            delta, self._residual[i], k, 8 if vd == "int8" else 4
                        )
                        wire_vals: Any = np.asarray(q)
                        extra = {"scale": scale, "zero_point": 0}
                    else:
                        idx, wire_vals, new_resid = ef_topk_encode(
                            delta, self._residual[i], k, vd
                        )
                    self._residual[i] = new_resid
                else:
                    idx, vals = topk_select(delta, k)
                    if vd in ("int8", "int4"):
                        qmax = 127 if vd == "int8" else 7
                        absmax = float(np.max(np.abs(vals))) if vals.size else 0.0
                        scale = absmax / qmax if absmax > 0 else 1.0
                        wire_vals = np.clip(
                            np.rint(vals / np.float32(scale)), -qmax, qmax
                        ).astype(np.int8)
                        extra = {"scale": scale, "zero_point": 0}
                    else:
                        wire_vals = vals
                # gap8 only inside the coalesced v2 body — the per-tensor
                # legacy layout stays decodable by pre-gap8 peers.
                packed, index_codec = encode_sparse_indices(
                    np.asarray(idx), allow_gap8=coalesce
                )
                val_bytes, val_entry = _encode_values(wire_vals, vd)
                val_entry.update(extra)
                sparse_tensors += 1
                if coalesce:
                    entry = {
                        "codec": "topk-c",
                        "dtype": leaf.dtype.str,
                        "shape": list(leaf.shape),
                        "index_codec": index_codec,
                        "parts": 0,
                        "k": int(np.asarray(idx).size),
                        "idx_bytes": int(packed.nbytes),
                        "val_bytes": len(val_bytes),
                    }
                    entry.update(val_entry)
                    spec.append(entry)
                    idx_plane += packed.tobytes()
                    val_plane += val_bytes
                else:
                    entry = {
                        "codec": "topk",
                        "dtype": leaf.dtype.str,
                        "shape": list(leaf.shape),
                        "index_codec": index_codec,
                        "parts": 2,
                    }
                    entry.update(val_entry)
                    spec.append(entry)
                    parts.append(packed)
                    if val_entry["values"] in ("int8", "int4"):
                        parts.append(np.frombuffer(val_bytes, np.uint8))
                    else:
                        parts.append(np.asarray(wire_vals))
            meta: Dict[str, Any] = {
                "contributors": list(model.contributors),
                "num_samples": int(model.num_samples),
                "additional_info": model.additional_info,
                CODEC_META_KEY: spec,
                DELTA_META_KEY: {
                    "round": int(round),
                    "anchor_crc": crc,
                },
            }
            if coalesce and sparse_tensors:
                level = Settings.COALESCE_DEFLATE_LEVEL
                ib, i_defl = _deflate_plane(bytes(idx_plane), level)
                vb, v_defl = _deflate_plane(bytes(val_plane), level)
                meta[COALESCE_META_KEY] = {
                    "deflate": [i_defl, v_defl],
                    "raw_len": [len(idx_plane), len(val_plane)],
                }
                parts.append(np.frombuffer(ib, np.uint8))
                parts.append(np.frombuffer(vb, np.uint8))
            # Span context rides the frame header (the gRPC weights oneof
            # has no args slot for Envelope.trace — tracing module docstring).
            wire_ctx = tracing.current_wire()
            if wire_ctx:
                meta[tracing.TRACE_META_KEY] = wire_ctx
            self.sparse_frames += 1
            _SPARSE_FRAMES.labels(self._addr).inc()
            payload = serialize_arrays(parts, meta)
            dense_bytes = sum(a.size * 4 for a in anchor) or 1
            _COMPRESSION_RATIO.labels(self._addr, label).set(
                dense_bytes / max(len(payload), 1)
            )
            if ef_path:
                _RESIDUAL_L2.labels(self._addr).set(
                    float(
                        np.sqrt(
                            sum(float(np.dot(np.asarray(r), np.asarray(r))) for r in self._residual)
                        )
                    )
                )
            return payload, label

    # --- decode -------------------------------------------------------------

    def decode_frame(self, blob: bytes) -> Tuple[List[np.ndarray], Dict[str, Any]]:
        """Decode any model-plane frame: dense frames pass through the
        standard codec inversion; sparse delta frames are reconstructed
        against the round anchor via the jitted scatter-add.

        Raises:
            DeltaAnchorError: sparse frame for a round we hold no anchor for.
            DecodingParamsError: malformed frame (any kind).
        """
        arrays, meta = deserialize_arrays(bytes(blob))
        delta_meta = meta.get(DELTA_META_KEY)
        if delta_meta is None:
            arrays = list(arrays)
            if CODEC_META_KEY in meta:
                try:
                    arrays = decompress_arrays(arrays, meta[CODEC_META_KEY])
                except DecodingParamsError:
                    raise
                except Exception as exc:
                    raise DecodingParamsError(
                        f"malformed wire codec spec: {exc}"
                    ) from exc
            return arrays, meta

        try:
            frame_round = int(delta_meta["round"])
            frame_crc = int(delta_meta.get("anchor_crc", 0))
            spec = meta[CODEC_META_KEY]
        except Exception as exc:
            raise DecodingParamsError(f"malformed delta frame metadata: {exc}") from exc

        with self._lock:
            if self._anchor is not None and self._anchor_round == frame_round:
                anchor, shapes, crc = self._anchor, self._shapes, self._anchor_crc
            elif frame_round in self._history:
                # Async lagging peer: the frame is anchored a few windows
                # back — decode against the retired anchor of that window.
                anchor, shapes, crc = self._history[frame_round]
            else:
                raise DeltaAnchorError(
                    f"no anchor for round {frame_round} "
                    f"(local anchor round: {self._anchor_round}, "
                    f"history: {sorted(self._history)})"
                )
            if frame_crc and frame_crc != crc:
                # Expected at fp-noise level in live federations (module
                # docstring); loud only for observability of true divergence.
                log.debug(
                    "(%s) delta frame anchor fingerprint differs "
                    "(round %s, theirs %08x vs ours %08x) — applying anyway",
                    self._addr, frame_round, frame_crc & 0xFFFFFFFF,
                    crc & 0xFFFFFFFF,
                )
            try:
                return self._reconstruct(arrays, spec, meta, anchor, shapes), meta
            except DecodingParamsError:
                raise
            except Exception as exc:
                raise DecodingParamsError(
                    f"malformed sparse delta frame: {exc}"
                ) from exc

    def _reconstruct(
        self,
        arrays: Sequence[np.ndarray],
        spec: Sequence[Dict[str, Any]],
        meta: Dict[str, Any],
        anchor: List[np.ndarray],
        shapes: List[tuple],
    ) -> List[np.ndarray]:
        """anchor + scatter(delta) per leaf (caller holds the lock).

        Every structural fact a hostile frame controls — plane lengths,
        per-tensor byte extents, integer ranges, scale/zero-point
        finiteness, index bounds — is validated BEFORE the first value is
        dequantized or scattered, so corruption surfaces as a counted
        ``corrupt`` rejection and never perturbs the anchor or residuals.
        """
        import jax.numpy as jnp

        from p2pfl_tpu.ops.aggregation import sparse_delta_apply

        if len(spec) != len(anchor):
            raise DecodingParamsError(
                f"delta frame has {len(spec)} tensors, model has {len(anchor)}"
            )
        arrays = list(arrays)
        co = meta.get(COALESCE_META_KEY)
        idx_plane = val_plane = b""
        if co is not None:
            # Coalesced body: the LAST two arrays are the shared byte planes.
            try:
                raw_len = [int(x) for x in co["raw_len"]]
                deflate = [bool(x) for x in co["deflate"]]
            except Exception as exc:
                raise DecodingParamsError(f"malformed coalesce header: {exc}") from exc
            if len(arrays) < 2 or len(raw_len) != 2 or len(deflate) != 2:
                raise DecodingParamsError("coalesced frame missing its byte planes")
            planes = [np.asarray(a).tobytes() for a in arrays[-2:]]
            arrays = arrays[:-2]
            try:
                idx_plane = (
                    _inflate_plane(planes[0], raw_len[0]) if deflate[0] else planes[0]
                )
                val_plane = (
                    _inflate_plane(planes[1], raw_len[1]) if deflate[1] else planes[1]
                )
            except zlib.error as exc:
                raise DecodingParamsError(f"coalesced plane inflate failed: {exc}") from exc
            if len(idx_plane) != raw_len[0] or len(val_plane) != raw_len[1]:
                raise DecodingParamsError("coalesced plane length mismatch")
            declared_idx = sum(
                int(s.get("idx_bytes", 0)) for s in spec if s.get("codec") == "topk-c"
            )
            declared_val = sum(
                int(s.get("val_bytes", 0)) for s in spec if s.get("codec") == "topk-c"
            )
            if declared_idx != len(idx_plane) or declared_val != len(val_plane):
                raise DecodingParamsError(
                    "coalesced tensor extents disagree with the plane lengths"
                )
        expected = sum(int(s.get("parts", 1)) for s in spec)
        if expected != len(arrays):
            raise DecodingParamsError("delta frame part count mismatch")
        out: List[np.ndarray] = []
        pos = 0
        io = vo = 0  # plane cursors (coalesced tensors)
        for i, s in enumerate(spec):
            codec = s.get("codec", "raw")
            if codec == "raw":
                out.append(np.asarray(arrays[pos]))
                pos += 1
                continue
            if codec not in ("topk", "topk-c"):
                raise DecodingParamsError(
                    f"unexpected tensor codec {codec!r} in delta frame"
                )
            shape = tuple(s["shape"])
            if shape != shapes[i]:
                raise DecodingParamsError(
                    f"delta tensor {i} shape {shape} != model {shapes[i]}"
                )
            if codec == "topk-c":
                if co is None:
                    raise DecodingParamsError("topk-c tensor without a coalesce header")
                k = int(s["k"])
                ib, vb = int(s["idx_bytes"]), int(s["val_bytes"])
                if k < 0 or ib < 0 or vb < 0:
                    raise DecodingParamsError("negative coalesced tensor extent")
                idx_bytes = idx_plane[io : io + ib]
                val_bytes = val_plane[vo : vo + vb]
                io += ib
                vo += vb
                icodec = s["index_codec"]
                try:
                    dt = {"gap8": np.uint8, "gap16": np.uint16, "abs32": np.uint32}[
                        icodec
                    ]
                except KeyError:
                    raise DecodingParamsError(
                        f"unknown sparse index codec {icodec!r}"
                    ) from None
                if ib != k * np.dtype(dt).itemsize:
                    raise DecodingParamsError("index extent disagrees with k")
                packed = np.frombuffer(idx_bytes, dt)
                vals32 = _decode_values(val_bytes, s, k)
            else:
                packed, vals = arrays[pos], arrays[pos + 1]
                pos += 2
                if s.get("values") in ("int8", "int4"):
                    vals32 = None  # resolved below once idx is decoded
                else:
                    vals32 = np.asarray(vals).astype(np.float32)
                icodec = s["index_codec"]
            idx = decode_sparse_indices(np.asarray(packed), icodec)
            if codec == "topk" and vals32 is None:
                # Quantized uncoalesced layout: the value array is the raw
                # int8/uint8 plane; idx.size is the value count.
                vals32 = _decode_values(np.asarray(vals).tobytes(), s, idx.size)
            size = anchor[i].size
            if idx.size != np.asarray(vals32).size:
                raise DecodingParamsError("sparse index/values length mismatch")
            if idx.size and (int(idx[-1]) >= size or int(idx[0]) < 0):
                raise DecodingParamsError("sparse index out of tensor bounds")
            dense = sparse_delta_apply(
                jnp.asarray(anchor[i]),
                jnp.asarray(idx, jnp.int32),
                jnp.asarray(np.asarray(vals32, dtype=np.float32)),
            )
            out.append(
                np.asarray(dense).reshape(shape).astype(np.dtype(s["dtype"]))
            )
        return out
