"""Sparse delta wire path: round-anchored deltas + error-feedback top-k.

The dense gossip path re-ships every float32 weight on every sync tick. With
``Settings.WIRE_COMPRESSION = "topk"`` the model plane switches to this
codec, which changes *what* is gossiped:

* **delta encoding** — senders transmit ``params - round_anchor`` instead of
  raw weights, where the round anchor is the model every node holds when the
  round opens (the previous round's aggregated model; snapshotted by the
  stage machine). The receiver reconstructs against ITS anchor through the
  jitted scatter-add (:func:`p2pfl_tpu.ops.aggregation.sparse_delta_apply`).
* **top-k + error feedback** — only the ``WIRE_TOPK_RATIO`` largest-magnitude
  elements of each delta tensor ship (gap-packed indices + bf16 values,
  :mod:`p2pfl_tpu.ops.serialization`); the untransmitted remainder (and the
  value quantization error) accumulates in a per-node residual that is added
  back before the next selection (DGC, Lin et al. 2018; EF-SGD, Karimireddy
  et al. 2019). Selection/scatter are jitted kernels
  (:mod:`p2pfl_tpu.ops.compression`) — no host loop walks elements.

Frames stay self-describing: the sparse layout rides the standard
``__codec__`` spec and a ``__delta__`` marker carries the anchor round +
anchor fingerprint, so receivers need no configuration. Anchor matching is
BY ROUND, not by fingerprint: FedAvg aggregation order and sparsification
itself leave nodes with fp-level (and tail-level) differences in their
round-start models, so byte-identical anchors don't exist in a live
federation. Applying a delta against an anchor that drifted by epsilon
perturbs the model by the same epsilon — the next aggregation contracts it,
and the error-feedback residual keeps the transmitted mass conserved. A
fingerprint mismatch is therefore logged (observability for genuinely
diverged peers, e.g. an aggregation-timeout node) but does not reject the
frame; a ROUND mismatch does reject (:class:`DeltaAnchorError`), because an
anchor from another round is a different model generation entirely.

Fallback ladder: no anchor yet / non-float leaves / shape mismatch → the
caller ships a dense frame (``encode_model`` returns ``None``). Dense frames
decode transparently through :func:`decode_frame` too, so mixed sparse/dense
federations interoperate.
"""

from __future__ import annotations

import logging
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import DecodingParamsError, DeltaAnchorError
from p2pfl_tpu.ops.compression import (
    CODEC_META_KEY,
    decompress_arrays,
    ef_topk_encode,
    topk_count,
)
from p2pfl_tpu.ops.serialization import (
    decode_sparse_indices,
    deserialize_arrays,
    encode_sparse_indices,
    serialize_arrays,
)
from p2pfl_tpu.telemetry import REGISTRY, tracing

log = logging.getLogger("p2pfl_tpu")

#: Reserved metadata key marking a frame as a round-anchored sparse delta.
DELTA_META_KEY = "__delta__"

_COMPRESSION_RATIO = REGISTRY.gauge(
    "p2pfl_wire_compression_ratio",
    "Dense float32 bytes over sparse frame bytes for the last encoded frame",
    labels=("node",),
)
_RESIDUAL_L2 = REGISTRY.gauge(
    "p2pfl_wire_residual_l2",
    "L2 norm of the error-feedback residual after the last encode",
    labels=("node",),
)
_SPARSE_FRAMES = REGISTRY.counter(
    "p2pfl_wire_sparse_frames_total",
    "Sparse delta frames encoded",
    labels=("node",),
)
_DENSE_FALLBACK = REGISTRY.counter(
    "p2pfl_wire_dense_fallback_total",
    "encode_model calls that fell back to the dense path",
    labels=("node",),
)


def _leaf_crc(leaves: Sequence[np.ndarray]) -> int:
    """Fingerprint of a float32 leaf list (observability, not an acceptance
    gate — see module docstring)."""
    crc = 0
    for a in leaves:
        crc = zlib.crc32(np.ascontiguousarray(a, dtype=np.float32).tobytes(), crc)
    return crc


class DeltaWireCodec:
    """Per-node sparse-delta encode/decode state.

    Owns the round anchor (set by the stage machine at every round boundary)
    and the error-feedback residuals (persistent across rounds — that is the
    point of error feedback). Thread-safe: encode runs on the stage thread,
    decode on transport threads.
    """

    def __init__(self, self_addr: str = "unknown-node") -> None:
        self._addr = self_addr
        self._lock = threading.RLock()
        self._anchor: Optional[List[np.ndarray]] = None  # float32 flat leaves
        self._shapes: Optional[List[tuple]] = None
        self._anchor_round: int = -1
        self._anchor_crc: int = 0
        self._residual: Optional[List[Any]] = None  # float32 flat, jax arrays
        # Anchor HISTORY for elastic async federation: windows advance per
        # node, so a lagging peer's sparse frame may be anchored several
        # windows back — keep the last ``anchor_history`` anchors (round ->
        # (flat leaves, shapes, crc)) so those frames still decode. Sync mode
        # keeps the default depth 1 (one round, one anchor — the pre-async
        # behavior, byte for byte). The async scheduler raises it to
        # ``Settings.ASYNC_ANCHOR_HISTORY``.
        self.anchor_history: int = 1
        self._history: Dict[int, Tuple[List[np.ndarray], List[tuple], int]] = {}
        # wire accounting (encode side): frames/bytes by (sparse|dense)
        self.sparse_frames = 0
        self.dense_fallback_frames = 0

    # --- anchor bookkeeping (driven by the stage machine) -------------------

    def set_anchor(self, leaves: Sequence[np.ndarray], round: int) -> None:
        """Snapshot the round-start model (float32). Residuals persist across
        rounds unless the model structure changed."""
        flat = [np.ascontiguousarray(a, dtype=np.float32).reshape(-1) for a in leaves]
        shapes = [tuple(np.asarray(a).shape) for a in leaves]
        with self._lock:
            if self._residual is not None and (
                self._shapes is None
                or [f.size for f in flat] != [int(np.prod(s, dtype=np.int64)) for s in self._shapes]
            ):
                self._residual = None
            # Retire the outgoing anchor into the history ring (async keeps
            # several so lagging peers' frames decode; depth 1 keeps none).
            if self._anchor is not None and self._anchor_round != int(round):
                self._history[self._anchor_round] = (
                    self._anchor, self._shapes, self._anchor_crc
                )
            self._anchor = flat
            self._shapes = shapes
            self._anchor_round = int(round)
            self._anchor_crc = _leaf_crc(flat)
            self._history.pop(self._anchor_round, None)
            # Trim: current + (anchor_history - 1) most recent retired rounds.
            excess = len(self._history) - max(0, self.anchor_history - 1)
            if excess > 0:
                for r in sorted(self._history)[:excess]:
                    del self._history[r]

    @property
    def anchor_round(self) -> int:
        with self._lock:
            return self._anchor_round

    def resync(self, leaves: Sequence[np.ndarray], round: int) -> None:
        """Rejoin path: re-anchor after the node fell out of phase (crash +
        restart, healed partition). Unlike :meth:`set_anchor` — the normal
        one-round-boundary advance, where residuals carry over — this DROPS
        the error-feedback residuals: they accumulated against a model
        generation the federation has moved past, and replaying them against
        the resynced anchor would inject stale mass into the next frames.
        The anchor history is dropped too — retired anchors from before the
        divergence would decode in-flight frames into the wrong generation."""
        with self._lock:
            self._residual = None
            self._history.clear()
        self.set_anchor(leaves, round)

    def reset(self) -> None:
        with self._lock:
            self._anchor = None
            self._shapes = None
            self._anchor_round = -1
            self._anchor_crc = 0
            self._residual = None
            self._history.clear()

    # --- recovery journal (management/checkpoint.py NodeJournal) ------------

    def export_state(self) -> Dict[str, Any]:
        """Snapshot of the recovery closure this codec owns: the current
        anchor (flat float32 leaves + shapes + round + fingerprint) and the
        error-feedback residuals. The anchor HISTORY is deliberately not
        exported — after a crash the federation has moved on, and retired
        anchors would decode in-flight frames into a dead generation (the
        same rationale as :meth:`resync` dropping it)."""
        with self._lock:
            return {
                "anchor": (
                    [a.copy() for a in self._anchor]
                    if self._anchor is not None
                    else None
                ),
                "shapes": list(self._shapes) if self._shapes is not None else None,
                "anchor_round": self._anchor_round,
                "anchor_crc": self._anchor_crc,
                "residual": (
                    [np.asarray(r, np.float32).copy() for r in self._residual]
                    if self._residual is not None
                    else None
                ),
            }

    def import_state(self, st: Dict[str, Any]) -> None:
        """Restore an :meth:`export_state` snapshot (crash-restart resume):
        the node re-enters the federation holding the exact anchor and EF
        residuals it journaled, so sparse frames for the journaled round
        keep decoding and the untransmitted-mass accounting survives the
        restart bit-exact."""
        with self._lock:
            anchor = st.get("anchor")
            self._anchor = (
                [np.ascontiguousarray(a, dtype=np.float32).reshape(-1) for a in anchor]
                if anchor is not None
                else None
            )
            shapes = st.get("shapes")
            self._shapes = [tuple(s) for s in shapes] if shapes is not None else None
            self._anchor_round = int(st.get("anchor_round", -1))
            self._anchor_crc = int(st.get("anchor_crc", 0))
            residual = st.get("residual")
            self._residual = (
                [np.ascontiguousarray(r, dtype=np.float32).reshape(-1) for r in residual]
                if residual is not None
                else None
            )
            self._history.clear()

    def anchor_model(self) -> Optional[Tuple[List[np.ndarray], int]]:
        """(leaves reshaped to model shapes, anchor round), or ``None`` when
        no anchor is set. This is the round-START model every in-phase node
        anchors the current round against — exactly what a healed
        partition's behind half must adopt to rejoin the ahead half's model
        generation (the reconcile catch-up payload)."""
        with self._lock:
            if self._anchor is None or self._shapes is None:
                return None
            return (
                [a.reshape(s).copy() for a, s in zip(self._anchor, self._shapes)],
                self._anchor_round,
            )

    # --- encode -------------------------------------------------------------

    def encode_model(self, model: Any, round: int) -> Optional[bytes]:
        """Sparse delta frame for ``model`` against the round anchor, or
        ``None`` when the dense path must be used (wrong scheme, no anchor
        for ``round``, structure mismatch). ``model`` is a
        :class:`~p2pfl_tpu.models.model_handle.ModelHandle`.
        """
        if Settings.WIRE_COMPRESSION != "topk":
            return None
        with self._lock:
            if self._anchor is None or self._anchor_round != int(round):
                self.dense_fallback_frames += 1
                _DENSE_FALLBACK.labels(self._addr).inc()
                return None
            leaves = model.get_parameters()
            if len(leaves) != len(self._anchor) or any(
                tuple(l.shape) != s for l, s in zip(leaves, self._shapes)
            ):
                self.dense_fallback_frames += 1
                _DENSE_FALLBACK.labels(self._addr).inc()
                return None
            if self._residual is None:
                self._residual = [np.zeros((a.size,), np.float32) for a in self._anchor]

            ratio = Settings.WIRE_TOPK_RATIO
            value_dtype = Settings.WIRE_TOPK_VALUES
            parts: List[np.ndarray] = []
            spec: List[Dict[str, Any]] = []
            for i, (leaf, anchor_flat) in enumerate(zip(leaves, self._anchor)):
                leaf = np.asarray(leaf)
                if not np.issubdtype(leaf.dtype, np.floating) or leaf.size == 0:
                    parts.append(leaf)
                    spec.append({"codec": "raw"})
                    continue
                delta = (
                    np.ascontiguousarray(leaf, dtype=np.float32).reshape(-1)
                    - anchor_flat
                )
                if not np.isfinite(delta).all():
                    # diverged tensor: ship it raw (dense) like int8 does —
                    # sparsifying NaNs would launder the divergence. Raw here
                    # means the FULL leaf, so the receiver's reconstruction
                    # ignores its anchor for this tensor.
                    parts.append(leaf)
                    spec.append({"codec": "raw"})
                    continue
                k = topk_count(delta.size, ratio)
                idx, wire_vals, new_resid = ef_topk_encode(
                    delta, self._residual[i], k, value_dtype
                )
                self._residual[i] = new_resid
                packed, index_codec = encode_sparse_indices(np.asarray(idx))
                parts.append(packed)
                parts.append(np.asarray(wire_vals))
                spec.append(
                    {
                        "codec": "topk",
                        "dtype": leaf.dtype.str,
                        "shape": list(leaf.shape),
                        "index_codec": index_codec,
                        "parts": 2,
                    }
                )
            meta: Dict[str, Any] = {
                "contributors": list(model.contributors),
                "num_samples": int(model.num_samples),
                "additional_info": model.additional_info,
                CODEC_META_KEY: spec,
                DELTA_META_KEY: {
                    "round": int(round),
                    "anchor_crc": self._anchor_crc,
                },
            }
            # Span context rides the frame header (the gRPC weights oneof
            # has no args slot for Envelope.trace — tracing module docstring).
            wire_ctx = tracing.current_wire()
            if wire_ctx:
                meta[tracing.TRACE_META_KEY] = wire_ctx
            self.sparse_frames += 1
            _SPARSE_FRAMES.labels(self._addr).inc()
            payload = serialize_arrays(parts, meta)
            dense_bytes = sum(a.size * 4 for a in self._anchor) or 1
            _COMPRESSION_RATIO.labels(self._addr).set(dense_bytes / max(len(payload), 1))
            _RESIDUAL_L2.labels(self._addr).set(
                float(
                    np.sqrt(
                        sum(float(np.dot(np.asarray(r), np.asarray(r))) for r in self._residual)
                    )
                )
            )
            return payload

    # --- decode -------------------------------------------------------------

    def decode_frame(self, blob: bytes) -> Tuple[List[np.ndarray], Dict[str, Any]]:
        """Decode any model-plane frame: dense frames pass through the
        standard codec inversion; sparse delta frames are reconstructed
        against the round anchor via the jitted scatter-add.

        Raises:
            DeltaAnchorError: sparse frame for a round we hold no anchor for.
            DecodingParamsError: malformed frame (any kind).
        """
        arrays, meta = deserialize_arrays(bytes(blob))
        delta_meta = meta.get(DELTA_META_KEY)
        if delta_meta is None:
            arrays = list(arrays)
            if CODEC_META_KEY in meta:
                try:
                    arrays = decompress_arrays(arrays, meta[CODEC_META_KEY])
                except DecodingParamsError:
                    raise
                except Exception as exc:
                    raise DecodingParamsError(
                        f"malformed wire codec spec: {exc}"
                    ) from exc
            return arrays, meta

        try:
            frame_round = int(delta_meta["round"])
            frame_crc = int(delta_meta.get("anchor_crc", 0))
            spec = meta[CODEC_META_KEY]
        except Exception as exc:
            raise DecodingParamsError(f"malformed delta frame metadata: {exc}") from exc

        with self._lock:
            if self._anchor is not None and self._anchor_round == frame_round:
                anchor, shapes, crc = self._anchor, self._shapes, self._anchor_crc
            elif frame_round in self._history:
                # Async lagging peer: the frame is anchored a few windows
                # back — decode against the retired anchor of that window.
                anchor, shapes, crc = self._history[frame_round]
            else:
                raise DeltaAnchorError(
                    f"no anchor for round {frame_round} "
                    f"(local anchor round: {self._anchor_round}, "
                    f"history: {sorted(self._history)})"
                )
            if frame_crc and frame_crc != crc:
                # Expected at fp-noise level in live federations (module
                # docstring); loud only for observability of true divergence.
                log.debug(
                    "(%s) delta frame anchor fingerprint differs "
                    "(round %s, theirs %08x vs ours %08x) — applying anyway",
                    self._addr, frame_round, frame_crc & 0xFFFFFFFF,
                    crc & 0xFFFFFFFF,
                )
            try:
                return self._reconstruct(arrays, spec, anchor, shapes), meta
            except DecodingParamsError:
                raise
            except Exception as exc:
                raise DecodingParamsError(
                    f"malformed sparse delta frame: {exc}"
                ) from exc

    def _reconstruct(
        self,
        arrays: Sequence[np.ndarray],
        spec: Sequence[Dict[str, Any]],
        anchor: List[np.ndarray],
        shapes: List[tuple],
    ) -> List[np.ndarray]:
        """anchor + scatter(delta) per leaf (caller holds the lock)."""
        import jax.numpy as jnp

        from p2pfl_tpu.ops.aggregation import sparse_delta_apply

        if len(spec) != len(anchor):
            raise DecodingParamsError(
                f"delta frame has {len(spec)} tensors, model has {len(anchor)}"
            )
        expected = sum(int(s.get("parts", 1)) for s in spec)
        if expected != len(arrays):
            raise DecodingParamsError("delta frame part count mismatch")
        out: List[np.ndarray] = []
        pos = 0
        for i, s in enumerate(spec):
            codec = s.get("codec", "raw")
            if codec == "raw":
                out.append(np.asarray(arrays[pos]))
                pos += 1
                continue
            if codec != "topk":
                raise DecodingParamsError(
                    f"unexpected tensor codec {codec!r} in delta frame"
                )
            packed, vals = arrays[pos], arrays[pos + 1]
            pos += 2
            shape = tuple(s["shape"])
            if shape != shapes[i]:
                raise DecodingParamsError(
                    f"delta tensor {i} shape {shape} != model {shapes[i]}"
                )
            idx = decode_sparse_indices(np.asarray(packed), s["index_codec"])
            size = anchor[i].size
            if idx.size != np.asarray(vals).size:
                raise DecodingParamsError("sparse index/values length mismatch")
            if idx.size and (int(idx[-1]) >= size or int(idx[0]) < 0):
                raise DecodingParamsError("sparse index out of tensor bounds")
            dense = sparse_delta_apply(
                jnp.asarray(anchor[i]),
                jnp.asarray(idx, jnp.int32),
                jnp.asarray(np.asarray(vals).astype(np.float32)),
            )
            out.append(
                np.asarray(dense).reshape(shape).astype(np.dtype(s["dtype"]))
            )
        return out
