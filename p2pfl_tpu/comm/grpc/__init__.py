"""gRPC transport for real (multi-process / multi-host) federations."""

from p2pfl_tpu.comm.grpc.grpc_protocol import GrpcCommunicationProtocol  # noqa: F401
