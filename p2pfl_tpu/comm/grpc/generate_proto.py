"""Regenerate ``node_pb2.py`` from ``node.proto``.

Parity: the reference ships the same convenience as
``p2pfl/communication/protocols/grpc/proto/generate_proto.py`` (it shells
out to grpc_tools.protoc). This image has the ``protoc`` binary but not
``grpc_tools``, and the transport registers its RPC methods manually
(grpc_protocol.py builds ``grpc.unary_unary`` handlers itself), so plain
``--python_out`` is the whole job — no ``_grpc`` stub module exists.

Usage::

    python -m p2pfl_tpu.comm.grpc.generate_proto [--check]

``--check`` regenerates into a temp dir and exits nonzero if the committed
``node_pb2.py`` is stale (useful as a CI gate after editing node.proto).
"""

from __future__ import annotations

import shutil
import subprocess
import sys
import tempfile
from pathlib import Path

HERE = Path(__file__).resolve().parent


def generate(out_dir: Path) -> Path:
    protoc = shutil.which("protoc")
    if protoc is None:
        raise RuntimeError("protoc not found on PATH")
    subprocess.run(
        [protoc, f"--proto_path={HERE}", f"--python_out={out_dir}", "node.proto"],
        check=True,
    )
    return out_dir / "node_pb2.py"


def main(argv: list[str]) -> int:
    if "--check" in argv:
        with tempfile.TemporaryDirectory() as td:
            fresh = generate(Path(td)).read_bytes()
        committed = (HERE / "node_pb2.py").read_bytes()
        if fresh != committed:
            print(
                "node_pb2.py is stale (or protoc version drift): regenerate "
                "with `python -m p2pfl_tpu.comm.grpc.generate_proto`",
                file=sys.stderr,
            )
            return 1
        print("node_pb2.py is up to date")
        return 0
    path = generate(HERE)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
