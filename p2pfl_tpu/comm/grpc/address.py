"""Address parsing for the gRPC transport.

Parity with reference grpc/address.py:26-114: IPv4 / IPv6 / unix-socket
targets, random free port assignment when none is given.
"""

from __future__ import annotations

import ipaddress
import socket
from typing import Optional, Tuple


def free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def parse_address(addr: Optional[str]) -> Tuple[str, str]:
    """Normalize an address into (bind_target, public_addr).

    Accepts ``None`` (fresh localhost:random-port), ``"host"``,
    ``"host:port"``, ``"[ipv6]:port"`` and ``"unix:..."`` / ``"unix://..."``.
    """
    if addr is None or addr == "":
        port = free_port()
        return f"127.0.0.1:{port}", f"127.0.0.1:{port}"
    if addr.startswith("unix:"):
        return addr, addr
    host: str
    port: Optional[str]
    if addr.startswith("["):  # [ipv6]:port
        closing = addr.index("]")
        host = addr[1:closing]
        rest = addr[closing + 1 :]
        port = rest[1:] if rest.startswith(":") else None
    elif addr.count(":") > 1:  # bare ipv6 without port
        host, port = addr, None
    elif ":" in addr:
        host, port = addr.rsplit(":", 1)
    else:
        host, port = addr, None
    if port is None:
        port = str(free_port())
    try:
        is_v6 = isinstance(ipaddress.ip_address(host), ipaddress.IPv6Address)
    except ValueError:
        is_v6 = False  # hostname
    target = f"[{host}]:{port}" if is_v6 else f"{host}:{port}"
    return target, target
