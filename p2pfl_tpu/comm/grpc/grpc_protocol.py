"""gRPC communication protocol.

Capability parity with the reference's gRPC stack
(grpc_communication_protocol.py:50-263, grpc_server.py:36-237,
grpc_client.py:35-208, grpc_neighbors.py:32-144): handshake/disconnect/send
unary RPCs, 1 GiB message cap, optional mTLS from Settings, send-failure
removes the neighbor, TTL-decrement re-gossip on the server side.

Implementation notes (departures by design):
* grpcio-tools isn't available in the image, so the service is registered
  through grpc's *generic handler* API with serializers from the
  protoc-generated ``node_pb2`` — same wire format, no generated stub class.
* the server thread pool is 8 workers (the reference caps at 2,
  grpc_server.py:67, which serializes model reception).
"""

from __future__ import annotations

import concurrent.futures
import logging
from typing import Any, Optional

import grpc

from p2pfl_tpu.comm.envelope import Envelope
from p2pfl_tpu.comm.grpc import node_pb2
from p2pfl_tpu.comm.grpc.address import parse_address
from p2pfl_tpu.comm.neighbors import Neighbors
from p2pfl_tpu.comm.protocol import CommunicationProtocol
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import CommunicationError
from p2pfl_tpu.telemetry import bundle as bundle_mod
from p2pfl_tpu.telemetry import digest as digest_mod
from p2pfl_tpu.telemetry import tracing

log = logging.getLogger("p2pfl_tpu")

_SERVICE = "p2pfl_tpu.NodeService"


def _env_to_pb(env: Envelope) -> node_pb2.Envelope:
    pb = node_pb2.Envelope(source=env.source, cmd=env.cmd, round=env.round)
    if env.is_weights:
        # protobuf only accepts bytes; the native codec hands out bytearray.
        # No trace slot here: traced weights frames carry their span context
        # in the PFLT header (tracing.TRACE_META_KEY) instead.
        pb.weights.payload = bytes(env.payload)
        pb.weights.contributors.extend(env.contributors)
        pb.weights.num_samples = env.num_samples
    else:
        pb.control.args.extend(env.args)
        if env.digest:
            # Reserved trailing args (digest before trace, popped in reverse
            # by _pb_to_env): the schema predates tracing/digests and protoc
            # isn't in the image to regenerate it; every receiver strips
            # these before dispatch, and a version-skewed peer just sees
            # extra args (handlers index from the front).
            pb.control.args.append(digest_mod.WIRE_ARG_PREFIX + env.digest)
        if env.trace:
            pb.control.args.append(tracing.WIRE_ARG_PREFIX + env.trace)
        if env.run_id:
            pb.control.args.append(bundle_mod.WIRE_ARG_PREFIX + env.run_id)
        pb.control.ttl = env.ttl
        pb.control.msg_id = env.msg_id
    return pb


def _pb_to_env(pb: node_pb2.Envelope) -> Envelope:
    if pb.WhichOneof("body") == "weights":
        return Envelope(
            source=pb.source,
            cmd=pb.cmd,
            round=pb.round,
            payload=bytes(pb.weights.payload),
            contributors=list(pb.weights.contributors),
            num_samples=int(pb.weights.num_samples),
        )
    args = list(pb.control.args)
    run_id = ""
    if args and args[-1].startswith(bundle_mod.WIRE_ARG_PREFIX):
        run_id = args.pop()[len(bundle_mod.WIRE_ARG_PREFIX):]
    trace = ""
    if args and args[-1].startswith(tracing.WIRE_ARG_PREFIX):
        trace = args.pop()[len(tracing.WIRE_ARG_PREFIX):]
    digest = ""
    if args and args[-1].startswith(digest_mod.WIRE_ARG_PREFIX):
        digest = args.pop()[len(digest_mod.WIRE_ARG_PREFIX):]
    return Envelope(
        source=pb.source,
        cmd=pb.cmd,
        round=pb.round,
        args=args,
        ttl=int(pb.control.ttl),
        msg_id=int(pb.control.msg_id),
        trace=trace,
        digest=digest,
        run_id=run_id,
    )


class _GrpcConnection:
    """Channel + unary callables for one neighbor."""

    def __init__(self, addr: str, self_addr: str) -> None:
        options = [
            ("grpc.max_send_message_length", Settings.MAX_MESSAGE_BYTES),
            ("grpc.max_receive_message_length", Settings.MAX_MESSAGE_BYTES),
        ]
        if Settings.USE_SSL:
            with open(Settings.SSL_CLIENT_KEY, "rb") as f:
                key = f.read()
            with open(Settings.SSL_CLIENT_CRT, "rb") as f:
                crt = f.read()
            with open(Settings.SSL_CA_CRT, "rb") as f:
                ca = f.read()
            creds = grpc.ssl_channel_credentials(
                root_certificates=ca, private_key=key, certificate_chain=crt
            )
            self.channel = grpc.secure_channel(addr, creds, options=options)
        else:
            self.channel = grpc.insecure_channel(addr, options=options)
        self._self_addr = self_addr
        self.handshake = self.channel.unary_unary(
            f"/{_SERVICE}/Handshake",
            request_serializer=node_pb2.Hello.SerializeToString,
            response_deserializer=node_pb2.Ack.FromString,
        )
        self.disconnect = self.channel.unary_unary(
            f"/{_SERVICE}/Disconnect",
            request_serializer=node_pb2.Hello.SerializeToString,
            response_deserializer=node_pb2.Ack.FromString,
        )
        self.send = self.channel.unary_unary(
            f"/{_SERVICE}/Send",
            request_serializer=node_pb2.Envelope.SerializeToString,
            response_deserializer=node_pb2.Ack.FromString,
        )

    def close(self) -> None:
        try:
            self.channel.close()
        except Exception:
            pass


class _GrpcNeighbors(Neighbors):
    def connect_to(self, addr: str, *, handshake: bool) -> _GrpcConnection:
        conn = _GrpcConnection(addr, self.self_addr)
        if handshake:
            try:
                ack = conn.handshake(
                    node_pb2.Hello(addr=self.self_addr), timeout=Settings.GRPC_TIMEOUT
                )
                if ack.error:
                    raise CommunicationError(ack.error)
            except grpc.RpcError as exc:
                conn.close()
                raise CommunicationError(f"handshake with {addr} failed: {exc.code()}") from exc
        return conn

    def disconnect_from(self, addr: str, conn: _GrpcConnection, *, notify: bool) -> None:
        if notify:
            try:
                conn.disconnect(
                    node_pb2.Hello(addr=self.self_addr), timeout=Settings.GRPC_TIMEOUT
                )
            except grpc.RpcError:
                pass
        conn.close()


class GrpcCommunicationProtocol(CommunicationProtocol):
    """Real-network transport (reference grpc_communication_protocol.py:50)."""

    def __init__(self, addr: Optional[str] = None) -> None:
        bind_target, public = parse_address(addr)
        self._bind_target = bind_target
        super().__init__(public)
        self._server: Optional[grpc.Server] = None

    def _default_addr(self) -> str:  # pragma: no cover - set via __init__
        raise RuntimeError("address resolved in __init__")

    def _build_neighbors(self, addr: str) -> Neighbors:
        return _GrpcNeighbors(addr)

    # --- server -------------------------------------------------------------

    def _server_start(self) -> None:
        protocol = self

        def handshake(request: node_pb2.Hello, context: Any) -> node_pb2.Ack:
            try:
                protocol.neighbors.add(request.addr, non_direct=False, handshake=False)
                return node_pb2.Ack()
            except Exception as exc:  # pragma: no cover
                return node_pb2.Ack(error=str(exc))

        def disconnect(request: node_pb2.Hello, context: Any) -> node_pb2.Ack:
            # Graceful goodbye from the peer — not a failure departure.
            protocol.neighbors.remove(request.addr, notify=False, departed=False)
            return node_pb2.Ack()

        def send(request: node_pb2.Envelope, context: Any) -> node_pb2.Ack:
            try:
                protocol.handle_envelope(_pb_to_env(request))
                return node_pb2.Ack()
            except Exception as exc:
                log.exception("error handling %s from %s", request.cmd, request.source)
                return node_pb2.Ack(error=str(exc))

        rpcs = {
            "Handshake": grpc.unary_unary_rpc_method_handler(
                handshake,
                request_deserializer=node_pb2.Hello.FromString,
                response_serializer=node_pb2.Ack.SerializeToString,
            ),
            "Disconnect": grpc.unary_unary_rpc_method_handler(
                disconnect,
                request_deserializer=node_pb2.Hello.FromString,
                response_serializer=node_pb2.Ack.SerializeToString,
            ),
            "Send": grpc.unary_unary_rpc_method_handler(
                send,
                request_deserializer=node_pb2.Envelope.FromString,
                response_serializer=node_pb2.Ack.SerializeToString,
            ),
        }
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix=f"grpc-{self.addr}"
            ),
            handlers=[grpc.method_handlers_generic_handler(_SERVICE, rpcs)],
            options=[
                ("grpc.max_send_message_length", Settings.MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", Settings.MAX_MESSAGE_BYTES),
            ],
        )
        if Settings.USE_SSL:
            with open(Settings.SSL_SERVER_KEY, "rb") as f:
                key = f.read()
            with open(Settings.SSL_SERVER_CRT, "rb") as f:
                crt = f.read()
            with open(Settings.SSL_CA_CRT, "rb") as f:
                ca = f.read()
            creds = grpc.ssl_server_credentials(
                [(key, crt)], root_certificates=ca, require_client_auth=True
            )
            port = self._server.add_secure_port(self._bind_target, creds)
        else:
            port = self._server.add_insecure_port(self._bind_target)
        if port == 0:
            raise CommunicationError(f"could not bind gRPC server at {self._bind_target}")
        self._server.start()

    def _server_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None

    # --- client -------------------------------------------------------------

    def _transport_send(self, nei: str, env: Envelope) -> None:
        conn = self.neighbors.get(nei)
        if conn is None:
            # Non-direct neighbor: open a transient connection (reference
            # create_connection path, grpc_client.py:140-160).
            conn = _GrpcConnection(nei, self.addr)
            try:
                ack = conn.send(_env_to_pb(env), timeout=Settings.GRPC_TIMEOUT)
            finally:
                conn.close()
        else:
            ack = conn.send(_env_to_pb(env), timeout=Settings.GRPC_TIMEOUT)
        if ack.error:
            raise CommunicationError(f"{nei} rejected {env.cmd}: {ack.error}")
