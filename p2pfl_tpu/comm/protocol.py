"""CommunicationProtocol: the transport-agnostic composition root.

Parity with the reference's CommunicationProtocol ABC
(communication/protocols/communication_protocol.py:27-198) and the per-
transport composition roots (grpc_communication_protocol.py:50-263,
memory_communication_protocol.py:33-66). Design departure: the reference
duplicates the Neighbors+Client+Gossiper+Server+Heartbeater wiring in each
transport; here the base class owns the composition and transports supply
three factories (server, client-send, neighbors), so both transports share
one tested code path.
"""

from __future__ import annotations

import functools
import logging
import random
import threading
import time
from typing import Any, Callable, List, Optional

from p2pfl_tpu.chaos import CHAOS
from p2pfl_tpu.comm.commands.command import Command, CommandDispatcher
from p2pfl_tpu.comm.envelope import Envelope
from p2pfl_tpu.comm.gossiper import Gossiper
from p2pfl_tpu.comm.heartbeater import HEARTBEAT_CMD, Heartbeater
from p2pfl_tpu.comm.neighbors import Neighbors
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import (
    CommunicationError,
    NeighborNotConnectedError,
    ProtocolNotStartedError,
)
from p2pfl_tpu.telemetry import REGISTRY, TRACER
from p2pfl_tpu.telemetry import bundle as bundle_mod
from p2pfl_tpu.telemetry import digest as digest_mod
from p2pfl_tpu.telemetry.flight_recorder import FlightRecorder
from p2pfl_tpu.telemetry.observatory import Observatory

log = logging.getLogger("p2pfl_tpu")

# Inbound wire accounting (the TX mirror lives in comm/gossiper.py).
_RX_BYTES = REGISTRY.counter(
    "p2pfl_gossip_rx_bytes_total",
    "Model-plane payload bytes received, by command",
    labels=("node", "cmd"),
)
_RX_FRAMES = REGISTRY.counter(
    "p2pfl_gossip_rx_frames_total",
    "Inbound envelopes dispatched (control + weights), by command",
    labels=("node", "cmd"),
)
_SEND_RETRIES = REGISTRY.counter(
    "p2pfl_send_retries_total",
    "Transport send attempts retried after a failure (bounded backoff)",
    labels=("node",),
)
_PEER_WRITTEN_OFF = REGISTRY.counter(
    "p2pfl_peer_written_off_total",
    "Neighbors removed after a send failed all its retry attempts",
    labels=("node",),
)
_HEALS = REGISTRY.counter(
    "p2pfl_recovery_heals_total",
    "Failure-departed peers observed coming back (heal/recover detections)",
    labels=("node",),
)
_DIGEST_BYTES = REGISTRY.counter(
    "p2pfl_digest_bytes_total",
    "Health-digest payload bytes emitted onto heartbeats (per beat) — the "
    "observability plane's wire cost, which must stay flat-to-logarithmic "
    "as the fleet grows (sketches, not per-peer scalars)",
    labels=("node",),
)


def jittered_backoff(src: str, dst: str, attempt: int) -> float:
    """Seeded-jitter retry backoff for gossip sends.

    Pure exponential backoff synchronizes retries: after a partition heals,
    every survivor that was mid-retry against the returned peer fires again
    in lockstep (same base, same attempt index), re-colliding forever. The
    fix is the classic decorrelation jitter — scale the exponential base by
    a uniform in [0.5, 1.5) — but drawn from a DEDICATED stream seeded by
    ``(CHAOS_SEED, src, dst, attempt)``, so replays stay deterministic and
    the chaos plane's per-pair decision streams are never consumed."""
    base = min(Settings.GOSSIP_SEND_BACKOFF * (2 ** max(0, int(attempt))), 2.0)
    if base <= 0.0:
        return 0.0
    u = random.Random(
        f"{Settings.CHAOS_SEED}|backoff|{src}->{dst}|{attempt}"
    ).random()
    return base * (0.5 + u)


def running(fn: Callable) -> Callable:
    """Guard decorator: raise unless the protocol has been started
    (reference grpc_communication_protocol.py:38-47)."""

    @functools.wraps(fn)
    def wrapper(self: "CommunicationProtocol", *args: Any, **kwargs: Any) -> Any:
        if not self._running:
            raise ProtocolNotStartedError(f"{fn.__name__} requires a started protocol")
        return fn(self, *args, **kwargs)

    return wrapper


class CommunicationProtocol:
    """Base protocol: membership + gossip + command dispatch.

    Subclasses implement :meth:`_build_neighbors`, :meth:`_server_start`,
    :meth:`_server_stop`, and :meth:`_transport_send`.
    """

    def __init__(self, addr: Optional[str] = None) -> None:
        self._addr = addr or self._default_addr()
        self._running = False
        self._lock = threading.Lock()
        self.dispatcher = CommandDispatcher()
        # Federation observatory + flight recorder (telemetry/): the
        # observatory assembles peers' heartbeat-piggybacked health digests
        # into a fleet view; the recorder keeps the postmortem event ring.
        self.flight_recorder = FlightRecorder(self._addr)
        # The observatory records membership transitions (join/rejoin/leave)
        # into the flight recorder — churn is postmortem-worthy.
        self.observatory = Observatory(self._addr, recorder=self.flight_recorder)
        # Digest source: returns this node's HealthDigest for the next beat.
        # The default sees only the registry; Node swaps in a state-aware
        # provider (round/stage); None disables emission entirely (the node
        # stays wire-compatible — its beats are simply digest-free).
        self._digest_provider: Optional[Callable[[], Optional[digest_mod.HealthDigest]]] = (
            lambda: digest_mod.collect(self._addr)
        )
        self.neighbors = self._build_neighbors(self._addr)
        self.gossiper = Gossiper(
            self._addr,
            send_fn=self._safe_send,
            get_direct_neighbors_fn=lambda: self.neighbors.get_all(only_direct=True),
            recorder=self.flight_recorder,
        )
        self.heartbeater = Heartbeater(
            self._addr,
            self.neighbors,
            self.broadcast,
            digest_fn=self._digest_wire,
            probe_fn=self._probe_departed,
        )
        # Dead peers leave the fleet view and the postmortem record together.
        self.neighbors.add_removal_listener(self._observe_peer_removed)
        # Healed peers re-enter it with fresh scoring state (a returned
        # partition survivor must not inherit its pre-partition z-scores).
        self.neighbors.add_recovery_listener(self._observe_peer_recovered)
        # auto-register the heartbeat handler (reference
        # grpc_communication_protocol.py:63-89)
        protocol = self

        class _BeatCommand(Command):
            @staticmethod
            def get_name() -> str:
                return HEARTBEAT_CMD

            def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
                ts = float(args[0]) if args else 0.0
                protocol.heartbeater.beat(source, ts)

        self.dispatcher.register([_BeatCommand()])

    # --- observatory / flight recorder --------------------------------------

    def set_digest_source(
        self, provider: Optional[Callable[[], Optional[digest_mod.HealthDigest]]]
    ) -> None:
        """Install the health-digest provider piggybacked on heartbeats
        (``None`` disables emission — the node keeps interoperating, its
        beats are just digest-free)."""
        self._digest_provider = provider

    def _digest_wire(self) -> Optional[str]:
        """Encoded digest for the next beat (None = skip). The self view
        rides the same ingest path as peers' digests, so the local fleet
        snapshot always includes this node."""
        provider = self._digest_provider
        if provider is None:
            return None
        dig = provider()
        if dig is None:
            return None
        self.observatory.ingest(dig)
        wire = dig.encode()
        _DIGEST_BYTES.labels(self._addr).inc(len(wire))
        return wire

    def _ingest_digest(self, env: Envelope) -> None:
        dig = digest_mod.decode(env.digest)
        if dig is None:
            log.debug("(%s) undecodable digest from %s ignored", self._addr, env.source)
            return
        if dig.node != env.source:
            # A digest must describe its sender; a mismatch is either a bug
            # or spoofed attribution — drop it (beats stay valid either way).
            log.debug(
                "(%s) digest node %s != envelope source %s — ignored",
                self._addr, dig.node, env.source,
            )
            return
        if self.observatory.ingest(dig):
            self.flight_recorder.record(
                "digest", peer=dig.node, round=dig.round, stage=dig.stage
            )

    def _observe_peer_removed(self, addr: str) -> None:
        self.observatory.forget(addr)
        self.flight_recorder.record("peer_lost", peer=addr)

    def _observe_peer_recovered(self, addr: str) -> None:
        """A failure-departed peer demonstrably returned: the heal event.
        The observatory resets its scoring state (stale pre-partition
        straggler/link stats must not outlive the partition) and the return
        is postmortem-worthy."""
        self.observatory.peer_recovered(addr)
        self.flight_recorder.record("peer_recovered", peer=addr)
        _HEALS.labels(self._addr).inc()

    def on_neighbor_recovered(self, fn: Callable[[str], None]) -> None:
        """Register a heal callback: fired (with the address) whenever a
        peer that was written off via a failure path comes back — the hook
        partition-heal reconciliation hangs off (node-level reconcile pings,
        stages re-evaluating quorum)."""
        self.neighbors.add_recovery_listener(fn)

    def _probe_departed(self) -> None:
        """Heal detection (runs on the heartbeater's sweep tick): attempt to
        re-reach peers that left the table via failure paths. Beats alone
        cannot re-discover a healed partition — the first blocked send
        already dropped the only link that would carry them — so the
        detector must actively knock.

        The probe is a handshake-connect: it respects chaos partitions and
        crashes via the STATE-ONLY :meth:`ChaosPlane.link_blocked` check
        (drawing from the per-pair decision streams here would make their
        replay depend on probe cadence), touches neither side's neighbor
        table unless the connect round-trips, and fires the recovery
        listeners only on success."""
        if not self._running or not Settings.RECOVERY_PROBE_ENABLED:
            return
        for addr in self.neighbors.departed(Settings.RECOVERY_PROBE_MAX):
            if not self._running:
                return
            if CHAOS.active and CHAOS.link_blocked(self._addr, addr):
                continue  # still partitioned/crashed: don't pierce it
            try:
                # connect_to performs the transport handshake; failure (peer
                # still down) leaves both tables untouched, success re-adds
                # the peer and _note_returned fires the recovery listeners.
                self.neighbors.add(addr, non_direct=False)
            except Exception:  # noqa: BLE001 — still dead; keep probing
                log.debug("(%s) heal probe to %s failed", self._addr, addr)

    def export_trace(self, path: str) -> str:
        """Write this PROCESS's span buffer as an annotated Chrome trace.

        On top of ``TRACER.export_chrome_trace()`` (which already carries
        the wall-clock epoch anchor), the dump's ``metadata`` records this
        node's address and its per-peer clock-skew snapshot from the
        heartbeater — everything
        :meth:`p2pfl_tpu.telemetry.critical_path.CriticalPathAnalyzer.
        from_chrome_traces` needs to merge dumps from separate gRPC
        processes onto one skew-corrected timeline. Atomic write (tmp +
        rename) so a crash mid-dump never leaves a torn trace.
        """
        import json
        import os

        doc = TRACER.export_chrome_trace()
        meta = doc.setdefault("metadata", {})
        meta["node"] = self._addr
        meta["peer_clock_skew_s"] = self.heartbeater.clock_skews()
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        # pid alone collides when two node threads write the same doc path
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    # --- transport hooks ----------------------------------------------------

    def _default_addr(self) -> str:
        raise NotImplementedError

    def _build_neighbors(self, addr: str) -> Neighbors:
        raise NotImplementedError

    def _server_start(self) -> None:
        raise NotImplementedError

    def _server_stop(self) -> None:
        raise NotImplementedError

    def _transport_send(self, nei: str, env: Envelope) -> None:
        """Deliver one envelope to a connected neighbor (may raise)."""
        raise NotImplementedError

    # --- lifecycle (reference communication_protocol.py:56-77) --------------

    @property
    def addr(self) -> str:
        return self._addr

    def get_address(self) -> str:
        return self._addr

    def start(self) -> None:
        if self._running:
            return
        self._server_start()
        # _running must be set before the heartbeater launches: its thread
        # broadcasts immediately and would hit the @running guard, delaying
        # first-beat membership discovery by a full HEARTBEAT_PERIOD.
        self._running = True
        self.heartbeater.start()
        self.gossiper.start()

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        self.heartbeater.stop()
        self.gossiper.stop()
        self.neighbors.clear()
        self._server_stop()

    def crash(self) -> None:
        """Abrupt-death simulation: tear everything down WITHOUT disconnect
        notifications, as a killed process would. Peers must discover the
        death through heartbeat timeouts / send failures — which is exactly
        what chaos tests exercise."""
        if not self._running:
            return
        self._running = False
        # Postmortem FIRST, while the ring still holds the final moments —
        # the teardown below emits nothing worth recording.
        self.flight_recorder.record("crash")
        self.flight_recorder.dump("crash")
        self.heartbeater.stop()
        self.gossiper.stop()
        self.neighbors.clear(notify=False)
        self._server_stop()

    # --- membership ---------------------------------------------------------

    @running
    def connect(self, addr: str, non_direct: bool = False) -> bool:
        try:
            return self.neighbors.add(addr, non_direct=non_direct)
        except Exception as exc:
            raise CommunicationError(f"could not connect to {addr}: {exc}") from exc

    @running
    def disconnect(self, addr: str, notify: bool = True) -> None:
        # Explicit local disconnect: graceful, never a failure departure.
        self.neighbors.remove(addr, notify=notify, departed=False)

    @running
    def get_neighbors(self, only_direct: bool = False) -> List[str]:
        return self.neighbors.get_all(only_direct=only_direct)

    def on_neighbor_removed(self, fn: Callable[[str], None]) -> None:
        """Register a death callback: fired (with the address) whenever a
        neighbor leaves the table — heartbeat-timeout sweeps, send-failure
        write-offs and explicit disconnects all converge here, so round
        machinery (vote expectations, aggregation finish conditions) can
        shrink immediately instead of sleeping out its fixed timeout."""
        self.neighbors.add_removal_listener(fn)

    # --- messaging (reference communication_protocol.py:95-160) -------------

    def build_msg(self, cmd: str, args: Optional[List[str]] = None, round: int = 0) -> Envelope:
        return Envelope.message(self._addr, cmd, args=args, round=round)

    def build_weights(
        self,
        cmd: str,
        round: int,
        serialized_model: bytes,
        contributors: Optional[List[str]] = None,
        num_samples: int = 1,
        codec: str = "dense",
    ) -> Envelope:
        return Envelope.weights(
            self._addr, cmd, round, serialized_model, list(contributors or []),
            num_samples, codec=codec,
        )

    @running
    def send(
        self,
        nei: str,
        env: Envelope,
        create_connection: bool = False,
        raise_error: bool = True,
        remove_on_error: bool = True,
        retries: int = 0,
    ) -> None:
        """Unicast with the reference's failure semantics
        (grpc_client.py:124-192), hardened two ways:

        * **chaos intercept** — when the fault plane is active, each attempt
          consults :data:`~p2pfl_tpu.chaos.CHAOS` first: injected drops
          return silently (the sender believes it delivered), delays stall
          this thread, duplicates double-deliver, and blocked links
          (partition / crash) raise into the normal failure path below.
        * **bounded retry** — a failed attempt is retried up to ``retries``
          times with exponential backoff before the neighbor is written off
          and removed (firing the death callbacks registered via
          :meth:`on_neighbor_removed`). The gossip path passes
          ``Settings.GOSSIP_SEND_RETRIES``; heartbeats stay at 0 (they ARE
          the retry loop).
        """
        if not self.neighbors.exists(nei):
            if create_connection:
                self.neighbors.add(nei, non_direct=False)
            elif raise_error:
                raise NeighborNotConnectedError(f"{nei} is not a neighbor")
            else:
                return
        attempts = 1 + max(0, int(retries))
        if CHAOS.active and env.is_weights:
            # Byzantine peer behavior (chaos plane): a node marked adversarial
            # poisons every model-plane frame it sends — corrupted ONCE per
            # send call, before the retry loop, so retries re-ship the same
            # (corrupted) frame like a real adversary would.
            env = CHAOS.corrupt_weights(self._addr, env)
        for attempt in range(attempts):
            try:
                if CHAOS.active:
                    decision = CHAOS.intercept(self._addr, nei)
                    if decision.blocked:
                        self.flight_recorder.record(
                            "fault", fault=decision.blocked, peer=nei, cmd=env.cmd
                        )
                        raise CommunicationError(
                            f"chaos: link {self._addr} -> {nei} blocked "
                            f"({decision.blocked})"
                        )
                    if decision.drop:
                        self.flight_recorder.record(
                            "fault", fault="drop", peer=nei, cmd=env.cmd
                        )
                        return  # injected loss: the sender never learns
                    if decision.delay_s > 0.0:
                        time.sleep(decision.delay_s)
                    for _ in range(decision.duplicates):
                        self._transport_send(nei, env)
                self._transport_send(nei, env)
                return
            except (TypeError, AttributeError):
                # Local programming error (e.g. bad payload type), not a peer
                # failure: keep the neighbor and surface it loudly instead of
                # masking it as a CommunicationError. Never retried.
                # (ValueError stays on the transport path: grpc raises it for
                # closed-channel races.)
                log.exception("send to %s failed with a local error", nei)
                if raise_error:
                    raise
                return
            except Exception as exc:
                if attempt + 1 < attempts:
                    _SEND_RETRIES.labels(self._addr).inc()
                    time.sleep(jittered_backoff(self._addr, nei, attempt))
                    continue
                if remove_on_error:
                    _PEER_WRITTEN_OFF.labels(self._addr).inc()
                    self.flight_recorder.record(
                        "peer_written_off", peer=nei, cmd=env.cmd, error=str(exc)[:200]
                    )
                    if attempts > 1:
                        log.warning(
                            "(%s) writing off %s after %d failed send attempts: %s",
                            self._addr, nei, attempts, exc,
                        )
                    self.neighbors.remove(nei, notify=False)
                if raise_error:
                    raise CommunicationError(f"send to {nei} failed: {exc}") from exc
                return

    def _safe_send(self, nei: str, env: Envelope) -> None:
        if not self._running:
            return
        self.send(
            nei,
            env,
            raise_error=False,
            remove_on_error=True,
            retries=Settings.GOSSIP_SEND_RETRIES,
        )

    @running
    def broadcast(self, env: Envelope, node_list: Optional[List[str]] = None) -> None:
        """Send to every direct neighbor (reference grpc_client.py:194-208)."""
        for nei in node_list if node_list is not None else self.neighbors.get_all(only_direct=True):
            self.send(nei, env, raise_error=False, remove_on_error=True)
            if env.payload is not None:
                # Model-plane accounting for broadcast weights (async window
                # contributions): the sync model gossip counts at its own
                # send point in gossip_weights — this is the only other
                # weights choke point, so bytes_for_round and the per-codec
                # TX attribution cover both schedulers.
                self.gossiper._record_tx(env, nei)

    # --- command wiring -----------------------------------------------------

    def add_command(self, cmds: Command | List[Command]) -> None:
        self.dispatcher.register(cmds if isinstance(cmds, list) else [cmds])

    # --- inbound (called by transport servers) ------------------------------

    def _dispatch_contained(self, env: Envelope, **kwargs: Any) -> None:
        """Dispatch with APPLICATION errors contained at the receiving node.

        An unknown command (version-skewed peer) or a handler exception must
        never surface as a transport failure: the gRPC server would return
        an error Ack, the SENDER's broadcast path would treat that as a dead
        link and remove the neighbor — one stray command dismantling
        connectivity. Transport-level problems (undecodable frames) still
        propagate from the server adapters.
        """
        args = () if env.is_weights else tuple(env.args)  # weights ride kwargs only
        try:
            self.dispatcher.dispatch(env.cmd, env.source, env.round, *args, **kwargs)
        except Exception:  # noqa: BLE001 — any app error is the receiver's own
            log.exception(
                "(%s) contained error dispatching %r from %s",
                self._addr, env.cmd, env.source,
            )

    def handle_envelope(self, env: Envelope) -> None:
        """Inbound dispatch with dedup + TTL re-gossip
        (reference grpc_server.py:161-212).

        Traced frames (``env.trace`` set) dispatch inside a receiver span
        parented onto the SENDER's span, so cross-node latency — model
        diffusion, vote RTT — is attributable in the exported trace.
        """
        _RX_FRAMES.labels(self._addr, env.cmd).inc()
        if env.is_weights:
            _RX_BYTES.labels(self._addr, env.cmd).inc(len(env.payload))
            self.flight_recorder.record(
                "recv", cmd=env.cmd, peer=env.source,
                round=env.round, bytes=len(env.payload),
            )
            with TRACER.recv_span(
                f"recv:{env.cmd}", self._addr, env.trace,
                source=env.source, round=env.round, bytes=len(env.payload),
            ):
                self._dispatch_contained(
                    env,
                    weights=env.payload,
                    contributors=env.contributors,
                    num_samples=env.num_samples,
                )
            return
        if not self.gossiper.check_and_set_processed(env.msg_id):
            return
        # Run-id adoption (AFTER dedup, like digests): first-wins for
        # ordinary frames — a stale peer's heartbeat must not flip an
        # established context — but a start_learning kickoff forces it, so
        # every node converges on the initiator's experiment id before any
        # model traffic flows.
        if env.run_id:
            bundle_mod.adopt_run_id(env.run_id, force=env.cmd == "start_learning")
        # Piggybacked health digest (normally on beats): feed the fleet view
        # AFTER dedup so re-gossiped copies don't re-ingest. Absent digests
        # (older / opted-out peers) skip this entirely — wire compatibility.
        if env.digest:
            self._ingest_digest(env)
        with TRACER.recv_span(
            f"recv:{env.cmd}", self._addr, env.trace,
            source=env.source, round=env.round,
        ):
            self._dispatch_contained(env)
        if env.ttl > 1:
            fwd = Envelope(
                source=env.source,
                cmd=env.cmd,
                round=env.round,
                args=env.args,
                ttl=env.ttl - 1,
                msg_id=env.msg_id,
                trace=env.trace,  # re-gossip stays in the sender's trace
                digest=env.digest,  # digests reach non-direct peers this way
                run_id=env.run_id,  # run id diffuses past direct neighbors
            )
            self.gossiper.add_message(fwd)

    # --- model gossip (reference communication_protocol.py:162-198) ---------

    @running
    def gossip_weights(
        self,
        early_stopping_fn: Callable[[], bool],
        get_candidates_fn: Callable[[], List[str]],
        status_fn: Callable[[], Any],
        model_fn: Callable[[str], Optional[Envelope]],
        period: Optional[float] = None,
        create_connection: bool = False,
    ) -> None:
        self.gossiper.gossip_weights(
            early_stopping_fn, get_candidates_fn, status_fn, model_fn, period
        )
