"""Thread-safe neighbor table.

Parity with reference communication/protocols/neighbors.py:27-167: direct
neighbors (we hold a live connection) vs non-direct neighbors (learned about
via heartbeat gossip); refresh-or-add keeps last-seen timestamps for the
failure detector.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger("p2pfl_tpu")

#: Bound on the remembered failure-departed set (heal-detection probe pool).
_DEPARTED_CAP = 256


class Neighbors:
    """addr -> (connection, direct, last_seen). Transports subclass to build
    real connections in :meth:`connect_to`."""

    def __init__(self, self_addr: str) -> None:
        self.self_addr = self_addr
        self._lock = threading.RLock()
        self._neighbors: Dict[str, Tuple[Any, bool, float]] = {}
        # Fired (with the removed address) AFTER an entry actually leaves the
        # table — the death-propagation hook: heartbeat sweeps and send-
        # failure write-offs both land here, so one callback covers every way
        # a peer can die mid-round. Listeners run on the removing thread
        # (heartbeater/transport) outside the table lock and must be cheap.
        self._removal_listeners: List[Callable[[str], None]] = []
        # Durable recovery plane: addresses that left the table via FAILURE
        # paths (heartbeat timeout, send write-off, peer crash) — the
        # heal-detection probe pool. A graceful disconnect is NOT a
        # departure: the peer said goodbye and owes no heal. Bounded FIFO.
        self._departed: "OrderedDict[str, float]" = OrderedDict()
        # Fired when a departed peer comes BACK (a probe round-tripped, a
        # handshake re-arrived, or a heartbeat resumed): the heal hook —
        # observatory recover events and reconcile pings hang off this.
        self._recovery_listeners: List[Callable[[str], None]] = []

    # --- transport hooks ----------------------------------------------------

    def connect_to(self, addr: str, *, handshake: bool) -> Any:
        """Build a transport connection object. Default: no connection state.
        Raising here aborts :meth:`add`."""
        return None

    def disconnect_from(self, addr: str, conn: Any, *, notify: bool) -> None:
        """Tear down a transport connection object."""

    # --- table --------------------------------------------------------------

    def add(self, addr: str, *, non_direct: bool = False, handshake: bool = True) -> bool:
        if addr == self.self_addr:
            return False
        with self._lock:
            existing = self._neighbors.get(addr)
            if existing is not None:
                conn, direct, _ = existing
                if direct or non_direct:
                    # Already at least as connected as requested: refresh.
                    self._neighbors[addr] = (conn, direct, time.time())
                    self._note_returned(addr)
                    return True
        # Build the connection outside the lock (may do network IO).
        conn = None
        if not non_direct:
            conn = self.connect_to(addr, handshake=handshake)
        with self._lock:
            self._neighbors[addr] = (conn, not non_direct, time.time())
        # A peer we wrote off as dead is demonstrably back (the connect /
        # handshake / heartbeat that re-added it succeeded): heal.
        self._note_returned(addr)
        return True

    def _note_returned(self, addr: str) -> None:
        """Fire the recovery listeners iff ``addr`` was failure-departed.
        Listeners run outside the table lock on the re-adding thread."""
        with self._lock:
            if self._departed.pop(addr, None) is None:
                return
        log.warning(
            "(%s) peer %s reappeared after being written off — heal",
            self.self_addr, addr,
        )
        for fn in list(self._recovery_listeners):
            try:
                fn(addr)
            except Exception:  # a listener bug must not break membership
                log.exception("neighbor-recovery listener failed for %s", addr)

    def refresh_or_add(self, addr: str) -> None:
        """Heartbeat path (reference heartbeater.py:66-80): update last_seen,
        or learn a new non-direct neighbor."""
        with self._lock:
            existing = self._neighbors.get(addr)
            if existing is not None:
                conn, direct, _ = existing
                self._neighbors[addr] = (conn, direct, time.time())
                return
        self.add(addr, non_direct=True)

    def add_removal_listener(self, fn: Callable[[str], None]) -> None:
        self._removal_listeners.append(fn)

    def add_recovery_listener(self, fn: Callable[[str], None]) -> None:
        """Heal hook: fired (with the address) when a failure-departed peer
        demonstrably returns — a probe round-tripped, its handshake
        re-arrived, or its heartbeats resumed."""
        self._recovery_listeners.append(fn)

    def departed(self, limit: Optional[int] = None) -> List[str]:
        """Oldest-first addresses that left via failure paths (the heal
        probe pool)."""
        with self._lock:
            out = list(self._departed)
        return out[: limit] if limit is not None else out

    def remove(
        self, addr: str, *, notify: bool = False, departed: Optional[bool] = None
    ) -> None:
        """Drop ``addr``. ``departed`` marks the removal as a FAILURE
        (peer presumed dead/unreachable → eligible for heal probing);
        default: infer from ``notify`` — a notified disconnect is graceful,
        an unnotified one is a write-off."""
        with self._lock:
            entry = self._neighbors.pop(addr, None)
            if entry is not None and (departed if departed is not None else not notify):
                self._departed[addr] = time.monotonic()
                self._departed.move_to_end(addr)
                while len(self._departed) > _DEPARTED_CAP:
                    self._departed.popitem(last=False)
        if entry is None:
            return
        if entry[0] is not None:
            try:
                self.disconnect_from(addr, entry[0], notify=notify)
            except Exception:
                pass
        for fn in list(self._removal_listeners):
            try:
                fn(addr)
            except Exception:  # a listener bug must not break membership
                log.exception("neighbor-removal listener failed for %s", addr)

    def exists(self, addr: str, *, only_direct: bool = False) -> bool:
        with self._lock:
            e = self._neighbors.get(addr)
            return e is not None and (e[1] or not only_direct)

    def get(self, addr: str) -> Optional[Any]:
        with self._lock:
            e = self._neighbors.get(addr)
            return e[0] if e else None

    def get_all(self, only_direct: bool = False) -> List[str]:
        with self._lock:
            return [a for a, (_, direct, _) in self._neighbors.items() if direct or not only_direct]

    def last_seen(self) -> Dict[str, float]:
        with self._lock:
            return {a: t for a, (_, _, t) in self._neighbors.items()}

    def clear(self, *, notify: bool = True) -> None:
        """Drop every neighbor; ``notify=False`` models an abrupt crash (no
        disconnect RPCs — peers must discover the death via heartbeats).
        Teardown is never a peer departure: this table is dying, not them."""
        for addr in self.get_all():
            self.remove(addr, notify=notify, departed=False)
