"""Gossip engine: async message diffusion + synchronous model gossip.

Parity with reference communication/protocols/gossiper.py:31-239:

* **async path** — pending (envelope, targets) pairs drained every
  ``GOSSIP_PERIOD``, at most ``GOSSIP_MESSAGES_PER_PERIOD`` per tick
  (:124-155 in the reference), with a bounded dedup ring of recently-seen
  message ids (:101-122),
* **sync path** — ``gossip_weights``: a paced loop that asks for candidate
  peers, exits when candidates are empty or progress stalls for
  ``GOSSIP_EXIT_ON_X_EQUAL_ROUNDS`` consecutive rounds, and sends
  ``GOSSIP_MODELS_PER_ROUND`` models per tick (:163-239).
"""

from __future__ import annotations

import logging
import random
import threading
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from p2pfl_tpu.comm.envelope import Envelope
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import ProtocolNotStartedError
from p2pfl_tpu.telemetry import REGISTRY

log = logging.getLogger("p2pfl_tpu")

# Model-plane TX accounting, exposed through the telemetry registry (the
# Prometheus/JSON exposition surface every subsystem shares). The gossiper
# ALSO keeps a per-instance (cmd, round) table: per-round queries
# (``bytes_for_round``, read by RoundFinishedStage and bench --wire) must be
# scoped to THIS gossiper's lifetime, and registry series — keyed by node
# label — would bleed across tests that reuse an address.
_TX_BYTES = REGISTRY.counter(
    "p2pfl_gossip_tx_bytes_total",
    "Model-plane payload bytes sent, by command, round and wire codec "
    "(topk / topk-int8 / topk-int4 / dense)",
    labels=("node", "cmd", "round", "codec"),
)
_TX_FRAMES = REGISTRY.counter(
    "p2pfl_gossip_tx_frames_total",
    "Model-plane frames sent, by command, round and wire codec",
    labels=("node", "cmd", "round", "codec"),
)
_MSGS_SENT = REGISTRY.counter(
    "p2pfl_gossip_msgs_sent_total",
    "Control-plane messages fanned out by the async gossip thread",
    labels=("node",),
)
_QUEUE_DEPTH = REGISTRY.gauge(
    "p2pfl_gossip_queue_depth",
    "Pending (envelope, targets) pairs awaiting the next gossip tick",
    labels=("node",),
)
_ABANDONED = REGISTRY.counter(
    "p2pfl_gossip_abandoned_total",
    "Model gossip loops that gave up with candidates still unreached "
    "(GOSSIP_EXIT_ON_X_EQUAL_ROUNDS stall trips)",
    labels=("node",),
)


class Gossiper:
    """Owns the async gossip thread; the sync weights gossip runs on the
    caller's thread (stage machine)."""

    def __init__(
        self,
        self_addr: str,
        send_fn: Callable[[str, Envelope], None],
        get_direct_neighbors_fn: Callable[[], List[str]],
        recorder: Optional[Any] = None,
    ) -> None:
        self._self_addr = self_addr
        self._send = send_fn
        self._get_direct = get_direct_neighbors_fn
        # Optional flight recorder (comm/protocol.py wires its own): model-
        # plane sends and gossip give-ups become postmortem events.
        self._recorder = recorder
        self._pending: deque[Tuple[Envelope, List[str]]] = deque()
        self._pending_lock = threading.Lock()
        self._processed: "OrderedDict[int, None]" = OrderedDict()
        self._processed_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Model-plane TX accounting: (cmd, round, codec) -> [frames, bytes].
        # The sparse delta wire path's bytes-per-round metric reads this
        # (surfaced per round by RoundFinishedStage and by bench.py --wire);
        # the registry mirror (module-level counters above) is the process-
        # wide exposition surface.
        self._tx_lock = threading.Lock()
        self._tx: Dict[Tuple[str, int, str], List[int]] = {}
        self._msgs_sent = _MSGS_SENT.labels(self_addr)
        self._queue_depth = _QUEUE_DEPTH.labels(self_addr)

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"gossiper-{self._self_addr}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # --- wire accounting ----------------------------------------------------

    def _record_tx(self, env: Envelope, nei: str = "") -> None:
        if env.payload is None:
            return
        codec = getattr(env, "codec", "") or "dense"
        with self._tx_lock:
            row = self._tx.setdefault((env.cmd, env.round, codec), [0, 0])
            row[0] += 1
            row[1] += len(env.payload)
        _TX_FRAMES.labels(self._self_addr, env.cmd, env.round, codec).inc()
        _TX_BYTES.labels(self._self_addr, env.cmd, env.round, codec).inc(
            len(env.payload)
        )
        if self._recorder is not None:
            self._recorder.record(
                "send", cmd=env.cmd, peer=nei,
                round=env.round, bytes=len(env.payload), codec=codec,
            )

    def wire_stats(self) -> Dict[Tuple[str, int, str], Tuple[int, int]]:
        """Copy of the model-plane TX table:
        (cmd, round, codec) -> (frames, bytes)."""
        with self._tx_lock:
            return {k: (v[0], v[1]) for k, v in self._tx.items()}

    def bytes_for_round(self, round: int) -> int:
        """Total model-plane payload bytes sent for ``round``."""
        with self._tx_lock:
            return sum(v[1] for (_, r, _c), v in self._tx.items() if r == round)

    def bytes_by_codec(self) -> Dict[str, int]:
        """Model-plane payload bytes per wire codec — the per-encoder
        attribution ``bench.py --wire`` and ``fed_top`` surface."""
        with self._tx_lock:
            out: Dict[str, int] = {}
            for (_, _, codec), v in self._tx.items():
                out[codec] = out.get(codec, 0) + v[1]
            return out

    def total_tx_bytes(self) -> int:
        with self._tx_lock:
            return sum(v[1] for v in self._tx.values())

    # --- dedup (reference gossiper.py:101-122) ------------------------------

    def check_and_set_processed(self, msg_id: int) -> bool:
        """True if unseen (and records it); False if duplicate."""
        if msg_id == 0:
            return True
        with self._processed_lock:
            if msg_id in self._processed:
                return False
            self._processed[msg_id] = None
            while len(self._processed) > Settings.AMOUNT_LAST_MESSAGES_SAVED:
                self._processed.popitem(last=False)
            return True

    # --- async message gossip ----------------------------------------------

    def add_message(self, env: Envelope, targets: Optional[List[str]] = None) -> None:
        """Queue a message for diffusion to ``targets`` (default: direct
        neighbors except the message source)."""
        if targets is None:
            targets = [n for n in self._get_direct() if n != env.source]
        if not targets:
            return
        with self._pending_lock:
            self._pending.append((env, targets))
            self._queue_depth.set(len(self._pending))

    def _run(self) -> None:
        while not self._stop.wait(Settings.GOSSIP_PERIOD):
            budget = Settings.GOSSIP_MESSAGES_PER_PERIOD
            while budget > 0:
                with self._pending_lock:
                    if not self._pending:
                        break
                    env, targets = self._pending.popleft()
                    self._queue_depth.set(len(self._pending))
                for t in targets:
                    try:
                        self._send(t, env)
                    except ProtocolNotStartedError:
                        return  # protocol stopping under us — normal shutdown
                    except Exception:
                        # transport failures are already swallowed and logged
                        # by protocol.send (raise_error=False); this guard
                        # only keeps the gossip thread alive on local bugs
                        log.exception("gossip send to %s failed unexpectedly", t)
                self._msgs_sent.inc(len(targets) or 1)
                budget -= len(targets) or 1

    # --- sync model gossip (reference gossiper.py:163-239) ------------------

    def gossip_weights(
        self,
        early_stopping_fn: Callable[[], bool],
        get_candidates_fn: Callable[[], List[str]],
        status_fn: Callable[[], Any],
        model_fn: Callable[[str], Optional[Envelope]],
        period: Optional[float] = None,
        max_rounds: Optional[int] = None,
    ) -> None:
        """Paced diffusion of model weights until convergence.

        Each tick: stop if ``early_stopping_fn`` or no candidates; stop if
        ``status_fn()`` hasn't changed for ``GOSSIP_EXIT_ON_X_EQUAL_ROUNDS``
        ticks; otherwise sample ``GOSSIP_MODELS_PER_ROUND`` candidates and
        send each ``model_fn(candidate)``.
        """
        period = Settings.GOSSIP_MODELS_PERIOD if period is None else period
        equal_rounds = 0
        last_status: Any = None
        ticker = threading.Event()
        rounds = 0
        while True:
            if early_stopping_fn():
                return
            if max_rounds is not None and rounds >= max_rounds:
                return
            rounds += 1
            candidates = get_candidates_fn()
            if not candidates:
                return
            status = status_fn()
            if status == last_status:
                equal_rounds += 1
                if equal_rounds >= Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS:
                    # NOT the normal exit (that is candidates == []): progress
                    # stalled with peers still unreached — e.g. a dead peer
                    # that never confirms. Previously silent; a vanished model
                    # transfer was undiagnosable.
                    log.warning(
                        "(%s) model gossip ABANDONED after %d stalled ticks; "
                        "unreached candidates: %s",
                        self._self_addr, equal_rounds, candidates,
                    )
                    _ABANDONED.labels(self._self_addr).inc()
                    if self._recorder is not None:
                        self._recorder.record(
                            "gossip_abandoned", candidates=list(candidates)
                        )
                    return
            else:
                equal_rounds = 0
                last_status = status
            sample = random.sample(
                candidates, min(Settings.GOSSIP_MODELS_PER_ROUND, len(candidates))
            )
            for nei in sample:
                env = model_fn(nei)
                if env is None:
                    continue
                try:
                    self._send(nei, env)
                    self._record_tx(env, nei)
                except ProtocolNotStartedError:
                    return  # protocol stopping under us — normal shutdown
                except Exception:
                    log.exception("model gossip to %s failed unexpectedly", nei)
            if ticker.wait(period):  # plain sleep, interruptible-style
                return
