"""The elastic async scheduler: windows instead of rounds, no vote barrier.

Second scheduler over the same pluggable stage machine (the ROADMAP refactor
note: sync and async are two schedulers over one stage set). Where the sync
scheduler runs StartLearning → [Vote → (Train | WaitAgg) → GossipModel →
RoundFinished] with a vote barrier and an aggregation deadline per round, the
async scheduler (Papaya, arxiv 2111.04877; FedBuff buffering) runs

    AsyncStart → [AsyncWindow → AsyncWindowFinished] * windows

per node, with NO cross-node barrier anywhere:

* every node trains at its own pace and broadcasts each contribution tagged
  with the window it trained against;
* inbound contributions fold into the node's
  :class:`~p2pfl_tpu.learning.aggregators.async_buffer.AsyncBufferedAggregator`
  as they arrive, staleness-weighted — a straggler contributes LATE (at a
  discount) instead of gating the fleet;
* a window closes on a fill target (``ASYNC_BUFFER_K`` distinct
  contributors, shrunk live by peer deaths) or ``ASYNC_WINDOW_TIMEOUT``;
* membership is elastic: nodes join mid-experiment (``async_join`` →
  welcome + dense full-model catch-up + anchor resync), leave or crash
  without stalling any window (death callbacks re-evaluate the fill target);
* participation is observatory-driven (closes PR 5's detect→act loop):
  peers whose fleet suspect score crosses ``ASYNC_SUSPECT_GATE`` are not
  solicited and their contributions are dropped; peers whose straggler score
  crosses ``ASYNC_STRAGGLER_GATE`` are deprioritized — still folded on
  arrival, but the fill target never waits on them.

Telemetry: each window runs inside the ``AsyncWindowStage`` stage span
(tagged with the window as ``round``), with ``fit`` / ``diffuse:async_model``
/ ``async_window_wait`` child spans — the PR 6 critical-path analyzer
attributes gating nodes per window exactly as it does per round. The
``p2pfl_async_*`` registry section carries window durations, contribution
freshness, staleness and drops.
"""

from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, List, Optional, Tuple, Type

from p2pfl_tpu.comm.commands.impl import AsyncContributionCommand, AsyncDoneCommand
from p2pfl_tpu.config import Settings
from p2pfl_tpu.population.cohort import wire_cohort_filter
from p2pfl_tpu.stages.base_node import TrainStage, establish_initial_model
from p2pfl_tpu.stages.stage import Stage, check_early_stop
from p2pfl_tpu.telemetry import REGISTRY, TRACER
from p2pfl_tpu.telemetry.ledger import LEDGERS, canonical_params_hash

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.node import Node

log = logging.getLogger("p2pfl_tpu")

_WINDOW_SECONDS = REGISTRY.histogram(
    "p2pfl_async_window_seconds",
    "Wall-clock per completed async window (train + diffuse + fold wait)",
    labels=("node",),
)


def select_participants(node: "Node") -> Tuple[List[str], List[str]]:
    """Observatory-gated participation for the next window.

    Returns ``(solicit, countable)``: ``solicit`` — peers our contribution
    is sent to and whose contributions we accept (suspects excluded);
    ``countable`` — the subset the window fill target may wait on
    (stragglers excluded; their late contributions still fold on arrival).
    """
    peers = node.protocol.get_neighbors(only_direct=False)
    # Population-scale cohort sampling (population/cohort.py): with a plan
    # active, this window solicits only its hash-sampled cohort — the
    # Papaya fan-in bound, applied at the async scheduler's single
    # solicitation choke point. Self is included in the candidate pool so
    # every node derives the same cohort; an empty intersection (stale
    # membership under churn) falls back to the unfiltered peer set.
    cohort = wire_cohort_filter(node.state.round or 0, list(peers) + [node.addr])
    if cohort:
        in_cohort = set(cohort)
        peers = [p for p in peers if p in in_cohort]
    obs = node.observatory
    done = node.state.async_done_peers
    try:
        scores = obs.scores()
    except Exception:  # noqa: BLE001 — scoring must never break the window
        scores = {}
    s_gate = Settings.ASYNC_SUSPECT_GATE
    g_gate = Settings.ASYNC_STRAGGLER_GATE
    solicit: List[str] = []
    countable: List[str] = []
    for p in peers:
        if p in done:
            # Finished its windows: produces nothing further — don't ship
            # to it, never wait on it.
            continue
        # suspect_score answers for digest-less peers too — an adversary
        # that never reports digests must still be gateable.
        if s_gate > 0 and obs.suspect_score(p) >= s_gate:
            continue
        solicit.append(p)
        if g_gate > 0 and scores.get(p, {}).get("straggler", 0.0) >= g_gate:
            continue
        countable.append(p)
    return solicit, countable


class AsyncStartStage(Stage):
    """Session bootstrap for the async scheduler.

    Round-0 cohort members run the same initial-model establishment as the
    sync scheduler (shared helper). A mid-experiment JOINER — recognizable
    by the welcome having fast-forwarded its window past 0 — instead waits
    for the dense ``async_catchup`` frame (which adopts the model and
    resyncs the delta anchor to the current window)."""

    name = "AsyncStartStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        state = node.state
        # Lagging peers' sparse frames must stay decodable: windows advance
        # per node, so keep a few anchors instead of sync's single one.
        state.wire.anchor_history = Settings.ASYNC_ANCHOR_HISTORY
        if Settings.PRIVACY_SECAGG:
            # Pairwise masks need a round-scoped committee whose members all
            # fold into ONE sum; async windows fold dynamic, per-node
            # subsets, so mask cancellation has no place to happen. This is
            # Papaya's production split exactly: buffered async aggregation
            # pairs with CLIENT-side DP (clipping + noise at the sender,
            # which this scheduler keeps — the budget ledger and epsilon
            # digest ride every async fit), while committee masking runs on
            # the sync scheduler. Warn once, proceed unmasked.
            log.warning(
                "%s: PRIVACY_SECAGG is sync-only — async windows run the DP "
                "half of the privacy plane (clipping-at-sender + noise + "
                "budget ledger), contributions ride the wire unmasked",
                node.addr,
            )
        if (state.round or 0) > 0:
            # Mid-experiment joiner: wait for the catch-up model.
            deadline = time.time() + Settings.VOTE_TIMEOUT
            while not state.model_initialized_event.wait(timeout=0.5):
                if check_early_stop(node):
                    return None
                if time.time() >= deadline:
                    log.warning(
                        "%s: async catch-up wait timed out — joining with "
                        "local weights", node.addr,
                    )
                    state.model_initialized_event.set()
                    break
            if state.wire.anchor_round < (state.round or 0):
                # Catch-up resyncs the anchor; on the timeout path (or a
                # rejoiner that kept its model) anchor the local weights.
                state.wire.set_anchor(
                    node.learner.get_model().get_parameters(), state.round or 0
                )
            node.protocol.flight_recorder.record(
                "membership", event="join", window=state.round
            )
        else:
            if not establish_initial_model(node):
                return None
        return AsyncWindowStage


class AsyncWindowStage(Stage):
    """One async window: train, broadcast the contribution, fold what
    arrived, adopt the staleness-weighted aggregate. No barrier: the fill
    target shrinks live as peers die, a timeout bounds the worst case, and
    the window completes on the own contribution alone when every trainer
    is gone."""

    name = "AsyncWindowStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        from p2pfl_tpu.management.profiler import device_trace_window
        from p2pfl_tpu.stages.recovery import (
            apply_pending_reconcile,
            park_until_quorum,
        )

        state = node.state
        agg = node.async_agg
        if agg is None:  # stopped under our feet
            return None
        # Quorum-aware degraded mode: below the live-peer quorum, park
        # between windows (state journaled, heartbeats + heal probes keep
        # running) instead of closing empty windows on the timeout.
        if not park_until_quorum(node):
            return None
        # Partition-heal catch-up: adopt the ahead side's generation and
        # fast-forward the window counter, then run this window from the
        # fresh model — no committee bookkeeping to skip in async mode, and
        # both halves' in-flight contributions keep folding through the
        # staleness-weighted buffer.
        apply_pending_reconcile(node)
        w = state.round or 0
        t0 = time.perf_counter()
        agg.open_window(w)
        solicit, _ = select_participants(node)
        # Trajectory ledger: async windows are the scheduler's rounds — the
        # fill target's peer set is the closest analogue of a committee.
        LEDGERS.emit(
            node.addr, "window_open", round=w,
            members=sorted(solicit + [node.addr]),
        )

        with TRACER.span("fit", node=node.addr, round=w):
            with device_trace_window(Settings.PERF_TRACE_DIR, label="fit"):
                node.learner.fit()
        if check_early_stop(node):
            return None

        # Snapshot COPY (same race rationale as the sync TrainStage): the
        # live handle mutates when a window aggregate or catch-up lands.
        live = node.learner.get_model()
        own = live.build_copy(
            params=live.get_parameters(),
            contributors=[node.addr],
            num_samples=live.get_num_samples(),
        )
        agg.fold(own, w, node.addr)

        # One frame for every solicited peer: sparse delta against this
        # window's anchor when the codec is active (the async wire gets the
        # same int8/int4-quantized, coalesced codec as sync partials — a
        # laggard's window may already be retired into the anchor history,
        # which encode_tagged serves statelessly), dense otherwise.
        tagged = state.wire.encode_tagged(own, w)
        if tagged is None:
            payload, codec = own.encode_parameters(), "dense"
        else:
            payload, codec = tagged
        env = node.protocol.build_weights(
            AsyncContributionCommand.get_name(),
            w,
            payload,
            [node.addr],
            own.get_num_samples(),
            codec=codec,
        )
        with TRACER.span("diffuse:async_model", node=node.addr, round=w):
            node.protocol.broadcast(env, node_list=solicit)

        def fill_target() -> int:
            # Re-evaluated on every wake: live membership minus suspects and
            # stragglers, capped at the buffer size. Peer deaths and joins
            # move it between waits (death callbacks call agg.notify()).
            _, countable = select_participants(node)
            return min(Settings.ASYNC_BUFFER_K, 1 + len(countable))

        with TRACER.span("async_window_wait", node=node.addr, round=w):
            aggregated = agg.wait_window(
                fill_target,
                Settings.ASYNC_WINDOW_TIMEOUT,
                early_stop_fn=lambda: check_early_stop(node),
            )
        if aggregated is None:
            return None
        # Zero-duration marker span carrying the window's close diagnosis —
        # the critical-path analyzer's window report reads these for the
        # close-reason breakdown and the staleness-discount attribution
        # (span args ride the chrome export, so offline merges see them too).
        with TRACER.span(
            "window_close", node=node.addr, round=w,
            reason=agg.last_close_reason, mean_lag=round(agg.last_mean_lag, 4),
            fill=agg.last_fill,
        ):
            pass

        if LEDGERS.enabled():
            LEDGERS.get(node.addr).emit(
                "aggregate_committed",
                round=w,
                dedup_key=("commit", w),
                hash=canonical_params_hash(aggregated.params),
                contributors=sorted(aggregated.contributors),
                num_samples=aggregated.get_num_samples(),
                origin="window",
                reason=agg.last_close_reason,
            )
        model = node.learner.get_model()
        model.set_parameters(aggregated.params)
        model.set_contribution(aggregated.contributors, aggregated.get_num_samples())
        model.additional_info.update(aggregated.additional_info)
        # A later full-model frame for this window is redundant (first wins,
        # same contract as the sync TrainStage).
        state.note_full_model_round(w)
        _WINDOW_SECONDS.labels(node.addr).observe(time.perf_counter() - t0)
        return AsyncWindowFinishedStage


class AsyncWindowFinishedStage(Stage):
    """Close the window; loop or finish. The next window's delta anchor is
    this window's adopted aggregate — peers that folded a different subset
    drift by epsilon, which the codec's fingerprint-tolerant anchor matching
    absorbs (comm/delta.py module docstring)."""

    name = "AsyncWindowFinishedStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        state = node.state
        if check_early_stop(node):
            return None
        finished = state.round or 0
        node.log_metric(
            "wire_tx_bytes", float(node.protocol.gossiper.bytes_for_round(finished))
        )
        if node.async_agg is not None:
            node.log_metric(
                "async_window_staleness", float(node.async_agg.last_mean_lag)
            )
        LEDGERS.emit(node.addr, "window_close", round=finished)
        state.increase_round()
        state.wire.set_anchor(
            node.learner.get_model().get_parameters(), state.round or 0
        )
        node.log_round_finished()

        r, total = state.round, state.total_rounds
        if r is not None and total is not None and r < total:
            return AsyncWindowStage

        # Tell the fleet this node's contribution stream is over, so no
        # peer's fill target ever waits on it again (last-node-standing:
        # without this the stragglers burn a window timeout per window once
        # the fast cohort goes home).
        node.protocol.broadcast(
            node.protocol.build_msg(AsyncDoneCommand.get_name(), round=r or 0)
        )
        TrainStage._evaluate_and_broadcast(node)
        node.finish_learning()
        return None
