"""The while-loop stage machine (reference p2pfl/stages/workflows.py:28-58):
run stage -> next stage class -> repeat until None; record history for
test assertions (reference test/node_test.py:114-120).

Telemetry: the whole run executes inside an ``experiment`` root span whose
trace id is shared federation-wide (the initiator mints it; peers adopt it
from the start_learning frame — see ``Node.set_start_learning``), and every
stage executes inside a child span tagged with the round. Stage wall-clock
also feeds the ``p2pfl_stage_duration_seconds`` histogram, the per-stage
breakdown every perf PR reports through.
"""

from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, List, Optional, Type

from p2pfl_tpu.stages.stage import Stage
from p2pfl_tpu.telemetry import REGISTRY, TRACER
from p2pfl_tpu.telemetry.bundle import write_bundle

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.node import Node

log = logging.getLogger("p2pfl_tpu")

_STAGE_DURATION = REGISTRY.histogram(
    "p2pfl_stage_duration_seconds",
    "Wall-clock per stage execution",
    labels=("node", "stage"),
)


def scheduler_start_stage(mode: str) -> Type[Stage]:
    """Entry stage of a scheduler over the shared stage machine.

    Two schedulers exist (the ROADMAP refactor note): ``"sync"`` — barrier
    rounds (vote → train → aggregate → gossip, stages/base_node.py) — and
    ``"async"`` — elastic buffered windows with staleness weighting
    (stages/async_node.py). Both drive the same :class:`LearningWorkflow`
    while-loop; a scheduler is nothing but its start stage plus the
    transition graph its stages return."""
    if mode == "sync":
        from p2pfl_tpu.stages.base_node import StartLearningStage

        return StartLearningStage
    if mode == "async":
        from p2pfl_tpu.stages.async_node import AsyncStartStage

        return AsyncStartStage
    raise ValueError(f"unknown scheduler mode {mode!r} (expected 'sync' or 'async')")


class LearningWorkflow:
    def __init__(self, start_stage: Optional[Type[Stage]] = None) -> None:
        if start_stage is None:
            start_stage = scheduler_start_stage("sync")
        self.start_stage = start_stage
        self.history: List[str] = []

    def run(self, node: "Node") -> None:
        from p2pfl_tpu.exceptions import ProtocolNotStartedError

        stage: Optional[Type[Stage]] = self.start_stage
        exp = node.state.experiment
        try:
            with TRACER.span(
                "experiment",
                node=node.addr,
                trace_id=node.state.trace_id,  # None -> fresh trace
                experiment=exp.exp_name if exp is not None else None,
            ):
                recorder = node.protocol.flight_recorder
                while stage is not None:
                    self.history.append(stage.name)
                    log.debug("%s: stage %s", node.addr, stage.name)
                    name = stage.name
                    # Visible to the fleet: the next health digest carries
                    # the stage, and the transition lands in the postmortem
                    # ring — "where was node 5 when it stalled" is answerable.
                    node.state.current_stage = name
                    recorder.record("stage", stage=name, round=node.state.round)
                    t0 = time.perf_counter()
                    with TRACER.span(name, node=node.addr, round=node.state.round):
                        stage = stage.execute(node)
                    _STAGE_DURATION.labels(node.addr, name).observe(
                        time.perf_counter() - t0
                    )
        except StopIteration:
            log.info("%s: learning stopped early", node.addr)
        except ProtocolNotStartedError:
            # Node was stopped under our feet; treat as an early stop rather
            # than letting the exception escape the daemon thread.
            log.info("%s: protocol stopped mid-workflow — aborting learning", node.addr)
        except Exception as exc:
            log.exception("%s: workflow crashed", node.addr)
            # The failure the flight recorder exists for: dump the ring
            # before the daemon thread dies with the evidence.
            node.protocol.flight_recorder.record("workflow_crash")
            node.protocol.flight_recorder.dump("workflow_crash")
            # ...and the rest of the causal story with it: one evidence
            # bundle joining every run-matching stream (both schedulers
            # crash through this path).
            write_bundle(
                "workflow_crash",
                context={
                    "node": node.addr,
                    "stage": node.state.current_stage,
                    "round": node.state.round,
                },
                error=exc,
            )
            raise
        finally:
            node.state.current_stage = ""
