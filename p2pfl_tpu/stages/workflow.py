"""The while-loop stage machine (reference p2pfl/stages/workflows.py:28-58):
run stage -> next stage class -> repeat until None; record history for
test assertions (reference test/node_test.py:114-120)."""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, List, Optional, Type

from p2pfl_tpu.stages.stage import Stage

if TYPE_CHECKING:  # pragma: no cover
    from p2pfl_tpu.node import Node

log = logging.getLogger("p2pfl_tpu")


class LearningWorkflow:
    def __init__(self, start_stage: Optional[Type[Stage]] = None) -> None:
        if start_stage is None:
            from p2pfl_tpu.stages.base_node import StartLearningStage

            start_stage = StartLearningStage
        self.start_stage = start_stage
        self.history: List[str] = []

    def run(self, node: "Node") -> None:
        from p2pfl_tpu.exceptions import ProtocolNotStartedError

        stage: Optional[Type[Stage]] = self.start_stage
        try:
            while stage is not None:
                self.history.append(stage.name)
                log.debug("%s: stage %s", node.addr, stage.name)
                stage = stage.execute(node)
        except StopIteration:
            log.info("%s: learning stopped early", node.addr)
        except ProtocolNotStartedError:
            # Node was stopped under our feet; treat as an early stop rather
            # than letting the exception escape the daemon thread.
            log.info("%s: protocol stopped mid-workflow — aborting learning", node.addr)
        except Exception:
            log.exception("%s: workflow crashed", node.addr)
            raise
