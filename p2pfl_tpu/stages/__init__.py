"""Learning workflow: a stage-machine over federated rounds."""

from p2pfl_tpu.stages.stage import Stage, check_early_stop  # noqa: F401
from p2pfl_tpu.stages.workflow import LearningWorkflow  # noqa: F401
